"""Toy autoregressive LM + the DecodeServer executor contract.

A deliberately small single-layer transformer LM (embedding + learned
positions, one attention layer with residual, greedy argmax head) whose
decode step runs against the paged KV cache. It exists so the decode
serving stack — scheduler, cache, paged attention — has a real
autoregressive model to drive in tests and ``tools/bench_serving.py``
without hauling in a checkpoint; the executor contract is what a real
model would implement.

Executor contract (``make_step_fn``): the DecodeServer hands the step
function its flattened varlen batch —
``[tokens (T,), row_id (T,), positions (T,), valid (T,),
block_tables (R, W), ctx_lens (R,), last_idx (R,)]`` — and expects
``[next_tokens (T,), k_new (1, T, H, D), v_new (1, T, H, D)]`` back.
``next_tokens[t]`` is the greedy argmax of the logits AT flattened slot
``t`` (garbage on padded slots) — per-POSITION next tokens, so a
speculative-verify chunk accepts/rejects its whole draft from one step
while a plain step just reads its row's ``last_idx`` slot. The step
function only COMPUTES (attention reads cached KV through the block
tables; the chunk's own K/V is returned, not written) — the server
commits cache writes after the batch finishes, so failovers re-run
steps idempotently.

Pure-decode batches (every context-bearing row carries exactly one
token) route to :func:`~paddle_tpu.ops.pallas.paged_attention.
paged_decode_attention` (XLA gather or the Pallas kernel); uniform
multi-token extension batches — speculative-verify chunks, same-width
prefill chunks — repack to a rectangular (R, S) layout and route to the
same dispatcher's multi-query verify path; everything else takes the
ragged XLA path. All are jitted per bucketed shape, so the compiled set
closes with the server's bucket set.

:func:`dense_generate` is the oracle: same parameters, full dense
recompute each step, no cache — paged serving must reproduce its token
stream exactly.
"""
from __future__ import annotations

import math
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.pallas.paged_attention import (paged_decode_attention,
                                          paged_prefill_attention)

__all__ = ["init_decode_model", "make_step_fn", "dense_generate",
           "executor_family"]


def init_decode_model(vocab: int = 128, num_heads: int = 2,
                      head_dim: int = 32, max_len: int = 512,
                      seed: int = 0) -> Dict[str, np.ndarray]:
    """Parameters of the toy LM (embed dim = num_heads * head_dim)."""
    e = num_heads * head_dim
    rs = np.random.RandomState(seed)
    s = 1.0 / math.sqrt(e)
    return {
        "embed": rs.randn(vocab, e).astype(np.float32) * 0.5,
        "pos": rs.randn(max_len, e).astype(np.float32) * 0.1,
        "wq": rs.randn(e, e).astype(np.float32) * s,
        "wk": rs.randn(e, e).astype(np.float32) * s,
        "wv": rs.randn(e, e).astype(np.float32) * s,
        "wo": rs.randn(e, e).astype(np.float32) * s,
        "head": rs.randn(e, vocab).astype(np.float32) * s,
        "num_heads": np.int32(num_heads),
        "head_dim": np.int32(head_dim),
    }


def _qkv(params, x):
    h, d = int(params["num_heads"]), int(params["head_dim"])
    t = x.shape[0]
    q = (x @ params["wq"]).reshape(t, h, d)
    k = (x @ params["wk"]).reshape(t, h, d)
    v = (x @ params["wv"]).reshape(t, h, d)
    return q, k, v


def make_step_fn(params: Dict[str, np.ndarray], cache,
                 kernel: str = "auto", interpret: bool = False):
    """Build a DecodeServer executor over ``cache`` (single layer).

    kernel/interpret select the decode attention path
    (``paged_decode_attention``'s dispatcher); mixed batches always take
    the ragged XLA path.
    """
    if cache.num_layers != 1:
        raise ValueError("the toy decode model is single-layer")
    h, d = int(params["num_heads"]), int(params["head_dim"])
    e = h * d
    emb = jnp.asarray(params["embed"])
    pos = jnp.asarray(params["pos"])

    max_len = int(np.asarray(params["pos"]).shape[0])

    @jax.jit
    def _mixed(kp, vp, tokens, row_id, positions, valid, tables,
               ctx_lens, last_idx):
        del last_idx
        x = emb[tokens] + pos[positions]                    # (T, E)
        q, k, v = _qkv(params, x)
        o = paged_prefill_attention(q, k, v, row_id, positions, valid,
                                    kp, vp, tables, ctx_lens)
        y = x + o.reshape(-1, e) @ params["wo"]
        nxt = jnp.argmax(y @ params["head"],
                         axis=-1).astype(jnp.int32)         # (T,)
        return nxt, k[None], v[None]

    @jax.jit
    def _decode(kp, vp, tokens, row_id, positions, valid, tables,
                ctx_lens, last_idx):
        t_b = tokens.shape[0]
        tok = tokens[last_idx]                              # (R,)
        x = emb[tok] + pos[positions[last_idx]]             # (R, E)
        q, k, v = _qkv(params, x)
        o = paged_decode_attention(
            q[:, None], kp, vp, tables, ctx_lens,
            k_new=k[:, None], v_new=v[:, None],
            kernel=kernel, interpret=interpret)             # (R, 1, H, D)
        y = x + o[:, 0].reshape(-1, e) @ params["wo"]
        nxt = jnp.argmax(y @ params["head"], axis=-1).astype(jnp.int32)
        # scatter each row's next token and K/V back to its flattened
        # token slot; padded rows (ctx_lens == 0) are routed out of
        # bounds + dropped so they cannot clobber slot 0
        idx = jnp.where(ctx_lens > 0, last_idx, t_b)
        nxt_flat = jnp.zeros(t_b, jnp.int32).at[idx].set(nxt, mode="drop")
        k_flat = jnp.zeros((t_b, h, d), k.dtype).at[idx].set(k, mode="drop")
        v_flat = jnp.zeros((t_b, h, d), v.dtype).at[idx].set(v, mode="drop")
        return nxt_flat, k_flat[None], v_flat[None]

    @jax.jit
    def _verify(kp, vp, tok2d, n_tok, tables, ctx_lens):
        """Rectangular extension batch: row i's ``n_tok[i]`` chunk
        tokens sit at positions ``ctx_lens[i] + col``. Used for
        speculative-verify chunks (and any uniform-width prefill), where
        every chunk token needs its own next-token argmax."""
        r_b, s_b = tok2d.shape
        cols = jnp.arange(s_b, dtype=jnp.int32)
        posn = ctx_lens.astype(jnp.int32)[:, None] + cols[None, :]
        posn = jnp.clip(posn, 0, max_len - 1)               # pad cols only
        x = emb[tok2d] + pos[posn]                          # (R, S, E)
        xf = x.reshape(r_b * s_b, e)
        q, k, v = _qkv(params, xf)
        q4 = q.reshape(r_b, s_b, h, d)
        k4 = k.reshape(r_b, s_b, h, d)
        v4 = v.reshape(r_b, s_b, h, d)
        o = paged_decode_attention(
            q4, kp, vp, tables, ctx_lens, k_new=k4, v_new=v4,
            kernel=kernel, interpret=interpret)             # (R, S, H, D)
        y = x + o.reshape(r_b, s_b, e) @ params["wo"]
        nxt = jnp.argmax(y @ params["head"],
                         axis=-1).astype(jnp.int32)         # (R, S)
        return nxt, k4, v4

    def step(arrays: List[np.ndarray]) -> List[np.ndarray]:
        tokens, row_id, positions, valid, tables, ctx_lens, last_idx = \
            [np.asarray(a) for a in arrays]
        kp, vp = cache.pools(0)
        t_b = tokens.shape[0]
        r_b = ctx_lens.shape[0]
        # pure decode <=> every valid token belongs to a row that already
        # has context and carries exactly one token (semantically: each
        # such row computes a single next position)
        n_valid = int(valid.sum())
        real_rows = int((ctx_lens > 0).sum())
        if n_valid > 0 and n_valid == real_rows:
            nxt, k_new, v_new = _decode(kp, vp, tokens, row_id, positions,
                                        valid, tables, ctx_lens, last_idx)
            return [np.asarray(nxt), np.asarray(k_new), np.asarray(v_new)]
        # uniform multi-token extension (speculative verify, same-width
        # prefill): repack rectangular and take the multi-query kernel
        # path. S is bucketed to a power of two so the compiled set
        # stays closed alongside the server's token buckets.
        counts = np.bincount(row_id[valid > 0], minlength=r_b)
        live = counts[counts > 0]
        if live.size and live.min() == live.max() and live[0] > 1:
            s = int(live[0])
            s_b = 1 << (s - 1).bit_length()
            tok2d = np.zeros((r_b, s_b), np.int32)
            n_tok = np.zeros(r_b, np.int32)
            offs = []
            off = 0
            for i in range(r_b):
                if counts[i] == 0:
                    offs.append(None)
                    continue
                tok2d[i, :s] = tokens[off:off + s]
                n_tok[i] = s
                offs.append(off)
                off += s
            nxt2d, k2d, v2d = [np.asarray(o) for o in _verify(
                kp, vp, tok2d, n_tok, tables, ctx_lens)]
            nxt = np.zeros(t_b, np.int32)
            k_new = np.zeros((t_b, h, d), k2d.dtype)
            v_new = np.zeros((t_b, h, d), v2d.dtype)
            for i, off in enumerate(offs):
                if off is None:
                    continue
                nxt[off:off + s] = nxt2d[i, :s]
                k_new[off:off + s] = k2d[i, :s]
                v_new[off:off + s] = v2d[i, :s]
            return [nxt, k_new[None], v_new[None]]
        nxt, k_new, v_new = _mixed(kp, vp, tokens, row_id, positions,
                                   valid, tables, ctx_lens, last_idx)
        return [np.asarray(nxt), np.asarray(k_new), np.asarray(v_new)]

    # exposed so harnesses (tools/bench_serving.py) can measure the
    # compiled-shape set directly via _cache_size()
    step.jit_fns = (_mixed, _decode, _verify)
    return step


def executor_family(step, arg_specs, mesh=None, name="decode-executor"):
    """The mixed/decode/verify executor router as a declared
    :class:`~paddle_tpu.analysis.schedule.ProgramFamily`.

    The server picks which compiled program runs from the BATCH
    COMPOSITION (prefill rows present / pure decode / speculative-verify
    chunks) — a host decision every rank makes identically for a given
    dispatched batch, so the members' schedules may legitimately
    diverge. ``arg_specs`` maps member name to the abstract argument
    tuple (``jax.ShapeDtypeStruct``) each executor fn is traced with;
    members absent from it are skipped.
    """
    import jax as _jax

    from ..analysis.schedule import ProgramFamily

    fns = dict(zip(("mixed", "decode", "verify"), step.jit_fns))
    members = {}
    for member, fn in fns.items():
        if member not in arg_specs:
            continue
        spec = tuple(arg_specs[member])
        members[member] = (
            lambda f=fn, a=spec: _jax.make_jaxpr(lambda *xs: f(*xs))(*a))
    return ProgramFamily(
        name=name,
        selector="batch composition bucket (has-prefill / pure-decode / "
                 "has-verify rows), host-uniform per dispatched batch",
        rank_invariant=True,
        members=members,
        mesh=mesh)


def dense_generate(params: Dict[str, np.ndarray], prompt_tokens,
                   max_new: int) -> List[int]:
    """Greedy-decode oracle: full dense recompute per step, no cache.
    The paged serving stack must emit exactly this token stream."""
    h, d = int(params["num_heads"]), int(params["head_dim"])
    e = h * d
    emb = jnp.asarray(params["embed"])
    pos = jnp.asarray(params["pos"])
    toks = [int(t) for t in prompt_tokens]
    out: List[int] = []
    scale = 1.0 / math.sqrt(d)
    for _ in range(int(max_new)):
        t = len(toks)
        x = emb[jnp.asarray(toks, jnp.int32)] + pos[:t]
        q, k, v = _qkv(params, x)
        s = jnp.einsum("thd,uhd->htu", q.astype(jnp.float32) * scale,
                       k.astype(jnp.float32))
        causal = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(causal[None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("htu,uhd->thd", p, v.astype(jnp.float32))
        y = x + o.reshape(t, e) @ params["wo"]
        nxt = int(jnp.argmax(y[-1] @ params["head"]))
        toks.append(nxt)
        out.append(nxt)
    return out
