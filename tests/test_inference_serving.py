"""Serving runtime (paddle_tpu/inference/serving.py): admission control,
continuous batching over shape buckets, deadline handling, replica
failover, drain — every path driven deterministically with gate-blocked
fake executors (the Predictor e2e at the bottom is the only jax user).

The load-bearing invariant asserted throughout: every submitted request
terminates in exactly ONE of completed / shed / expired / failed
(``InferenceServer.accounted``) — overload and failover may shed, but
never silently lose, an accepted request.
"""
import signal
import threading
import time

import numpy as np
import pytest

from paddle_tpu import telemetry
from paddle_tpu.inference import serving
from paddle_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.reset()


def wait_until(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {msg}")


def double_fn(arrays):
    return [np.asarray(a) * 2.0 for a in arrays]


class Gate:
    """Executor that blocks every batch until released — makes queue /
    backlog states reachable deterministically."""

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()
        self.calls = 0
        self._lock = threading.Lock()

    def fn(self, arrays):
        with self._lock:
            self.calls += 1
        self.entered.set()
        assert self.release.wait(30), "gate never released"
        return double_fn(arrays)


def rows(n, dim=2, seed=0):
    return [np.random.RandomState(seed).rand(n, dim).astype("float32")]


# -- admission + completion ---------------------------------------------------

def test_submit_complete_roundtrip():
    with serving.InferenceServer(double_fn, replicas=2) as srv:
        reqs = [srv.submit(rows(n, seed=n)) for n in (1, 2, 3)]
        for n, r in zip((1, 2, 3), reqs):
            out = r.result(timeout=10)
            assert out[0].shape == (n, 2)
            np.testing.assert_allclose(out[0], rows(n, seed=n)[0] * 2.0,
                                       rtol=1e-6)
            assert r.latency is not None and r.latency >= 0
        s = srv.stats()
        assert s["submitted"] == 3 and s["completed"] == 3
        assert s["shed"] == s["expired"] == s["failed"] == 0
        assert srv.accounted()


def test_submit_input_validation():
    with pytest.raises(ValueError):
        serving.Request([])
    with pytest.raises(ValueError):
        serving.Request([np.zeros((2, 3)), np.zeros((3, 3))])  # row mismatch
    with pytest.raises(ValueError):
        serving.InferenceServer([])


def test_request_signature_and_tokens():
    r = serving.Request([np.zeros((2, 3), "float32"),
                         np.zeros((2, 5), "int32")], tokens=17)
    assert r.rows == 2 and r.tokens == 17
    same = serving.Request([np.ones((4, 3), "float32"),
                            np.ones((4, 5), "int32")])
    assert r.signature() == same.signature()
    assert same.tokens == 4  # defaults to rows
    other = serving.Request([np.zeros((2, 4), "float32")])
    assert r.signature() != other.signature()


# -- batching + shape buckets -------------------------------------------------

def test_coalescing_padding_and_bucket_closure():
    """Backlogged same-signature requests coalesce into one padded
    bucketed batch; once every bucket of a signature is seen, further
    traffic causes ZERO new recompiles (the closed-bucket-set claim)."""
    gate = Gate()
    cfg = serving.ServingConfig(max_batch=4, batch_wait_s=0.005,
                                call_timeout_s=60.0)
    with serving.InferenceServer(gate.fn, replicas=1, config=cfg) as srv:
        # wedge the pipeline: one batch executing (gate), two distinct-
        # signature batches in the replica's pending slots, one parked
        # in the dispatcher — new work can only accumulate in the deque
        blockers = [srv.submit(rows(1, dim=d)) for d in (9, 5, 7)]
        wait_until(gate.entered.is_set, msg="blocker executing")
        wait_until(lambda: srv.replicas[0].pending() == 2,
                   msg="pending slots full")
        blockers.append(srv.submit(rows(1, dim=11)))
        wait_until(lambda: srv.stats()["queue_depth"] == 0,
                   msg="dispatcher parked")
        # backlog 6 same-signature single-row requests while wedged
        reqs = [srv.submit(rows(1, seed=i)) for i in range(6)]
        assert srv.stats()["queue_depth"] == 6
        gate.release.set()
        outs = [r.result(timeout=10) for r in reqs]
        for b in blockers:
            b.result(timeout=10)
        for i, o in enumerate(outs):
            np.testing.assert_allclose(o[0], rows(1, seed=i)[0] * 2.0,
                                       rtol=1e-6)
        s = srv.stats()
        # the 6 backlogged requests coalesced into exactly 2 padded
        # batches (4-row bucket + 2-row bucket) after the 4 blockers
        assert s["batches"] == 4 + 2
        assert s["recompiles"] == len(srv._seen_shapes)
        # warm the remaining buckets of the dim-2 signature, then prove
        # the compiled set is CLOSED under more traffic
        for n in (1, 2, 4):
            srv.submit(rows(n)).result(timeout=10)
        warm = srv.stats()["recompiles"]
        more = [srv.submit(rows(1 + (i % 4), seed=i)) for i in range(12)]
        for r in more:
            r.result(timeout=10)
        assert srv.stats()["recompiles"] == warm
        assert srv.accounted()


def test_padded_batch_slices_per_request_rows():
    """A 3-row batch pads to bucket 4; each request gets exactly its own
    rows back (the pad rows never leak into results)."""
    seen = []

    def spy(arrays):
        seen.append(tuple(a.shape for a in arrays))
        return double_fn(arrays)

    cfg = serving.ServingConfig(max_batch=4, batch_wait_s=0.01,
                                call_timeout_s=60.0)
    with serving.InferenceServer(spy, replicas=1, config=cfg) as srv:
        a = srv.submit(rows(1, seed=1))
        b = srv.submit(rows(2, seed=2))
        np.testing.assert_allclose(a.result(10)[0],
                                   rows(1, seed=1)[0] * 2.0, rtol=1e-6)
        np.testing.assert_allclose(b.result(10)[0],
                                   rows(2, seed=2)[0] * 2.0, rtol=1e-6)
        # every executed array was padded to a power-of-two bucket
        assert all(shape[0][0] in (1, 2, 4) for shape in seen)


# -- shedding -----------------------------------------------------------------

def _backlogged_server(gate, max_queue=2):
    """1 replica, max_batch=1: gate-block the replica, fill its pending
    slots, park one batch in the dispatcher — the admission deque is now
    the only place left for new work."""
    cfg = serving.ServingConfig(max_queue=max_queue, max_batch=1,
                                batch_wait_s=0.001, call_timeout_s=60.0)
    srv = serving.InferenceServer(gate.fn, replicas=1, config=cfg).start()
    srv.submit(rows(1))                       # executing, holds the gate
    wait_until(gate.entered.is_set, msg="executor entered")
    srv.submit(rows(1))
    srv.submit(rows(1))
    wait_until(lambda: srv.replicas[0].pending() == 2,
               msg="replica pending slots full")
    parked = srv.submit(rows(1))              # batcher parks in dispatch
    wait_until(lambda: srv.stats()["queue_depth"] == 0,
               msg="dispatcher holds the parked batch")
    return srv, parked


def test_queue_full_shed():
    gate = Gate()
    srv, _ = _backlogged_server(gate, max_queue=2)
    try:
        srv.submit(rows(1))
        srv.submit(rows(1))                   # deque now at max_queue
        rejected = srv.submit(rows(1))
        assert rejected.done() and rejected.state == "shed"
        with pytest.raises(serving.RequestShed) as ei:
            rejected.result(timeout=1)
        assert ei.value.cause == "queue_full"
        gate.release.set()
        wait_until(srv.accounted, msg="all requests terminal")
        assert srv.stats()["shed_causes"]["queue_full"] == 1
    finally:
        gate.release.set()
        srv.shutdown(drain=True, timeout=10)


def test_deadline_infeasible_shed_uses_healthy_replica_count():
    with serving.InferenceServer(double_fn, replicas=2) as srv:
        # prime the service model: 1 row/s per replica, 5 s batch latency
        with srv._cv:
            srv._ewma_rows_per_s = 1.0
            srv._ewma_batch_s = 5.0
        assert srv.modeled_wait(1) == pytest.approx(0.5 + 5.0)
        hopeless = srv.submit(rows(1), deadline_s=0.5)
        assert hopeless.state == "shed"
        with pytest.raises(serving.RequestShed) as ei:
            hopeless.result(timeout=1)
        assert ei.value.cause == "deadline_infeasible"
        # a feasible deadline is admitted and completes
        ok = srv.submit(rows(1), deadline_s=60.0)
        assert ok.result(timeout=10)[0].shape == (1, 2)
        # benching a replica halves the modeled drain rate
        srv.replicas[1].healthy = False
        with srv._cv:
            srv._ewma_rows_per_s, srv._ewma_batch_s = 1.0, 5.0
        assert srv.modeled_wait(1) == pytest.approx(1.0 + 5.0)
        srv.replicas[1].healthy = True
        assert srv.accounted()


def test_deadline_expired_in_queue():
    gate = Gate()
    srv, _ = _backlogged_server(gate)
    try:
        doomed = srv.submit(rows(1), deadline_s=0.15)
        # admitted: the EWMA is cold (no completed batch yet), and a
        # blind admission model cannot reject
        assert doomed.state == "pending"
        with pytest.raises(serving.DeadlineExpired):
            doomed.result(timeout=10)
        assert doomed.cause == "deadline_expired_in_queue"
        gate.release.set()
        wait_until(srv.accounted, msg="all requests terminal")
        assert srv.stats()["expired"] == 1
    finally:
        gate.release.set()
        srv.shutdown(drain=True, timeout=10)


# -- failover -----------------------------------------------------------------

def test_replica_stall_failover_zero_loss():
    """An injected wedged device call: the per-call deadline fires, the
    wedged worker is abandoned (fresh generation), the batch requeues to
    the survivor, and every request still completes."""
    cfg = serving.ServingConfig(max_batch=4, call_timeout_s=0.15,
                                probation_base_s=0.02,
                                probation_max_s=0.2, seed=3)
    with serving.InferenceServer(double_fn, replicas=2, config=cfg) as srv:
        with faults.inject("replica_stall", at_step=1) as spec:
            reqs = [srv.submit(rows(1, seed=i)) for i in range(4)]
            for i, r in enumerate(reqs):
                np.testing.assert_allclose(r.result(timeout=20)[0],
                                           rows(1, seed=i)[0] * 2.0,
                                           rtol=1e-6)
        assert spec.fired == 1
        s = srv.stats()
        assert s["failovers"] >= 1
        assert s["requeues"] >= 1
        assert s["completed"] == 4 and s["failed"] == 0
        assert srv.accounted()
        # the benched replica re-admits after probation — the probe
        # happens at dispatch, so keep traffic flowing while polling
        deadline = time.monotonic() + 10.0
        while (srv.stats()["replicas_healthy"] < 2
               and time.monotonic() < deadline):
            srv.submit(rows(1)).result(timeout=20)
            time.sleep(0.01)
        assert srv.stats()["replicas_healthy"] == 2


def test_serving_io_failover():
    """An injected executor IOError fails the batch over to the other
    replica (no respawn needed — the worker survived the exception)."""
    cfg = serving.ServingConfig(call_timeout_s=5.0, probation_base_s=0.02,
                                probation_max_s=0.2)
    with serving.InferenceServer(double_fn, replicas=2, config=cfg) as srv:
        with faults.inject("serving_io", at_step=1) as spec:
            r = srv.submit(rows(2))
            np.testing.assert_allclose(r.result(timeout=20)[0],
                                       rows(2)[0] * 2.0, rtol=1e-6)
        assert spec.fired == 1
        s = srv.stats()
        assert s["failovers"] >= 1 and s["failed"] == 0
        assert srv.accounted()


def test_terminal_failure_after_max_attempts():
    """With every replica broken, a deadline-less request must not
    bounce forever: the attempts cap seals it FAILED (still accounted)."""

    def broken(arrays):
        raise RuntimeError("boom")

    cfg = serving.ServingConfig(call_timeout_s=5.0, max_attempts=2,
                                probation_base_s=0.005,
                                probation_max_s=0.02)
    with serving.InferenceServer(broken, replicas=1, config=cfg) as srv:
        r = srv.submit(rows(1))
        with pytest.raises(serving.ServingError,
                           match="after 2 dispatch attempts"):
            r.result(timeout=30)
        s = srv.stats()
        assert s["failed"] == 1 and s["completed"] == 0
        assert s["failovers"] >= 1
        assert srv.accounted()


# -- drain --------------------------------------------------------------------

def test_shutdown_drain_then_shed_draining():
    srv = serving.InferenceServer(double_fn, replicas=1).start()
    reqs = [srv.submit(rows(1, seed=i)) for i in range(3)]
    srv.shutdown(drain=True, timeout=10)
    for r in reqs:  # accepted work finished before stopping
        assert r.state == "completed"
    late = srv.submit(rows(1))
    with pytest.raises(serving.RequestShed) as ei:
        late.result(timeout=1)
    assert ei.value.cause == "draining"
    assert srv.stats()["shed_causes"]["draining"] == 1
    assert srv.accounted()


def test_sigterm_triggers_drain():
    import os
    prev = signal.getsignal(signal.SIGTERM)
    # earlier tests can leave a process-global SIGTERM handler behind
    # (fleet/elastic raises SystemExit) — pin a benign one so the chain
    # install_sigterm_drain builds on top of it is inert here
    signal.signal(signal.SIGTERM, lambda *a: None)
    srv = serving.InferenceServer(double_fn, replicas=1).start()
    try:
        srv.install_sigterm_drain()
        assert signal.getsignal(signal.SIGTERM) is not prev
        done = srv.submit(rows(1))
        done.result(timeout=10)
        os.kill(os.getpid(), signal.SIGTERM)
        wait_until(lambda: srv.draining, timeout=5, msg="drain flag")
        wait_until(lambda: srv._stopped, timeout=10, msg="server stopped")
        late = srv.submit(rows(1))
        assert late.state == "shed" and late.cause == "draining"
        assert srv.accounted()
    finally:
        signal.signal(signal.SIGTERM, prev)
        srv.shutdown(drain=False, timeout=5)


# -- telemetry ----------------------------------------------------------------

def test_serving_telemetry_series():
    with telemetry.scope(profile=False) as sc:
        cfg = serving.ServingConfig(max_batch=2)
        with serving.InferenceServer(double_fn, replicas=1,
                                     config=cfg) as srv:
            for i in range(4):
                srv.submit(rows(1, seed=i)).result(timeout=10)
            hopeless = srv.submit(rows(1), deadline_s=-1.0)
            assert hopeless.state in ("shed", "expired")
        text = sc.prometheus_text()
    for series in ("serving_requests_total", "serving_batches_total",
                   "serving_recompiles_total", "serving_queue_wait_seconds",
                   "serving_e2e_seconds", "serving_replicas_healthy"):
        assert series in text, f"missing {series} in exposition"
    assert 'outcome="completed"' in text


# -- Predictor-backed end-to-end ---------------------------------------------

def test_predictor_e2e_shape_polymorphic(tmp_path):
    """from_config over a jit.saved shape-polymorphic model: varying row
    counts serve correctly through padding/slicing, and the bucket set
    closes after warmup."""
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn
    from paddle_tpu.jit import InputSpec

    paddle.seed(11)
    net = nn.Linear(6, 3)
    net.eval()
    prefix = str(tmp_path / "model")
    paddle.jit.save(net, prefix,
                    input_spec=[InputSpec([None, 6], "float32")])
    cfg = inference.Config(prefix)
    direct = inference.create_predictor(cfg)
    scfg = serving.ServingConfig(max_batch=4, call_timeout_s=30.0)
    with serving.InferenceServer.from_config(cfg, replicas=2,
                                             serving=scfg) as srv:
        for n in (1, 2, 4):  # warm every bucket
            srv.submit(rows(n, dim=6)).result(timeout=30)
        warm = srv.stats()["recompiles"]
        xs = [np.random.RandomState(i).rand(1 + i % 4, 6).astype("float32")
              for i in range(10)]
        reqs = [srv.submit([x]) for x in xs]
        for x, r in zip(xs, reqs):
            np.testing.assert_allclose(r.result(timeout=30)[0],
                                       direct.run([x])[0], rtol=1e-5)
        assert srv.stats()["recompiles"] == warm
        assert srv.accounted()
