"""Process/env discovery (reference: fleet/base/role_maker.py env parsing +
distributed/parallel.py init_parallel_env).

On TPU, rank/world-size come from jax.distributed / jax.process_index rather
than PADDLE_TRAINER_* env vars; the env vars are still honored for
subprocess-simulated tests (SURVEY.md §4 TestDistBase translation).
"""
from __future__ import annotations

import os

import jax


def get_rank() -> int:
    if "PADDLE_TRAINER_ID" in os.environ:
        return int(os.environ["PADDLE_TRAINER_ID"])
    try:
        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    if "PADDLE_TRAINERS_NUM" in os.environ:
        return int(os.environ["PADDLE_TRAINERS_NUM"])
    try:
        return jax.process_count()
    except Exception:
        return 1


class ParallelEnv:
    """reference: fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_RANK_IN_NODE", get_rank()))

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def nranks(self):
        return get_world_size()

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else ["127.0.0.1:6170"]


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """reference: distributed/parallel.py:58 init_parallel_env.

    Multi-host TPU: jax.distributed.initialize discovers pod topology from
    TPU metadata (replacing the reference's TCP ncclUniqueId broadcast,
    gen_comm_id_helper.cc:297).
    """
    if get_world_size() > 1 or coordinator_address is not None:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        except (RuntimeError, ValueError):
            pass  # already initialized or single-process
    return ParallelEnv()
