"""Tests for API-parity additions: inplace-variant ops, TensorArray ops,
misc tensor fns (add_n/diagonal/rank/shard_index), top-level compat aliases
(Places, rng state, batch reader decorator).

Reference surfaces covered: python/paddle/__init__.py top-level exports,
python/paddle/tensor/{math,manipulation,array}.py, python/paddle/batch.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_inplace_variants_return_results():
    x = paddle.to_tensor(np.array([1.0, 4.0, 9.0], dtype="float32"))
    np.testing.assert_allclose(np.asarray(paddle.sqrt_(x)), [1, 2, 3])
    np.testing.assert_allclose(np.asarray(paddle.rsqrt_(x)), [1, 0.5, 1 / 3],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(paddle.add_(x, x)), [2, 8, 18])
    np.testing.assert_allclose(np.asarray(paddle.clip_(x, 2.0, 5.0)), [2, 4, 5])
    np.testing.assert_allclose(np.asarray(paddle.scale_(x, 2.0, 1.0)),
                               [3, 9, 19])
    assert paddle.reshape_(x, [3, 1]).shape == (3, 1)
    assert paddle.unsqueeze_(x, 0).shape == (1, 3)
    assert paddle.squeeze_(paddle.unsqueeze_(x, 0)).shape == (3,)
    assert paddle.flatten_(paddle.ones([2, 3])).shape == (6,)
    np.testing.assert_allclose(np.asarray(paddle.tensor.zero_(x)), [0, 0, 0])


def test_tensor_array_ops():
    arr = paddle.create_array()
    x = paddle.ones([2])
    paddle.tensor.array_write(x, 3, arr)
    assert paddle.tensor.array_length(arr) == 4
    got = paddle.tensor.array_read(arr, 3)
    np.testing.assert_allclose(np.asarray(got), [1, 1])


def test_add_n_diagonal_rank_reverse():
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    s = paddle.add_n([x, x, x])
    np.testing.assert_allclose(np.asarray(s), 3 * np.arange(6).reshape(2, 3))
    np.testing.assert_allclose(np.asarray(paddle.diagonal(x)), [0, 4])
    assert int(paddle.rank(x)) == 2
    np.testing.assert_allclose(np.asarray(paddle.tensor.reverse(x, [1]))[0],
                               [2, 1, 0])


def test_shard_index():
    idx = paddle.to_tensor(np.array([1, 5, 9], dtype="int64"))
    # 10 indices over 2 shards -> shard_size 5; shard 0 owns [0,5)
    out = np.asarray(paddle.shard_index(idx, 10, 2, 0))
    np.testing.assert_array_equal(out, [1, -1, -1])
    out1 = np.asarray(paddle.shard_index(idx, 10, 2, 1))
    np.testing.assert_array_equal(out1, [-1, 0, 4])
    with pytest.raises(ValueError):
        paddle.shard_index(idx, 10, 2, 7)


def test_places_and_compat_aliases():
    assert paddle.CPUPlace() == paddle.CPUPlace()
    assert paddle.CUDAPlace(0).get_device_id() == 0
    paddle.XPUPlace(0), paddle.NPUPlace(0), paddle.CUDAPinnedPlace()
    assert paddle.VarBase is paddle.Tensor
    assert paddle.get_cudnn_version() is None
    assert not paddle.is_compiled_with_rocm()
    paddle.monkey_patch_math_varbase()
    paddle.monkey_patch_variable()


def test_static_mode_flag_roundtrip():
    assert paddle.in_dygraph_mode()
    paddle.enable_static()
    try:
        assert not paddle.in_dygraph_mode()
    finally:
        paddle.disable_static()
    assert paddle.in_dygraph_mode()


def test_cuda_rng_state_roundtrip():
    state = paddle.get_cuda_rng_state()
    a = np.asarray(paddle.rand([3]))
    paddle.set_cuda_rng_state(state)
    b = np.asarray(paddle.rand([3]))
    np.testing.assert_allclose(a, b)


def test_batch_decorator():
    r = paddle.batch(lambda: iter(range(7)), 3)
    sizes = [len(b) for b in r()]
    assert sizes == [3, 3, 1]
    r2 = paddle.batch(lambda: iter(range(7)), 3, drop_last=True)
    assert [len(b) for b in r2()] == [3, 3]


def test_create_parameter_and_grad_enabled():
    p = paddle.create_parameter([4, 5], "float32")
    assert p.shape == (4, 5) and p.trainable
    b = paddle.create_parameter([5], "float32", is_bias=True)
    np.testing.assert_allclose(np.asarray(b.value), np.zeros(5))
    with paddle.set_grad_enabled(False):
        pass
    with paddle.set_grad_enabled(True):
        pass


def test_check_shape():
    assert paddle.tensor.random.check_shape([2, 3]) == [2, 3]
    with pytest.raises(ValueError):
        paddle.tensor.random.check_shape([2, -3])


def test_namespace_all_parity_against_reference():
    """Every name in the reference's __all__ for each public namespace must
    resolve on the corresponding paddle_tpu module (the switching-user
    contract). Skips namespaces whose reference file is absent."""
    import ast
    import importlib
    import os

    REF = "/root/reference/python/paddle"
    if not os.path.isdir(REF):
        pytest.skip("reference tree not mounted")

    def ref_all(path):
        try:
            tree = ast.parse(open(path).read())
        except Exception:
            return None
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", "") == "__all__":
                        try:
                            return [e.value for e in node.value.elts
                                    if isinstance(e, ast.Constant)]
                        except Exception:
                            return None
        return None

    mods = ["", "nn", "nn.functional", "nn.initializer", "io", "amp", "jit",
            "metric", "optimizer", "optimizer.lr", "vision",
            "vision.transforms", "vision.datasets", "text", "utils",
            "incubate", "distribution", "onnx", "autograd", "device",
            "regularizer", "sysconfig", "static", "static.nn",
            "distributed"]
    gaps = {}
    for mod in mods:
        parts = mod.split(".") if mod else []
        cands = [os.path.join(REF, *parts, "__init__.py")]
        if parts:
            cands.append(os.path.join(REF, *parts[:-1], parts[-1] + ".py"))
        rp = next((c for c in cands if os.path.exists(c)), None)
        names = ref_all(rp) if rp else None
        if not names:
            continue
        m = importlib.import_module(
            "paddle_tpu" + ("." + mod if mod else ""))
        missing = [n for n in names if not hasattr(m, n)]
        if missing:
            gaps[mod or "paddle"] = missing
    assert not gaps, gaps
