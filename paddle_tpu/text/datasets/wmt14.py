"""WMT14 en→fr translation dataset (reference:
python/paddle/text/datasets/wmt14.py — preprocessed tarball with
``src.dict``/``trg.dict`` files and ``{mode}/{mode}`` parallel files of
tab-separated sentence pairs; sequences over 80 tokens dropped).
"""
from __future__ import annotations

import tarfile

import numpy as np

from ...io.dataset import Dataset
from ...utils.download import DATA_HOME, get_path_from_url

URL_TRAIN = ("https://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz")
START, END, UNK = "<s>", "<e>", "<unk>"
UNK_IDX = 2


class WMT14(Dataset):
    """Samples: (src_ids, trg_ids, trg_ids_next) np arrays."""

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        assert mode.lower() in ("train", "test", "gen"), mode
        self.mode = mode.lower()
        if data_file is None:
            assert download, "data_file not set and download disabled"
            data_file = get_path_from_url(URL_TRAIN, DATA_HOME + "/wmt14",
                                          decompress=False)
        self.data_file = data_file
        assert dict_size > 0, "dict_size must be positive"
        self.dict_size = dict_size
        self._load()

    @staticmethod
    def _read_dict(f, size):
        d = {}
        for i, line in enumerate(f):
            if i >= size:
                break
            d[line.decode("utf-8", "ignore").strip()] = i
        return d

    def _load(self):
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as tf:
            members = {m.name: m for m in tf}
            src_dicts = [n for n in members if n.endswith("src.dict")]
            trg_dicts = [n for n in members if n.endswith("trg.dict")]
            assert len(src_dicts) == 1 and len(trg_dicts) == 1
            self.src_dict = self._read_dict(
                tf.extractfile(members[src_dicts[0]]), self.dict_size)
            self.trg_dict = self._read_dict(
                tf.extractfile(members[trg_dicts[0]]), self.dict_size)
            want = f"{self.mode}/{self.mode}"
            for name in members:
                if not name.endswith(want):
                    continue
                for line in tf.extractfile(members[name]):
                    parts = line.decode("utf-8", "ignore").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, UNK_IDX)
                           for w in [START] + parts[0].split() + [END]]
                    trg = [self.trg_dict.get(w, UNK_IDX)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.src_ids.append(src)
                    self.trg_ids.append([self.trg_dict[START]] + trg)
                    self.trg_ids_next.append(trg + [self.trg_dict[END]])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, reverse=False):
        """Return (src_dict, trg_dict); reversed = id→word."""
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict
