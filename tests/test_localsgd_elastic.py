"""LocalSGD meta-optimizer + end-to-end elastic failure handling
(reference: fleet/meta_optimizers/localsgd_optimizer.py and
fleet/elastic.py:316 watch loop + launch_utils.py:565 trainer watch)."""
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.engine import ParallelTrainer
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.meta_parallel.localsgd import LocalSGDTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _data():
    rng = np.random.RandomState(0)
    return (rng.randn(16, 8).astype("f4"),
            rng.randn(16, 4).astype("f4"))


def _mse(o, y):
    return jnp.mean((o - y) ** 2)


class TestLocalSGD:
    def test_k1_matches_dp_trajectory(self):
        """k=1 LocalSGD == plain DP for SGD: avg(p - lr*g_i) ==
        p - lr*avg(g_i)."""
        x, y = _data()
        build_mesh({"data": 4})
        paddle.seed(0)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        dp = ParallelTrainer(net, opt, _mse)
        dp_losses = [float(dp.train_step(x, y)) for _ in range(5)]

        build_mesh({"data": 4})
        paddle.seed(0)
        net2 = nn.Linear(8, 4)
        opt2 = paddle.optimizer.SGD(0.1, parameters=net2.parameters())
        ls = LocalSGDTrainer(net2, opt2, _mse, k_steps=1)
        ls_losses = [float(ls.train_step(x, y)) for _ in range(5)]
        np.testing.assert_allclose(dp_losses, ls_losses, rtol=1e-5)

    def test_k4_diverges_then_syncs(self):
        x, y = _data()
        build_mesh({"data": 4})
        paddle.seed(1)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(0.05, parameters=net.parameters())
        ls = LocalSGDTrainer(net, opt, _mse, k_steps=4)
        losses = []
        for step in range(1, 9):
            losses.append(float(ls.train_step(x, y)))
            reps = ls.replica_params("weight")
            spread = np.abs(reps - reps.mean(0, keepdims=True)).max()
            if step % 4 == 0:
                assert spread < 1e-6, (step, spread)  # just synced
            else:
                assert spread > 1e-7, (step, spread)  # local divergence
        assert losses[-1] < losses[0]

    def test_adaptive_k_shrinks_as_loss_drops(self):
        """Reference schedule (localsgd_optimizer.py:425): next_k =
        ceil(sqrt(lr0*loss/(lr*loss0) * init_k)) — replicas sync MORE
        often as the loss falls."""
        x, y = _data()
        build_mesh({"data": 4})
        paddle.seed(2)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
        opt = paddle.optimizer.SGD(0.2, parameters=net.parameters())
        ls = LocalSGDTrainer(net, opt, _mse, adaptive=True,
                             init_k_steps=8, max_k_steps=16)
        for _ in range(30):
            ls.train_step(x, y)
        assert 1 <= ls.k_steps < 8

    def test_localsgd_with_adam(self):
        """Adam-family optimizers must work replica-major (regression:
        the replicated step counter used to break bias-correction
        broadcasting)."""
        x, y = _data()
        build_mesh({"data": 2})
        paddle.seed(3)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
        ls = LocalSGDTrainer(net, opt, _mse, k_steps=2)
        losses = [float(ls.train_step(x, y)) for _ in range(6)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]


ELASTIC_WORKER = """
    import os, sys, time
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    root = os.environ["ELASTIC_ROOT"]
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus)
    em = ElasticManager(elastic_server=root, job_id="e2e", np=2,
                        host=f"rank{rank}", timeout=2.0)
    em.register()
    with open(os.path.join(root, f"pid.{rank}"), "w") as f:
        f.write(str(os.getpid()))
    gen2 = os.path.join(root, "gen2.flag")
    resumed = os.path.exists(gen2)
    ck = os.path.join(root, f"ck.{rank}")
    step = int(open(ck).read()) if os.path.exists(ck) else 0
    for i in range(step, 40):
        time.sleep(0.12)
        with open(ck, "w") as f:   # checkpoint each "training" step
            f.write(str(i + 1))
        if not resumed:
            st = em.watch(proc_alive=lambda: True)
            if st == ElasticStatus.RESTART:
                # a peer died: record the observation and trigger the
                # supervisor's relaunch (reference watch-loop semantics)
                open(os.path.join(root, f"observed_restart.{rank}"),
                     "w").close()
                open(gen2, "w").close()
                sys.exit(9)
    em.deregister()
    print(f"rank {rank} done resumed={resumed}")
"""


@pytest.mark.slow
def test_elastic_kill_rank_restart_and_resume(tmp_path):
    """End-to-end: stall a live trainer mid-run (SIGSTOP — a hang the
    process supervisor cannot detect); the surviving rank's
    ElasticManager.watch observes the stale heartbeat (RESTART), exits to
    trigger the supervisor's relaunch, and the second incarnation RESUMES
    from checkpoints instead of restarting from zero (reference
    elastic.py:316 watch + launch_utils.py:565)."""
    script = tmp_path / "elastic_worker.py"
    script.write_text(textwrap.dedent(ELASTIC_WORKER))
    root = tmp_path / "kv"
    root.mkdir()
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "ELASTIC_ROOT": str(root),
    })
    launcher = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restarts", "2", str(script)],
        env=env, cwd=str(tmp_path), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        # wait until rank 1 is alive and training
        pid_file = root / "pid.1"
        deadline = time.time() + 60
        while not pid_file.exists():
            assert time.time() < deadline, "workers never started"
            time.sleep(0.05)
        victim = int(pid_file.read_text())
        while not (root / "ck.1").exists():
            assert time.time() < deadline, "no training progress"
            time.sleep(0.05)
        # STALL (not kill) the trainer: the process supervisor cannot see
        # a hang — only the membership watch's heartbeat staleness can
        os.kill(victim, signal.SIGSTOP)
        out, _ = launcher.communicate(timeout=120)
    except Exception:
        launcher.kill()
        raise
    assert launcher.returncode == 0, out[-3000:]
    # the surviving rank OBSERVED the failure through the membership watch
    assert (root / "observed_restart.0").exists(), out[-3000:]
    # the supervisor relaunched
    assert "elastic restart" in out
    # second incarnation resumed from checkpoints and completed
    assert "done resumed=True" in out
    assert int((root / "ck.0").read_text()) == 40
    assert int((root / "ck.1").read_text()) == 40


def test_elastic_exit_when_all_members_gone(tmp_path):
    """EXIT path: membership collapses to zero -> watch returns EXIT."""
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus)
    em = ElasticManager(elastic_server=str(tmp_path), job_id="x", np=2,
                        host="a", timeout=1.0)
    observer = ElasticManager(elastic_server=str(tmp_path), job_id="x",
                              np=2, host="b", timeout=1.0)
    em.register()
    # observer not registered: only 'a' alive -> below np_min -> RESTART
    assert observer.watch(proc_alive=lambda: True) == ElasticStatus.RESTART
    em.deregister()
    time.sleep(0.1)
    assert observer.watch(proc_alive=lambda: True) == ElasticStatus.EXIT
