"""Layer-system tests (reference: test_imperative_* family —
parameters/sublayers/state_dict/hooks)."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn


class TestLayerSystem:
    def test_parameter_registration(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(self.fc1(x))

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert set(names) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}

    def test_state_dict_roundtrip(self):
        net1 = nn.Linear(3, 3)
        net2 = nn.Linear(3, 3)
        net2.set_state_dict(net1.state_dict())
        x = jnp.ones((2, 3))
        np.testing.assert_allclose(np.asarray(net1(x)), np.asarray(net2(x)))

    def test_save_load(self, tmp_path):
        net = nn.Linear(3, 3)
        path = str(tmp_path / "model.pdparams")
        paddle.save(net.state_dict(), path)
        sd = paddle.load(path)
        net2 = nn.Linear(3, 3)
        net2.set_state_dict(sd)
        x = jnp.ones((1, 3))
        np.testing.assert_allclose(np.asarray(net(x)), np.asarray(net2(x)))

    def test_train_eval_mode_propagates(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        net.eval()
        assert not net[1].training
        net.train()
        assert net[1].training

    def test_forward_hooks(self):
        net = nn.Linear(2, 2)
        calls = []
        h = net.register_forward_post_hook(
            lambda layer, inp, out: calls.append(out.shape))
        net(jnp.ones((3, 2)))
        assert calls == [(3, 2)]
        h.remove()
        net(jnp.ones((3, 2)))
        assert len(calls) == 1

    def test_sequential_and_layerlist(self):
        seq = nn.Sequential(nn.Linear(2, 4), nn.ReLU(), nn.Linear(4, 1))
        assert len(seq) == 3
        y = seq(jnp.ones((2, 2)))
        assert y.shape == (2, 1)
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4
        assert len(nn.Sequential(*ll).parameters()) == 8

    def test_astype_bfloat16(self):
        net = nn.Linear(3, 3)
        net.bfloat16()
        assert net.weight.dtype == jnp.bfloat16

    def test_parameter_stop_gradient(self):
        net = nn.Linear(2, 2)
        net.bias.stop_gradient = True
        assert not net.bias.trainable


class TestDropoutRNG:
    def test_dropout_deterministic_under_guard(self):
        from paddle_tpu.framework.random import rng_guard
        import jax
        d = nn.Dropout(0.5)
        key = jax.random.key(7)
        with rng_guard(key):
            a = d(jnp.ones((4, 4)))
        with rng_guard(key):
            b = d(jnp.ones((4, 4)))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_dropout_eval_identity(self):
        d = nn.Dropout(0.9)
        d.eval()
        x = jnp.ones((4, 4))
        np.testing.assert_allclose(np.asarray(d(x)), 1.0)


class TestTransformer:
    def test_encoder_shapes(self):
        enc_layer = nn.TransformerEncoderLayer(32, 4, 64, dropout=0.0)
        enc = nn.TransformerEncoder(enc_layer, 2)
        x = jnp.ones((2, 10, 32))
        y = enc(x)
        assert y.shape == (2, 10, 32)

    def test_full_transformer(self):
        model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=1,
                               num_decoder_layers=1, dim_feedforward=32,
                               dropout=0.0)
        src = jnp.ones((2, 6, 16))
        tgt = jnp.ones((2, 4, 16))
        out = model(src, tgt)
        assert out.shape == (2, 4, 16)

    def test_causal_mask_matches_full(self):
        """Attention with causal flag == attention with explicit mask."""
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(0)
        q = jnp.asarray(rs.randn(1, 8, 2, 4), dtype=jnp.float32)
        k = jnp.asarray(rs.randn(1, 8, 2, 4), dtype=jnp.float32)
        v = jnp.asarray(rs.randn(1, 8, 2, 4), dtype=jnp.float32)
        causal = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        mask = jnp.tril(jnp.ones((8, 8), dtype=bool))[None, None]
        masked = F.scaled_dot_product_attention(q, k, v, attn_mask=mask)
        np.testing.assert_allclose(np.asarray(causal), np.asarray(masked),
                                   rtol=1e-5, atol=1e-5)

    def test_decode_cache(self):
        mha = nn.MultiHeadAttention(16, 2)
        mha.eval()
        x = jnp.ones((1, 4, 16))
        cache = mha.gen_cache(x)
        out, cache = mha(x, x, x, None, cache)
        assert out.shape == (1, 4, 16)
        assert cache[0].shape[1] == 4


class TestOptimizers:
    @pytest.mark.parametrize("opt_cls,kwargs", [
        ("SGD", {}), ("Momentum", {}), ("Adam", {}), ("AdamW", {}),
        ("Adagrad", {}), ("RMSProp", {}), ("Lamb", {}), ("Adamax", {}),
    ])
    def test_optimizer_decreases_loss(self, opt_cls, kwargs):
        paddle.seed(0)
        net = nn.Linear(4, 1)
        opt = getattr(paddle.optimizer, opt_cls)(
            learning_rate=0.1, parameters=net.parameters(), **kwargs)
        x = jnp.asarray(np.random.RandomState(0).randn(16, 4), dtype=jnp.float32)
        y = jnp.sum(x, axis=1, keepdims=True)

        def loss_closure():
            return jnp.mean(jnp.square(net(x) - y))

        from paddle_tpu.autograd import backward
        l0 = float(loss_closure())
        for _ in range(20):
            backward(net, loss_closure)
            opt.step()
            opt.clear_grad()
        l1 = float(loss_closure())
        assert l1 < l0, f"{opt_cls}: {l0} -> {l1}"

    def test_adam_bf16_slots(self):
        """slot_dtype='bfloat16' halves optimizer-state HBM (the 1.3B
        single-chip lever); moments must be STORED bf16 but the update
        math must still track the fp32-slot trajectory closely."""
        paddle.seed(0)
        net32 = nn.Linear(8, 1)
        paddle.seed(0)
        net16 = nn.Linear(8, 1)
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(32, 8), dtype=jnp.float32)
        y = jnp.sum(x, axis=1, keepdims=True)
        from paddle_tpu.autograd import backward
        opt32 = paddle.optimizer.AdamW(0.05, parameters=net32.parameters())
        opt16 = paddle.optimizer.AdamW(0.05, parameters=net16.parameters(),
                                       slot_dtype="bfloat16")
        assert opt16.init_slots(jnp.zeros((3,)))["m"].dtype == jnp.bfloat16
        l0 = float(jnp.mean(jnp.square(net16(x) - y)))
        for net, opt in ((net32, opt32), (net16, opt16)):
            for _ in range(60):
                backward(net, lambda: jnp.mean(jnp.square(net(x) - y)))
                opt.step()
                opt.clear_grad()
        l32 = float(jnp.mean(jnp.square(net32(x) - y)))
        l16 = float(jnp.mean(jnp.square(net16(x) - y)))
        assert l16 < 0.5 * l0  # it trained
        assert abs(l32 - l16) < 0.15 * max(l32, l16) + 5e-2

    def test_global_norm_clip(self):
        from paddle_tpu.optimizer import ClipGradByGlobalNorm
        clip = ClipGradByGlobalNorm(1.0)
        grads = {"a": jnp.ones((10,)) * 10, "b": jnp.ones((10,)) * 10}
        out = clip(grads)
        total = np.sqrt(sum(float(jnp.sum(jnp.square(g)))
                            for g in out.values()))
        np.testing.assert_allclose(total, 1.0, rtol=1e-5)

    def test_lr_scheduler_cosine(self):
        from paddle_tpu.optimizer.lr import CosineAnnealingDecay
        s = CosineAnnealingDecay(1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-6
        for _ in range(10):
            s.step()
        assert s() < 1e-6

    def test_lr_warmup(self):
        from paddle_tpu.optimizer.lr import LinearWarmup
        s = LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
        vals = []
        for _ in range(12):
            vals.append(s())
            s.step()
        assert vals[0] == 0.0
        assert abs(vals[-1] - 0.1) < 1e-9


class TestAmp:
    def test_autocast_linear_bf16(self):
        net = nn.Linear(4, 4)
        x = jnp.ones((2, 4))
        with paddle.amp.auto_cast():
            y = net(x)
        assert y.dtype == jnp.bfloat16
        y2 = net(x)
        assert y2.dtype == jnp.float32

    def test_grad_scaler_dynamics(self):
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0,
                                       incr_every_n_steps=2,
                                       decr_every_n_nan_or_inf=1)
        grads = {"w": jnp.ones(3) * 8.0}
        unscaled, found = scaler.unscale_(grads)
        assert not bool(found)
        np.testing.assert_allclose(np.asarray(unscaled["w"]), 1.0)
        scaler.update(True)  # nan step → halve
        assert scaler.get_loss_scaling() == 4.0

    def test_functional_scaler_disabled_is_inert(self):
        """enable=False must no-op through the functional path exactly as
        update() does imperatively — the scale stays 1.0 forever."""
        scaler = paddle.amp.GradScaler(enable=False, init_loss_scaling=8.0,
                                       incr_every_n_steps=1)
        st = scaler.init_scale_state()
        assert float(st["scale"]) == 1.0
        _, found, st = scaler.unscale_and_update({"w": jnp.ones(2)}, st)
        assert not bool(found)
        assert float(st["scale"]) == 1.0

    def test_jitted_dynamic_loss_scale_moves(self):
        """static.amp.decorate: overflow → scale halves; recovery → scale
        regrows — UNDER JIT, via the loss-scale state pytree (reference
        puts update_loss_scaling into the graph, decorator.py:446)."""
        import jax
        from paddle_tpu import static
        lin = nn.Linear(1, 1, bias_attr=False)
        opt = static.amp.decorate(
            paddle.optimizer.SGD(0.5, parameters=lin.parameters()),
            init_loss_scaling=16.0, incr_every_n_steps=3,
            decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.5)

        params = {"w": jnp.asarray([1.0])}
        state = opt.init_state(params)
        assert opt.get_loss_scaling(state) == 16.0

        @jax.jit
        def step(params, state, grads):
            return opt.apply_gradients(params, grads, state, lr=0.1)

        # scale_loss is traced from the state, not baked from the host float
        assert float(opt.scale_loss(jnp.asarray(1.0), state)) == 16.0

        inf_g = {"w": jnp.asarray([jnp.inf])}
        # 1st overflow: update skipped, counter advances, scale unchanged
        params, state = step(params, state, inf_g)
        assert float(params["w"][0]) == 1.0
        assert opt.get_loss_scaling(state) == 16.0
        # 2nd consecutive overflow: scale halves (decr_every=2)
        params, state = step(params, state, inf_g)
        assert float(params["w"][0]) == 1.0
        assert opt.get_loss_scaling(state) == 8.0
        # 3 good steps: scale regrows 2x (incr_every=3); params move
        good = {"w": jnp.asarray([8.0])}   # scaled grad, true grad 1.0
        for _ in range(3):
            params, state = step(params, state, good)
        assert opt.get_loss_scaling(state) == 16.0
        assert float(params["w"][0]) == pytest.approx(1.0 - 3 * 0.1)
