"""paddle_tpu.incubate — experimental features (reference:
python/paddle/incubate/)."""
from .moe import ExpertFFN, MoELayer, top2_gating  # noqa: F401
