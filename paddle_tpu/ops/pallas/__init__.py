from . import tuner  # noqa: F401
from .flash_attention import flash_attention, flash_supported  # noqa: F401
from .fused_ce import fused_ce_supported, fused_lm_ce  # noqa: F401
from .paged_attention import (  # noqa: F401
    paged_decode_attention, paged_decode_supported, paged_prefill_attention)
