"""C17 trainer/device-worker runtime tests: Hogwild lock-free threads,
Downpour async communicator, trainer factory, dataset wiring.
(reference analogues: test_trainer_desc.py, test_communicator_async.py,
test_downpoursgd.py, dist_fleet_ctr.py.)"""
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.ps import (Communicator, DenseTable,
                                       InMemoryDataset, MultiTrainer,
                                       SparseTable, TrainerDesc,
                                       TrainerFactory)

DIM = 8
VOCAB = 200
RS = np.random.RandomState(0)
# ground truth: each id has a latent score; label = 1 if mean score > 0
_TRUE = RS.randn(VOCAB).astype(np.float32)


def _make_batches(n_batches, bsz, seq=6, pad_frac=0.0, seed=1):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        ids = rs.randint(0, VOCAB, (bsz, seq)).astype(np.int64)
        labels = (_TRUE[ids].mean(axis=1) > 0).astype(np.float32)
        if pad_frac:
            mask = rs.rand(bsz, seq) < pad_frac
            mask[:, 0] = False          # keep >=1 valid id per example
            ids = np.where(mask, -1, ids)
        out.append({"ids": ids, "label": labels})
    return out


@jax.jit
def _logreg_step(emb, w, labels):
    """loss, d/demb, d/dw of a logistic regression over mean-pooled
    embeddings (the Wide&Deep 'deep' tower in miniature)."""
    def f(emb, w):
        feat = emb.mean(axis=1)
        logit = feat @ w
        return jnp.mean(
            jnp.maximum(logit, 0) - logit * labels
            + jnp.log1p(jnp.exp(-jnp.abs(logit))))
    loss, (ge, gw) = jax.value_and_grad(f, argnums=(0, 1))(emb, w)
    return loss, ge, gw


def _step_fn(emb, dense, batch):
    loss, ge, gw = _logreg_step(jnp.asarray(emb), jnp.asarray(dense),
                                jnp.asarray(batch["label"]))
    return float(loss), np.asarray(ge), np.asarray(gw)


def _train(worker, epochs=3, thread_num=3, pad_frac=0.0, lr=0.5):
    table = SparseTable(DIM, "adagrad", seed=3, init_range=0.01)
    dense = DenseTable(DIM, "sgd")
    dense.set(np.zeros(DIM, np.float32))
    desc = TrainerDesc(worker=worker, thread_num=thread_num,
                       batch_size=32, lr=lr)
    trainer = TrainerFactory().create(desc)
    batches = _make_batches(12, 32, pad_frac=pad_frac)
    stats = []
    for _ in range(epochs):
        stats.append(trainer.train(batches, _step_fn, table,
                                   dense_table=dense))
    return table, dense, trainer, stats


class TestHogwild:
    def test_loss_decreases_across_epochs(self):
        _, _, _, stats = _train("hogwild")
        assert stats[-1]["loss_mean"] < stats[0]["loss_mean"] - 0.05, \
            [s["loss_mean"] for s in stats]

    def test_all_batches_processed_across_threads(self):
        _, _, trainer, stats = _train("hogwild", epochs=1, thread_num=4)
        assert stats[0]["batches"] == 12
        assert stats[0]["threads"] == 4
        # round-robin partition: every worker got some batches
        assert all(w.batches_done > 0 for w in trainer.workers)

    def test_padding_ids_never_enter_table(self):
        table, _, _, _ = _train("hogwild", epochs=1, pad_frac=0.3)
        # table only materializes touched (non-negative) ids
        assert 0 < len(table) <= VOCAB


class TestDownpour:
    def test_loss_decreases_and_communicator_applies_all(self):
        _, _, trainer, stats = _train("downpour")
        assert stats[-1]["loss_mean"] < stats[0]["loss_mean"] - 0.05
        # one communicator per train() call; last one saw all 12 batches
        assert trainer.communicator.pushes_applied == 12

    def test_grad_merge_dedups_keys(self):
        # adagrad distinguishes merged from sequential pushes: one g=2
        # step gives -lr*2/sqrt(4) = -1.0; two g=1 steps give ~-1.707
        table = SparseTable(4, "adagrad", init_range=0.0)
        comm = Communicator(table, lr=1.0, merge_grads=True)
        keys = np.array([7, 7, 9], np.int64)
        grads = np.ones((3, 4), np.float32)
        comm.send(keys, grads)
        comm.stop()
        out = table.pull(np.array([7, 9]))
        np.testing.assert_allclose(out[0], -1.0 * np.ones(4), rtol=1e-4)
        np.testing.assert_allclose(out[1], -1.0 * np.ones(4), rtol=1e-4)

    def test_flush_barrier_applies_queue(self):
        table = SparseTable(4, "sgd", init_range=0.0)
        comm = Communicator(table, lr=1.0, send_queue_size=64)
        for _ in range(20):
            comm.send(np.array([1], np.int64), np.ones((1, 4), np.float32))
        comm.flush()
        np.testing.assert_allclose(table.pull(np.array([1]))[0],
                                   -20.0 * np.ones(4))
        comm.stop()


class TestFactoryAndDataset:
    def test_unknown_worker_raises(self):
        with pytest.raises(ValueError, match="hogwild"):
            TrainerFactory().create(TrainerDesc(worker="nope"))

    def test_worker_error_propagates(self):
        def bad_step(emb, dense, batch):
            raise RuntimeError("boom")
        t = MultiTrainer(TrainerDesc(worker="hogwild", thread_num=2))
        with pytest.raises(RuntimeError, match="boom"):
            t.train(_make_batches(2, 8), bad_step,
                    SparseTable(DIM, "sgd"))

    def test_train_from_inmemory_dataset(self, tmp_path):
        # MultiSlot file -> InMemoryDataset -> trainer (the reference
        # exe.train_from_dataset path)
        lines = []
        rs = np.random.RandomState(2)
        for _ in range(64):
            ids = rs.randint(0, VOCAB, 4)
            label = float(_TRUE[ids].mean() > 0)
            lines.append(
                f"{len(ids)} " + " ".join(str(i) for i in ids)
                + f" 1 {label}")
        p = tmp_path / "part-0"
        p.write_text("\n".join(lines) + "\n")
        ds = InMemoryDataset(["ids", "label"], dense_slots=["label"])
        ds.load_into_memory([str(p)])
        ds.global_shuffle(seed=0)

        table = SparseTable(DIM, "adagrad", seed=3, init_range=0.01)
        dense = DenseTable(DIM, "sgd")
        dense.set(np.zeros(DIM, np.float32))

        def step(emb, dw, batch):
            loss, ge, gw = _logreg_step(
                jnp.asarray(emb), jnp.asarray(dw),
                jnp.asarray(batch["label"][:, 0]))
            return float(loss), np.asarray(ge), np.asarray(gw)

        trainer = TrainerFactory().create(
            TrainerDesc(worker="downpour", thread_num=2, batch_size=16,
                        lr=0.5))
        first = trainer.train(ds, step, table, dense_table=dense)
        last = None
        for _ in range(4):
            last = trainer.train(ds, step, table, dense_table=dense)
        assert last["loss_mean"] < first["loss_mean"]
        assert len(table) > 0

    def test_executor_train_and_infer_from_dataset(self):
        from paddle_tpu import static
        exe = static.Executor()
        table = SparseTable(DIM, "adagrad", seed=5, init_range=0.01)
        dense = DenseTable(DIM, "sgd")
        dense.set(np.zeros(DIM, np.float32))
        batches = _make_batches(8, 16, seed=4)
        first = exe.train_from_dataset(_step_fn, batches, table,
                                       dense_table=dense, thread=2,
                                       lr=0.5, worker="downpour")
        for _ in range(3):
            stats = exe.train_from_dataset(_step_fn, batches, table,
                                           dense_table=dense, thread=2,
                                           lr=0.5, worker="downpour")
        assert stats["loss_mean"] < first["loss_mean"]
        # infer: loss reported, tables untouched — even on UNSEEN ids
        # (eval must not materialize rows or advance optimizer state)
        eval_batches = batches + [{
            "ids": np.full((4, 6), VOCAB + 999, np.int64),
            "label": np.zeros(4, np.float32)}]
        w_before = dense.pull().copy()
        n_before = len(table)
        seen = np.unique(np.concatenate(
            [b["ids"].ravel() for b in batches]))
        rows_before = table.pull(seen, create_missing=False).copy()
        ev = exe.infer_from_dataset(_step_fn, eval_batches, table,
                                    dense_table=dense, thread=2)
        assert np.isfinite(ev["loss_mean"])
        np.testing.assert_array_equal(dense.pull(), w_before)
        assert len(table) == n_before
        np.testing.assert_array_equal(
            table.pull(seen, create_missing=False), rows_before)
