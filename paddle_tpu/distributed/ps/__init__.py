"""paddle_tpu.distributed.ps — the sparse parameter-server tier.

Native C++ core (csrc/ps/): sharded hash-table embedding store with
server-side optimizers + multi-threaded MultiSlot ingest. Python tier here:
tables, the jit-compatible DistributedEmbedding layer, and the in-memory
dataset. See each module's docstring for the reference capability map
(C27–C30 in SURVEY.md §2).
"""
from .datafeed import InMemoryDataset, QueueDataset  # noqa: F401
from .embedding import DistributedEmbedding, make_lookup  # noqa: F401
from .heter import HeterEmbedding  # noqa: F401
from .service import (DistributedGraphTable, DistributedSparseTable,  # noqa: F401
                      PsServer)
from .table import (DenseTable, GraphTable, SparseTable,  # noqa: F401
                    shard_keys)
from .trainer import (Communicator, DownpourWorker,  # noqa: F401
                      HogwildWorker, MultiTrainer, TrainerDesc,
                      TrainerFactory)
