"""Single-box multi-host simulation: N subprocess "hosts" over the
file-KV ElasticManager + the FileCoordinator loopback barrier.

Each simulated host is a real OS process (``python -m
paddle_tpu.resilience.hostsim``) with its own jax runtime, its own
per-host CheckpointManager directory, and its own elastic runtime; the
only shared state is the KV/coordinator directory tree — exactly the
shape of a real multi-host job minus the accelerators. That makes the
whole elastic story (divergent-checkpoint restore barrier, host-loss
detection via heartbeat staleness, remesh + reshard, resume) testable on
CPU.

``SimCluster`` is the supervisor: it seeds (optionally divergent)
checkpoints, launches the hosts, arms per-host deterministic faults, and
collects one result JSON per surviving host. A host killed by the
``host_loss`` fault dies with ``os._exit(9)`` — no deregister, no
checkpoint flush — so survivors only learn of it when its heartbeat goes
stale, like a real machine loss.

Layout under the cluster root::

    kv/        the ElasticManager member files (<host>.alive)
    coord/     FileCoordinator generations (allgather/barrier rounds)
    ckpt/<h>/  per-host CheckpointManager directory
    reshard/<h>/  per-host save-on-old-mesh -> restore-on-new-mesh staging
    results/<h>.json  one line of results per surviving host
"""
from __future__ import annotations

import argparse
import contextlib
import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

__all__ = ["SimCluster", "worker_main", "HOST_LOSS_EXIT",
           "HOST_HANG_EXIT", "SCHEDULE_MISMATCH_EXIT"]

HOST_LOSS_EXIT = 9   # a host_loss death (distinct from every runner code)
HOST_HANG_EXIT = 10  # hang-watchdog self-termination (wedged step)
SCHEDULE_MISMATCH_EXIT = 11  # bootstrap collective-schedule verify abort

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _host_name(i: int) -> str:
    return f"host{i}"


class _SlowLoader:
    """Re-iterable deterministic loader with a per-batch delay, so a
    simulated run spans enough wall-clock for heartbeat staleness to be
    observable mid-run."""

    def __init__(self, batches, delay: float = 0.0):
        self.batches = batches
        self.delay = delay

    def __iter__(self):
        for b in self.batches:
            if self.delay:
                time.sleep(self.delay)
            yield b


def _tiny_batches(n: int = 4, batch: int = 8, seed: int = 3):
    import numpy as np
    rng = np.random.RandomState(seed)
    return [(rng.randn(batch, 8).astype(np.float32),
             rng.randn(batch, 4).astype(np.float32)) for _ in range(n)]


def _tiny_trainer(seed: int = 7, data_degree: int = 2):
    """The smallest trainer that still exercises the full elastic
    surface: int8 compressed exchange (non-empty comm_err residuals) on a
    data mesh whose degree the remesh path can change."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.engine import ParallelTrainer
    from paddle_tpu.distributed.mesh import build_mesh

    paddle.seed(seed)
    mesh = build_mesh({"data": data_degree})

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(8, 16)
            self.l2 = nn.Linear(16, 4)

        def forward(self, x):
            return self.l2(nn.functional.relu(self.l1(x)))

    model = MLP()
    opt = paddle.optimizer.Momentum(0.05, momentum=0.9,
                                    parameters=model.parameters())
    return ParallelTrainer(model, opt,
                           lambda out, y: jnp.mean((out - y) ** 2),
                           mesh=mesh, grad_sync="int8", grad_sync_block=8)


def seed_checkpoints(ckpt_dir: str, upto_step: int, seed: int = 7,
                     data_degree: int = 2):
    """Pre-populate a host's checkpoint directory with steps
    ``0..upto_step``, using the runner's own save format/cursor semantics
    so a worker resumes them transparently. Deterministic: two hosts
    seeded to the same step hold identical state."""
    from ..distributed.checkpoint import CheckpointManager
    from .runner import _save

    trainer = _tiny_trainer(seed=seed, data_degree=data_degree)
    batches = _tiny_batches()
    mgr = CheckpointManager(ckpt_dir, max_to_keep=upto_step + 1,
                            use_async=False)
    it = iter(batches)
    epoch, batch = 0, 0
    for step in range(upto_step + 1):
        try:
            x, y = next(it)
        except StopIteration:
            epoch, batch = epoch + 1, 0
            it = iter(batches)
            x, y = next(it)
        trainer.train_step(x, y)
        batch += 1
        _save(mgr, trainer, step, epoch, batch)
    mgr.close()


# ---------------------------------------------------------------------------
# worker (one simulated host; runs as __main__ in its own process)
# ---------------------------------------------------------------------------

def _counter_total(snapshot: dict, name: str) -> float:
    series = snapshot.get(name, {}).get("series", {})
    return float(sum(v for v in series.values()
                     if isinstance(v, (int, float))))


def worker_main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description="one simulated elastic host")
    p.add_argument("--root", required=True)
    p.add_argument("--host", required=True)
    p.add_argument("--world", type=int, required=True)
    p.add_argument("--np", dest="np_spec", default="2:3")
    p.add_argument("--steps", type=int, default=24)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--hb-timeout", type=float, default=1.0)
    p.add_argument("--step-delay", type=float, default=0.15)
    p.add_argument("--max-remeshes", type=int, default=3)
    p.add_argument("--hang-timeout", type=float, default=0.0,
                   help="arm the hang watchdog with this step deadline "
                        "(seconds); a firing exits with HOST_HANG_EXIT")
    p.add_argument("--fault", action="append", default=[],
                   metavar="KIND:STEP",
                   help="arm a deterministic fault, e.g. host_loss:12")
    p.add_argument("--desync-schedule", action="store_true",
                   help="advertise the WRONG program fingerprint (the "
                        "integrity-check variant) so the bootstrap "
                        "schedule verification must abort the cluster")
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from .. import telemetry
    from ..distributed.checkpoint import CheckpointManager
    from ..distributed.fleet.elastic import ElasticManager
    from . import faults, integrity
    from .elastic import ElasticRuntime, FileCoordinator, \
        data_parallel_remesh_fn
    from .runner import run_resilient

    telemetry.enable()
    # Every simulated host records flight spans into a shared directory;
    # the supervisor (or chaos_smoke) merges the per-host dumps rank-0
    # style, keyed by process_index.
    from ..telemetry import flight, tracing
    _pidx = int(args.host.lstrip("host") or 0) \
        if args.host.startswith("host") else 0
    flight.configure(os.path.join(args.root, "flight"),
                     process_index=_pidx)
    tracing.enable()  # always-on ring: a hang dump shows recent spans
    trainer = _tiny_trainer(seed=args.seed, data_degree=2)
    loader = _SlowLoader(_tiny_batches(), delay=args.step_delay)

    em = ElasticManager(elastic_server=os.path.join(args.root, "kv"),
                        job_id="sim", np=args.np_spec, host=args.host,
                        timeout=args.hb_timeout)
    em.register()
    # Background heartbeat: the step loop's own heartbeat stalls for
    # seconds during XLA compiles and restore barriers, which would look
    # like death to every peer at a sub-second staleness bound. A daemon
    # thread beats through those stalls; an os._exit host_loss kills it
    # abruptly, which is exactly how a real machine loss looks.
    hb_stop = threading.Event()

    def _beat():
        while not hb_stop.is_set():
            if integrity.hang_event.is_set():
                # the hang watchdog fired: this host is wedged — stop
                # advertising liveness so peers reclassify it as lost
                return
            try:
                em.heartbeat()
            except Exception:
                pass
            hb_stop.wait(min(0.2, args.hb_timeout / 4))

    threading.Thread(target=_beat, name="elastic-heartbeat",
                     daemon=True).start()
    # rendezvous: wait for the full initial world before entering
    deadline = time.time() + 60.0
    while len(em.hosts()) < args.world and time.time() < deadline:
        time.sleep(0.05)

    coord = FileCoordinator(os.path.join(args.root, "coord"), job_id="sim",
                            host=args.host,
                            stale_after=max(5.0, 4 * args.hb_timeout))
    mgr = CheckpointManager(os.path.join(args.root, "ckpt", args.host),
                            max_to_keep=4, use_async=False)
    world = args.world

    def _degrees(hosts):
        # full world trains on a 2-wide data mesh; any shrink drops to 1,
        # so a host loss exercises the R=2 -> R=1 residual remap
        return {"data": 2 if len(hosts) >= world else 1}

    # Bootstrap collective-schedule fingerprint: every host hashes the
    # canonical collective schedule of the program it is ABOUT to run and
    # cross-checks it through the coordinator before the first step (and
    # again after every elastic remesh). A --desync-schedule host hashes
    # the integrity-check variant instead — a realistic skew (one host
    # thinks this is a check step) whose schedules genuinely diverge.
    from ..analysis.schedule import ScheduleMismatch, program_fingerprint
    x0, y0 = _tiny_batches()[0]
    fp_closed = trainer.staged_jaxpr(x0, y0,
                                     do_check=args.desync_schedule)
    fps = {"train-step": program_fingerprint(fp_closed, trainer.mesh)}

    runtime = ElasticRuntime(
        em, coordinator=coord,
        remesh_fn=data_parallel_remesh_fn(
            os.path.join(args.root, "reshard", args.host),
            degrees_fn=_degrees),
        max_remeshes=args.max_remeshes,
        poll=0.1, stabilize_polls=3, stabilize_timeout=30.0,
        barrier_timeout=60.0,
        schedule_fingerprints=fps)

    def _write_result(out: dict):
        results_dir = os.path.join(args.root, "results")
        os.makedirs(results_dir, exist_ok=True)
        tmp = os.path.join(results_dir, f".{args.host}.tmp")
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, os.path.join(results_dir, args.host + ".json"))

    with contextlib.ExitStack() as stack:
        for spec in args.fault:
            kind, _, at = spec.partition(":")
            stack.enter_context(faults.inject(kind, at_step=int(at)))
        try:
            res = run_resilient(trainer, loader, args.steps, manager=mgr,
                                save_every=1, elastic=runtime,
                                hang_timeout=args.hang_timeout or None,
                                hang_exit=HOST_HANG_EXIT)
        except faults.HostLost:
            # abrupt machine death: no deregister, no flush, no result
            os._exit(HOST_LOSS_EXIT)
        except ScheduleMismatch as e:
            # the bootstrap fingerprint exchange found rank disagreement:
            # abort with the diffed report instead of wedging into the
            # collective hang it predicts (the watchdog never fires)
            _write_result({
                "host": args.host,
                "exit_code": SCHEDULE_MISMATCH_EXIT,
                "status": "schedule_mismatch",
                "schedule_diff": e.diff,
                "telemetry": telemetry.get_registry().to_dict(),
            })
            mgr.close()
            hb_stop.set()
            em.close()
            return SCHEDULE_MISMATCH_EXIT

    snap = telemetry.get_registry().to_dict()
    out = {
        "host": args.host,
        "exit_code": res.exit_code,
        "status": res.status,
        "steps_done": res.steps_done,
        "last_step": res.last_step,
        "loss": None if res.loss is None else float(res.loss),
        "restarts": res.restarts,
        "remeshes": res.remeshes,
        "barrier_steps": res.barrier_steps,
        "disagreements": _counter_total(
            snap, "elastic_step_disagreements_total"),
        "residual_dropped_norm": _counter_total(
            snap, "elastic_residual_dropped_norm_total"),
        "data_degree_final": int(trainer.mesh.shape.get("data", 1)),
        "telemetry": snap,
    }
    _write_result(out)
    mgr.close()
    hb_stop.set()
    em.close()
    return res.exit_code


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

class SimCluster:
    """Launch and supervise N simulated hosts sharing one cluster root."""

    def __init__(self, root: str, n_hosts: int = 3, np_spec: str = "2:3",
                 steps: int = 24, hb_timeout: float = 1.0,
                 step_delay: float = 0.15, seed: int = 7,
                 hang_timeout: float = 0.0):
        self.root = os.path.abspath(root)
        self.n_hosts = n_hosts
        self.np_spec = np_spec
        self.steps = steps
        self.hb_timeout = hb_timeout
        self.step_delay = step_delay
        self.seed = seed
        self.hang_timeout = hang_timeout
        os.makedirs(self.root, exist_ok=True)

    def host_ckpt_dir(self, i: int) -> str:
        return os.path.join(self.root, "ckpt", _host_name(i))

    def seed_divergent(self, steps_by_host: Dict[int, int]):
        """Pre-seed per-host checkpoint dirs to different steps (the
        divergence the restore barrier must reconcile)."""
        for i, upto in steps_by_host.items():
            seed_checkpoints(self.host_ckpt_dir(i), upto, seed=self.seed)

    def _spawn(self, i: int, faults_for: List[tuple],
               desync: bool = False) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "paddle_tpu.resilience.hostsim",
               "--root", self.root, "--host", _host_name(i),
               "--world", str(self.n_hosts), "--np", self.np_spec,
               "--steps", str(self.steps), "--seed", str(self.seed),
               "--hb-timeout", str(self.hb_timeout),
               "--step-delay", str(self.step_delay)]
        if self.hang_timeout:
            cmd += ["--hang-timeout", str(self.hang_timeout)]
        if desync:
            cmd += ["--desync-schedule"]
        for kind, at in faults_for:
            cmd += ["--fault", f"{kind}:{at}"]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)

    def run(self, faults: Optional[Dict[int, List[tuple]]] = None,
            timeout: float = 300.0,
            desync_hosts: Optional[set] = None) -> dict:
        """Run the cluster to completion. ``faults`` maps host index ->
        [(kind, at_step), ...]; ``desync_hosts`` is a set of host indices
        launched with ``--desync-schedule``. Returns per-host exit codes,
        parsed result JSONs (None for dead hosts), and the host-loss
        count."""
        faults = faults or {}
        desync_hosts = desync_hosts or set()
        procs = {i: self._spawn(i, faults.get(i, []),
                                desync=i in desync_hosts)
                 for i in range(self.n_hosts)}
        deadline = time.time() + timeout
        exit_codes: Dict[str, Optional[int]] = {}
        stderr: Dict[str, str] = {}
        for i, proc in procs.items():
            budget = max(1.0, deadline - time.time())
            try:
                _, err = proc.communicate(timeout=budget)
            except subprocess.TimeoutExpired:
                proc.kill()
                _, err = proc.communicate()
            exit_codes[_host_name(i)] = proc.returncode
            stderr[_host_name(i)] = (err or "")[-4000:]
        results: Dict[str, Optional[dict]] = {}
        for i in range(self.n_hosts):
            h = _host_name(i)
            path = os.path.join(self.root, "results", h + ".json")
            try:
                with open(path) as f:
                    results[h] = json.load(f)
            except (OSError, ValueError):
                results[h] = None
        hosts_lost = sum(1 for c in exit_codes.values()
                         if c == HOST_LOSS_EXIT)
        hosts_hung = sum(1 for c in exit_codes.values()
                         if c == HOST_HANG_EXIT)
        return {"exit_codes": exit_codes, "results": results,
                "hosts_lost": hosts_lost, "hosts_hung": hosts_hung,
                "stderr": stderr}


if __name__ == "__main__":
    sys.exit(worker_main())
