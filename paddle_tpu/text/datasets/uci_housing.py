"""UCI Housing regression dataset (reference:
python/paddle/text/datasets/uci_housing.py:34 — 14 whitespace-separated
columns, per-feature normalization over the full set, 80/20 train/test
split).
"""
from __future__ import annotations

import numpy as np

from ...io.dataset import Dataset
from ...utils.download import DATA_HOME, get_path_from_url

URL = "https://archive.ics.uci.edu/ml/machine-learning-databases/housing/housing.data"
MD5 = "d4accdce7a25600298819f8e28e8d593"
FEATURE_NUM = 14
TRAIN_RATIO = 0.8


class UCIHousing(Dataset):
    """Samples: (np.array(13 features, float32), np.array([price]))."""

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        if data_file is None:
            assert download, "data_file not set and download disabled"
            data_file = get_path_from_url(URL, DATA_HOME + "/uci_housing",
                                          decompress=False)
        data = np.loadtxt(data_file).reshape(-1, FEATURE_NUM)
        # normalize features (not the target) by max/min/mean over all rows
        maxs, mins, means = (data.max(0), data.min(0), data.mean(0))
        for i in range(FEATURE_NUM - 1):
            data[:, i] = (data[:, i] - means[i]) / (maxs[i] - mins[i])
        split = int(data.shape[0] * TRAIN_RATIO)
        self.data = (data[:split] if self.mode == "train"
                     else data[split:]).astype("float32")

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)
