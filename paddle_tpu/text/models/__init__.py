from .gpt import (  # noqa: F401
    GPTBlock, GPTForPretraining, GPTLMHead, GPTModel, gpt_1p3b,
    gpt_pipeline_descs, gpt_tiny)
