"""paddle_tpu.incubate — experimental features (reference:
python/paddle/incubate/)."""
from .moe import ExpertFFN, MoELayer, top2_gating  # noqa: F401
from . import asp  # noqa: F401,E402
from . import checkpoint  # noqa: F401,E402
from .operators import softmax_mask_fuse_upper_triangle  # noqa: F401,E402
from .optimizer import LookAhead, ModelAverage  # noqa: F401,E402
