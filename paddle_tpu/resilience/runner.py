"""Preemption-safe resilient training loop.

``run_resilient`` wraps a ParallelTrainer step loop with every recovery
path the platform's production story needs (reference capabilities:
fleet/elastic.py relaunch-on-membership-change, incubate auto_checkpoint
transparent resume, the launcher's SIGTERM drain):

- **auto-resume** — on entry, restores the newest VALID checkpoint
  (params, optimizer state, comm-error residuals, NaN-guard counters,
  data-epoch cursor, global RNG key) and continues from the next step.
- **graceful preemption** — SIGTERM/SIGINT set a flag; the loop finishes
  the in-flight step, writes a final checkpoint, and returns the
  conventional exit code (143 / 130) so the scheduler can tell a drained
  worker from a crash.
- **in-process restart** — a ``faults.SimulatedCrash`` (the injected
  kill -9 mid-commit) unwinds to the loop, which restores and replays;
  bounded by ``max_restarts``.
- **elastic restart** — when an ElasticManager observes a membership
  change (``ElasticStatus.RESTART``), the loop checkpoints and returns
  exit code 75 (EX_TEMPFAIL: re-exec me). With an
  ``elastic.ElasticRuntime`` instead (``reenter=True``) the RESTART is
  handled *in place*: drain → commit → bounded remesh/reshard →
  coordinated restore barrier → continue, with the data cursor taken
  from the restored checkpoint (never rewound to zero). ``elastic=True``
  constructs an ElasticManager from the ``PADDLE_ELASTIC_*`` env (the
  launcher's contract).
- **faulty input pipeline** — batch fetches run under retry/backoff
  (site ``dataloader_fetch``); the ``nan_grad`` fault is delivered via the
  step's ``grad_taint`` operand so the in-graph guard — not the runner —
  does the skipping. ``host_loss`` raises ``faults.HostLost`` through the
  loop (abrupt death: only a supervisor recovers it); ``host_join``
  materializes a synthetic elastic member.
- **silent corruption** — when the trainer runs with
  ``integrity_check_every`` set, its in-graph replica fingerprint check
  flags diverging replicas; the loop quarantines the outlier
  (``integrity.quarantine_outliers`` majority vote), skips saving the
  corrupt state, and ROLLS BACK through the restore path to the last
  clean checkpoint — bounded by ``max_rollbacks``. Multi-process, the
  outlier host self-evicts (``HostLost``) and the survivors remesh. The
  ``param_flip`` fault injects the corruption deterministically.
- **hang watchdog** — with ``hang_timeout`` set, each step runs under a
  deadline (``integrity.HangWatchdog``). A wedged step cannot be
  interrupted host-side, so on firing the watchdog stops pumping
  heartbeats (peers reclassify this host as lost and remesh) and, with
  ``hang_exit``, hard-exits the process. The ``host_hang`` fault wedges
  a step on purpose (``integrity.simulate_hang``).
"""
from __future__ import annotations

import dataclasses
import signal
from typing import Any, Iterable, List, Optional

import jax
import numpy as np

from . import faults
from .retry import call_with_retry

__all__ = ["RunResult", "run_resilient"]

# exit-code conventions: 128+signum for signal-terminated (what a shell
# reports), EX_TEMPFAIL for "transient — restart me"
EXIT_OK = 0
EXIT_SIGTERM = 128 + signal.SIGTERM   # 143
EXIT_SIGINT = 128 + signal.SIGINT     # 130
EXIT_RESTART = 75                     # os.EX_TEMPFAIL


@dataclasses.dataclass
class RunResult:
    exit_code: int
    status: str            # completed | sigterm | sigint | restart
    steps_done: int        # global steps completed, resumed ones included
    last_step: int         # global index of the last completed step (-1: none)
    loss: Optional[float]
    restarts: int          # in-process SimulatedCrash recoveries
    skipped_steps: int     # NaN-guard skips (from trainer state, total)
    restore_fallbacks: int # corrupt checkpoints skipped during restores
    remeshes: int = 0      # in-place elastic remeshes (runtime lifetime total)
    barrier_steps: List[int] = dataclasses.field(default_factory=list)
                           # common step of each restore-barrier entry
    divergences: int = 0   # fingerprint-check replica divergences observed
    hosts_quarantined: int = 0   # outlier replicas/hosts quarantined
    rollback_steps: List[int] = dataclasses.field(default_factory=list)
                           # checkpoint step each divergence rolled back to
    hangs: int = 0         # hang-watchdog firings


class _StopFlag:
    """Signal → flag. Handlers only record the signum; the loop acts at
    the next step boundary so the final checkpoint is never torn by the
    handler itself."""

    def __init__(self):
        self.signum: Optional[int] = None
        self._prev = {}

    def _handler(self, signum, frame):
        self.signum = signum

    def install(self):
        for s in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev[s] = signal.signal(s, self._handler)
            except ValueError:      # not the main thread — run unguarded
                pass

    def uninstall(self):
        for s, h in self._prev.items():
            signal.signal(s, h)
        self._prev = {}


def _rng_key_data() -> np.ndarray:
    from ..framework import random as _random
    return np.asarray(jax.random.key_data(_random._state.key))


def _set_rng_key_data(data):
    from ..framework import random as _random
    _random._state.key = jax.random.wrap_key_data(
        jax.numpy.asarray(np.asarray(data), dtype=np.uint32))


def _meta(step: int, epoch: int, batch: int) -> dict:
    return {"step": np.asarray(step), "epoch": np.asarray(epoch),
            "batch": np.asarray(batch), "rng": _rng_key_data()}


def _save(manager, trainer, step: int, epoch: int, batch: int) -> bool:
    return manager.save(step, {"trainer": trainer.state,
                               "meta": _meta(step, epoch, batch)})


def _checked(trainer) -> bool:
    """Whether the step that just ran executed the fingerprint-check
    program (the trainer's cadence counter landed on a check step)."""
    ce = int(getattr(trainer, "integrity_check_every", 0) or 0)
    return bool(ce) and int(getattr(trainer, "_steps_run", 0)) % ce == 0


def _restore(manager, trainer):
    """Newest-valid restore; returns (step, epoch, batch) of the restored
    cursor or None when starting fresh. Falls back past torn checkpoints
    (counted by the manager)."""
    if manager is None:
        return None
    template = {"trainer": trainer.state, "meta": _meta(0, 0, 0)}
    kw = {}
    if getattr(manager, "deep_every", 0):
        # tiered layout: prefer the newest deep-verified anchor, fall
        # back through the cheap tiers
        kw["prefer_deep"] = True
    restored = manager.restore(template=template, **kw)
    if restored is None:
        return None
    trainer.state = restored["trainer"]
    meta = restored["meta"]
    _set_rng_key_data(meta["rng"])
    return (int(meta["step"]), int(meta["epoch"]), int(meta["batch"]))


def run_resilient(trainer, loader: Iterable, steps: int,
                  manager=None, save_every: int = 1,
                  deep_every: int = 0,
                  elastic=None, lr: Optional[float] = None,
                  max_restarts: int = 2,
                  handle_signals: bool = True,
                  max_rollbacks: int = 2,
                  hang_timeout: Optional[float] = None,
                  hang_exit: Optional[int] = None) -> RunResult:
    """Run ``steps`` training steps with checkpoint/resume, signal drain,
    retry-wrapped fetches, and fault-injection hooks. ``loader`` must be
    re-iterable with a deterministic order (the epoch/batch cursor
    fast-forwards it on resume).

    ``max_rollbacks`` bounds divergence-quarantine rollbacks; past the
    bound — or when divergence strikes before anything was committed —
    the run proceeds on the corrupt state rather than live-lock, but
    stops checkpointing it until a later check step passes clean.
    ``hang_timeout`` (seconds) arms a :class:`integrity.HangWatchdog`
    around each step; ``hang_exit`` makes a firing hard-exit the process
    with that code (the supervisor observes it — hostsim's hang path).

    ``deep_every=M`` makes every M-th save a deep one (per-array content
    digests) and the rest cheap (file CRCs only) — the hierarchical-tier
    cadence, forwarded to the manager. With an async-commit manager the
    loop also registers a ``dirty_probe`` so a quarantine verdict that
    lands while a snapshot is in flight suppresses its commit."""
    from .. import telemetry
    from ..telemetry import flight as _flight
    from ..telemetry import tracing as _tracing
    from . import integrity
    from ..distributed.fleet.elastic import ElasticManager, ElasticStatus
    tel = telemetry.enabled()
    _flight.install_signal_handler()   # SIGUSR2 -> dump (main thread only)
    if elastic is True:
        elastic = ElasticManager()
    # an elastic object that re-enters in place (elastic.ElasticRuntime)
    # vs a plain manager whose RESTART means "exit 75, get relaunched"
    runtime = elastic if getattr(elastic, "reenter", False) else None
    stop = _StopFlag()
    if handle_signals:
        stop.install()
    restarts = 0
    rollbacks = 0
    divergences = 0
    quarantined = 0
    # live state is known-divergent and was NOT rolled back (nothing
    # restorable, or the rollback budget ran out): keep training but
    # never checkpoint it — a save would launder the corruption into a
    # "clean" restore. Cleared when a later check step passes clean or
    # a rollback restores verified state.
    dirty = False
    if manager is not None:
        if deep_every and hasattr(manager, "deep_every"):
            manager.deep_every = int(deep_every)
        if hasattr(manager, "dirty_probe"):
            # consulted by the committer at COMMIT time: a quarantine
            # verdict landing while a snapshot is in flight suppresses
            # that commit (the tainted state never reaches disk)
            manager.dirty_probe = lambda: dirty
    rollback_steps: List[int] = []
    step, epoch, batch = 0, 0, 0
    last_loss = None
    watchdog = None
    if hang_timeout:
        # ElasticRuntime has no heartbeat of its own — the membership
        # heartbeat lives on its wrapped manager
        beat_src = getattr(elastic, "manager", elastic)
        watchdog = integrity.HangWatchdog(
            hang_timeout,
            heartbeat_fn=getattr(beat_src, "heartbeat", None),
            # the flight dump happens INSIDE on_fire — before a hang_exit
            # hard-exits the process, so the ring survives as a file
            on_fire=lambda s: _flight.dump("hang_watchdog", step=s),
            exit_code=hang_exit).start()

    def _result(exit_code, status, loss=None):
        return RunResult(
            exit_code=exit_code, status=status,
            steps_done=step, last_step=step - 1,
            loss=loss, restarts=restarts,
            skipped_steps=trainer.skipped_steps(),
            restore_fallbacks=getattr(manager, "restore_fallbacks_total", 0),
            remeshes=runtime.remeshes if runtime is not None else 0,
            barrier_steps=(list(runtime.barrier_steps)
                           if runtime is not None else []),
            divergences=divergences, hosts_quarantined=quarantined,
            rollback_steps=list(rollback_steps),
            hangs=watchdog.fired if watchdog is not None else 0)

    def _enter(template_comm=None):
        """(Re)entry through the runtime's restore barrier; plain
        newest-valid restore without a runtime. Returns the restored
        (step, epoch, batch) cursor or None."""
        if runtime is None:
            return _restore(manager, trainer)
        state_t = dict(trainer.state)
        if template_comm is not None:
            # the drain checkpoint was written on the PRE-remesh mesh:
            # restore its residuals into matching buffers, remap after
            state_t["comm_err"] = template_comm
        template = {"trainer": state_t, "meta": _meta(0, 0, 0)}
        restored = runtime.enter(manager, template)
        if restored is None:
            return None
        rt = dict(restored["trainer"])
        rest_comm = rt.pop("comm_err", {})
        new_state = dict(trainer.state)
        new_state.update(rt)
        trainer.state = new_state
        from .elastic import remap_comm_err
        remap_comm_err({k: np.asarray(jax.device_get(v))
                        for k, v in rest_comm.items()}, trainer)
        meta = restored["meta"]
        _set_rng_key_data(meta["rng"])
        return (int(meta["step"]), int(meta["epoch"]), int(meta["batch"]))

    def _resume():
        nonlocal step, epoch, batch, dirty
        cur = _enter()
        if cur is not None:
            dirty = False  # restored state comes from a committed save
            step, epoch, batch = cur[0] + 1, cur[1], cur[2]
            if tel:
                telemetry.counter(
                    "resilience_resumes_total",
                    "runs that resumed from a checkpoint").inc()

    def _iter_from_cursor():
        """Fresh iterator fast-forwarded to the saved batch cursor."""
        it = iter(loader)
        for _ in range(batch):
            try:
                next(it)
            except StopIteration:
                return iter(loader)
        return it

    try:
        _resume()
        it = _iter_from_cursor()
        while step < steps:
            if faults.fires("sigterm", step, site="runner_loop"):
                signal.raise_signal(signal.SIGTERM)
            if faults.fires("host_loss", step, site="runner_loop"):
                raise faults.HostLost(f"injected host_loss at step {step}")
            if faults.fires("host_join", step, site="runner_loop") and \
                    hasattr(elastic, "simulate_join"):
                elastic.simulate_join()
            if stop.signum is not None:
                _flight.dump("drain", step=step)
                if manager is not None and step > 0 and not dirty:
                    _save(manager, trainer, step - 1, epoch, batch)
                    manager.wait_until_finished()
                sig = stop.signum
                return _result(
                    128 + sig,
                    "sigterm" if sig == signal.SIGTERM else "sigint",
                    loss=last_loss)
            if elastic is not None:
                st = elastic.watch()
                if st == ElasticStatus.RESTART:
                    if manager is not None and step > 0 and not dirty:
                        _save(manager, trainer, step - 1, epoch, batch)
                        manager.wait_until_finished()
                    # pre-remesh residual buffers, captured so the drain
                    # checkpoint restores into old-mesh shapes
                    old_comm = trainer.state.get("comm_err", {}) \
                        if runtime is not None else None
                    if runtime is not None and runtime.on_restart(trainer):
                        cur = _enter(template_comm=old_comm)
                        if cur is not None:
                            dirty = False
                            step, epoch, batch = cur[0] + 1, cur[1], cur[2]
                        # else: coordinated fresh start on a joiner —
                        # keep the live cursor, never rewind on RESTART
                        it = _iter_from_cursor()
                        continue
                    return _result(EXIT_RESTART, "restart", loss=last_loss)

            def _fetch():
                nonlocal it, epoch, batch
                faults.maybe_raise("data_fetch", step=step,
                                   site="dataloader_fetch",
                                   msg=f"injected data_fetch at step {step}")
                try:
                    return next(it)
                except StopIteration:
                    epoch += 1
                    batch = 0
                    it = iter(loader)
                    return next(it)

            # one trace per step (tail-sampled: kept only when slow or
            # when the step diverged / crashed)
            tr_step = _tracing.start_trace("train_step", step=step)
            sp_fetch = tr_step.span("fetch") if tr_step is not None else None
            inputs, labels = call_with_retry(
                _fetch, site="dataloader_fetch", tries=3, base_delay=0.01)
            if sp_fetch is not None:
                sp_fetch.end()

            taint = float("nan") if faults.fires(
                "nan_grad", step, site="train_step") else None
            flip = faults.fire_spec("param_flip", step, site="train_step")
            if flip is not None:
                # deterministic SDC: one mantissa bit on one replica —
                # only the in-graph fingerprint check can see it
                integrity.inject_param_flip(trainer, seed=flip.seed,
                                            step=step)
            try:
                if watchdog is not None:
                    watchdog.arm(step)
                sp_run = (tr_step.span("step") if tr_step is not None
                          else None)
                try:
                    if faults.fires("host_hang", step, site="train_step"):
                        # wedge like a stuck collective; released by the
                        # watchdog firing (or its timeout backstop)
                        integrity.simulate_hang()
                    # ambient span: engine stage/ckpt-snapshot child
                    # spans attach under this step
                    with _tracing.use_span(sp_run):
                        last_loss = trainer.train_step(
                            inputs, labels, lr=lr, grad_taint=taint)
                finally:
                    if sp_run is not None:
                        sp_run.end()
                    if watchdog is not None:
                        watchdog.disarm()
                diverged = (trainer.consume_divergence()
                            if hasattr(trainer, "consume_divergence")
                            else [])
                if diverged:
                    # taint IMMEDIATELY: an async snapshot of this state
                    # may already be in flight — the commit-time probe
                    # must see dirty before the committer reaches it.
                    # Cleared below only by a verified rollback restore.
                    dirty = True
                    divergences += 1
                    _flight.dump("divergence", step=step,
                                 extra={"leaves": [str(x)
                                                   for x in diverged]})
                    q = integrity.quarantine_outliers(
                        trainer, leaves=diverged, elastic=elastic)
                    quarantined += q["quarantined"]
                    if q["action"] == "self_evict":
                        # this host carries the corrupt replica: die so the
                        # survivors' elastic runtime remeshes around us
                        raise faults.HostLost(
                            f"quarantined after replica divergence at "
                            f"step {step}: {diverged}")
                    if rollbacks < max_rollbacks:
                        # never checkpoint the corrupt state; rewind to the
                        # last clean step through the restore path
                        rollbacks += 1
                        cur = _enter()
                        rollback_steps.append(
                            cur[0] if cur is not None else -1)
                        if cur is not None:
                            dirty = False
                            if tr_step is not None:
                                tr_step.close("divergence",
                                              rollback_to=cur[0])
                            step, epoch, batch = cur[0] + 1, cur[1], cur[2]
                            it = _iter_from_cursor()
                            continue
                        # nothing restorable (divergence before the first
                        # commit): keep the live cursor and state, but
                        # dirty — restarting from (0,0,0) would checkpoint
                        # the corrupt state at step 0 and replay the whole
                        # run on it
                        dirty = True
                    else:
                        # rollback budget exhausted: proceed (observably —
                        # the divergence counters keep climbing) but never
                        # save the corrupt state
                        dirty = True
                elif dirty and _checked(trainer):
                    dirty = False  # a later check step came back clean
                batch += 1
                if manager is not None and not dirty and (
                        step % save_every == 0 or step == steps - 1):
                    _save(manager, trainer, step, epoch, batch)
                if tr_step is not None:
                    tr_step.close("divergence" if diverged else "ok")
                step += 1
            except faults.SimulatedCrash:
                if tr_step is not None:
                    tr_step.close("failed")
                restarts += 1
                if tel:
                    telemetry.counter(
                        "resilience_restarts_total",
                        "in-process crash recoveries").inc()
                if restarts > max_restarts:
                    raise
                step, epoch, batch = 0, 0, 0
                _resume()
                it = _iter_from_cursor()

        if manager is not None:
            manager.wait_until_finished()
        loss_val = None if last_loss is None else float(
            jax.device_get(last_loss))
        skipped = trainer.skipped_steps()
        if tel:
            telemetry.gauge(
                "resilience_steps_skipped",
                "steps the NaN guard skipped (from trainer state)"
            ).set(skipped)
        return _result(EXIT_OK, "completed", loss=loss_val)
    finally:
        if watchdog is not None:
            watchdog.stop()
        if handle_signals:
            stop.uninstall()
