"""paddle_tpu.nn.functional — functional API surface
(reference: python/paddle/nn/functional/__init__.py)."""
from .activation import (  # noqa: F401
    celu, elu, elu_, gelu, glu, gumbel_softmax, hardshrink, hardsigmoid, hardswish,
    hardtanh, leaky_relu, log_sigmoid, log_softmax, maxout, mish, prelu, relu,
    relu6, relu_, rrelu, selu, sigmoid, silu, softmax, softplus, softshrink,
    softmax_, softsign, swish, tanh, tanh_, tanhshrink, thresholded_relu)
from .common import (  # noqa: F401
    alpha_dropout, bilinear, class_center_sample, cosine_similarity, dropout,
    dropout2d, dropout3d, embedding, fold, interpolate, label_smooth, linear,
    one_hot, pad, sequence_mask, unfold, upsample)
from .conv import (  # noqa: F401
    conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d, conv3d_transpose)
from .extension import diag_embed, gather_tree, temporal_shift  # noqa: F401
from .loss import (  # noqa: F401
    binary_cross_entropy, binary_cross_entropy_with_logits,
    cosine_embedding_loss, cross_entropy, ctc_loss, dice_loss,
    fused_linear_cross_entropy, hinge_embedding_loss, hsigmoid_loss,
    kl_div, l1_loss, log_loss, margin_ranking_loss, mse_loss, nll_loss,
    npair_loss, sigmoid_focal_loss, smooth_l1_loss, softmax_with_cross_entropy,
    square_error_cost, triplet_margin_loss)
from .norm import (  # noqa: F401
    batch_norm, group_norm, instance_norm, layer_norm, local_response_norm,
    normalize)
from .pooling import (  # noqa: F401
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d, avg_pool1d,
    avg_pool2d, avg_pool3d, max_pool1d, max_pool2d, max_pool3d)
from .vision import (  # noqa: F401
    affine_grid, channel_shuffle, grid_sample, pixel_shuffle, pixel_unshuffle)
from .attention import scaled_dot_product_attention  # noqa: F401
