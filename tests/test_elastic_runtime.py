"""Elastic multi-host runtime: coordinated restore barrier, remesh +
reshard, comm_err residual remapping, runner re-entry, and the fleet
ElasticManager satellites (stale reaping, close(), np parsing)."""
import json
import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, telemetry
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.engine import ParallelTrainer
from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.resilience import faults, run_resilient
from paddle_tpu.resilience.elastic import (CoordinatorTimeout,
                                           ElasticRuntime, FileCoordinator,
                                           coordinated_restore,
                                           data_parallel_remesh_fn,
                                           remap_comm_err, reshard_trainer)
from paddle_tpu.telemetry import aggregate


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    yield
    faults.reset()


@pytest.fixture()
def fresh_registry():
    """Isolated telemetry registry, enabled for the test."""
    old_reg = telemetry.get_registry()
    old_on = telemetry.enabled()
    reg = telemetry.Registry()
    telemetry._set_registry(reg)
    telemetry.enable(True)
    yield reg
    telemetry._set_registry(old_reg)
    telemetry.enable(old_on)


def _counter_total(reg, name):
    series = reg.to_dict().get(name, {}).get("series", {})
    return sum(series.values())


def _mlp_trainer(data=2, grad_sync="int8", seed=7):
    paddle.seed(seed)
    mesh = build_mesh({"data": data})

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(8, 16)
            self.l2 = nn.Linear(16, 4)

        def forward(self, x):
            return self.l2(nn.functional.relu(self.l1(x)))

    model = MLP()
    opt = paddle.optimizer.Momentum(0.05, momentum=0.9,
                                    parameters=model.parameters())
    return ParallelTrainer(model, opt,
                           lambda out, y: jnp.mean((out - y) ** 2),
                           mesh=mesh, grad_sync=grad_sync, grad_sync_block=8)


def _loader(n=4, batch=8, seed=3):
    rng = np.random.RandomState(seed)
    return [(rng.randn(batch, 8).astype(np.float32),
             rng.randn(batch, 4).astype(np.float32)) for _ in range(n)]


# ---------------------------------------------------------------------------
# fleet ElasticManager satellites
# ---------------------------------------------------------------------------

class TestElasticManager:
    def test_parse_np(self):
        assert ElasticManager._parse_np("4") == (4, 4)
        assert ElasticManager._parse_np("2:3") == (2, 3)
        assert ElasticManager._parse_np("0") == (0, 0)

    def _mgr(self, tmp_path, host="a", np_spec="2:3", timeout=30.0):
        return ElasticManager(elastic_server=str(tmp_path), job_id="j",
                              np=np_spec, host=host, timeout=timeout)

    def test_watch_hold_restart_exit_transitions(self, tmp_path):
        em = self._mgr(tmp_path, host="a", np_spec="2:3")
        em.register()
        try:
            # n=1 < np_min -> RESTART (someone must relaunch the fleet)
            assert em.watch() == ElasticStatus.RESTART
            b = self._mgr(tmp_path, host="b")
            b.register()
            assert em.watch() == ElasticStatus.HOLD          # n=2 in range
            c = self._mgr(tmp_path, host="c")
            c.register()
            assert em.watch() == ElasticStatus.HOLD          # n=3 == np_max
            d = self._mgr(tmp_path, host="d")
            d.register()
            assert em.watch() == ElasticStatus.RESTART       # n=4 > np_max
            for m in (b, c, d):
                m.close()
            # only the observer left, and it stops advertising itself:
            em.deregister()
            assert em.watch() == ElasticStatus.EXIT          # n=0
        finally:
            em.close()

    def test_stale_member_reaped_in_watch(self, tmp_path):
        em = self._mgr(tmp_path, host="a", np_spec="1:2", timeout=5.0)
        em.register()
        try:
            stale = em._member_file("ghost")
            with open(stale, "w") as f:
                f.write("1")
            past = time.time() - 60
            os.utime(stale, (past, past))
            assert em.hosts() == ["a"]        # filtered...
            assert os.path.exists(stale)
            assert em.watch() == ElasticStatus.HOLD
            assert not os.path.exists(stale)  # ...and now reaped
        finally:
            em.close()

    def test_close_restores_signal_handlers_and_deregisters(self, tmp_path):
        prev_term = signal.getsignal(signal.SIGTERM)
        prev_int = signal.getsignal(signal.SIGINT)
        em = self._mgr(tmp_path, host="a", np_spec="1:1")
        em.register()
        assert signal.getsignal(signal.SIGTERM) == em.signal_handler
        member = em._member_file()
        assert os.path.exists(member)
        em.close()
        assert signal.getsignal(signal.SIGTERM) == prev_term
        assert signal.getsignal(signal.SIGINT) == prev_int
        assert not os.path.exists(member)
        em.close()  # idempotent


# ---------------------------------------------------------------------------
# FileCoordinator
# ---------------------------------------------------------------------------

class TestFileCoordinator:
    def test_allgather_three_participants(self, tmp_path):
        hosts = ["a", "b", "c"]
        results = {}

        def _run(h, i):
            coord = FileCoordinator(str(tmp_path), job_id="j", host=h,
                                    poll=0.01)
            results[h] = coord.allgather("vals", i, lambda: hosts,
                                         timeout=20.0)

        ts = [threading.Thread(target=_run, args=(h, i))
              for i, h in enumerate(hosts)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        for h in hosts:
            assert results[h] == {"a": 0, "b": 1, "c": 2}

    def test_round_generations_do_not_reuse_values(self, tmp_path):
        hosts = ["a", "b"]
        out = {}

        def _run(h, values):
            coord = FileCoordinator(str(tmp_path), job_id="j", host=h,
                                    poll=0.01)
            out[h] = [coord.allgather("step", v, lambda: hosts, timeout=20.0)
                      for v in values]

        ts = [threading.Thread(target=_run, args=("a", [10, 20])),
              threading.Thread(target=_run, args=("b", [11, 21]))]
        [t.start() for t in ts]
        [t.join() for t in ts]
        for h in hosts:
            assert out[h][0] == {"a": 10, "b": 11}
            assert out[h][1] == {"a": 20, "b": 21}

    def test_timeout_when_participant_missing(self, tmp_path):
        coord = FileCoordinator(str(tmp_path), job_id="j", host="a",
                                poll=0.01)
        with pytest.raises(CoordinatorTimeout):
            coord.barrier("never", lambda: ["a", "ghost"], timeout=0.3)

    def test_membership_shrink_mid_round_completes(self, tmp_path):
        """hosts_fn is re-read every poll: when a peer dies mid-round and
        drops out of the live set, the round completes without it."""
        live = ["a", "ghost"]
        coord = FileCoordinator(str(tmp_path), job_id="j", host="a",
                                poll=0.01)

        def _shrink():
            time.sleep(0.2)
            live.remove("ghost")

        t = threading.Thread(target=_shrink)
        t.start()
        got = coord.allgather("shrink", 7, lambda: list(live), timeout=20.0)
        t.join()
        assert got == {"a": 7}


# ---------------------------------------------------------------------------
# coordinated restore barrier
# ---------------------------------------------------------------------------

def _tiny_state(v=0.0):
    return {"w": np.full((4,), v, dtype=np.float32)}


def _seed_manager(path, upto):
    mgr = CheckpointManager(str(path), max_to_keep=upto + 1, use_async=False)
    for s in range(upto + 1):
        mgr.save(s, _tiny_state(float(s)))
    mgr.wait_until_finished()
    return mgr


class TestCoordinatedRestore:
    def test_min_reduce_across_divergent_hosts(self, tmp_path,
                                               fresh_registry):
        """host a valid to step 2, b/c only to step 1: everyone restores
        step 1 (the ISSUE acceptance shape, scaled down)."""
        upto = {"a": 2, "b": 1, "c": 1}
        mgrs = {h: _seed_manager(tmp_path / h, s) for h, s in upto.items()}
        hosts = sorted(upto)
        out = {}

        def _run(h):
            coord = FileCoordinator(str(tmp_path / "coord"), job_id="j",
                                    host=h, poll=0.01)
            out[h] = coordinated_restore(mgrs[h], _tiny_state(), coord,
                                         lambda: hosts, timeout=30.0)

        ts = [threading.Thread(target=_run, args=(h,)) for h in hosts]
        [t.start() for t in ts]
        [t.join() for t in ts]
        for h in hosts:
            restored, common = out[h]
            assert common == 1
            np.testing.assert_allclose(np.asarray(restored["w"]),
                                       _tiny_state(1.0)["w"])
        assert _counter_total(fresh_registry,
                              "elastic_restore_barrier_total") == 3
        assert _counter_total(fresh_registry,
                              "elastic_step_disagreements_total") == 3
        for m in mgrs.values():
            m.close()

    def test_fresh_start_when_any_host_empty(self, tmp_path):
        mgr_a = _seed_manager(tmp_path / "a", 2)
        mgr_b = CheckpointManager(str(tmp_path / "b"), use_async=False)
        hosts = ["a", "b"]
        out = {}

        def _run(h, mgr):
            coord = FileCoordinator(str(tmp_path / "coord"), job_id="j",
                                    host=h, poll=0.01)
            out[h] = coordinated_restore(mgr, _tiny_state(), coord,
                                         lambda: hosts, timeout=30.0)

        ts = [threading.Thread(target=_run, args=("a", mgr_a)),
              threading.Thread(target=_run, args=("b", mgr_b))]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert out["a"] == (None, -1)     # a does NOT train ahead on step 2
        assert out["b"] == (None, -1)
        mgr_a.close()
        mgr_b.close()

    def test_restore_divergence_fault_forces_rollback(self, tmp_path):
        mgr = _seed_manager(tmp_path / "a", 2)
        coord = FileCoordinator(str(tmp_path / "coord"), job_id="j",
                                host="a", poll=0.01)
        with faults.inject("restore_divergence"):
            restored, common = coordinated_restore(
                mgr, _tiny_state(), coord, lambda: ["a"], timeout=10.0)
        assert common == 1                # reported 2-1, min-reduced to 1
        np.testing.assert_allclose(np.asarray(restored["w"]),
                                   _tiny_state(1.0)["w"])
        mgr.close()

    def test_divergence_beyond_retention_raises(self, tmp_path):
        # a's retention no longer holds the common step -> loud failure,
        # not silent training on mismatched state
        mgr_a = CheckpointManager(str(tmp_path / "a"), max_to_keep=2,
                                  use_async=False)
        for s in range(5):
            mgr_a.save(s, _tiny_state(float(s)))
        mgr_b = _seed_manager(tmp_path / "b", 1)
        hosts = ["a", "b"]
        errs = {}

        def _run(h, mgr):
            coord = FileCoordinator(str(tmp_path / "coord"), job_id="j",
                                    host=h, poll=0.01)
            try:
                # short timeout: host b restores fine but then waits at the
                # exit barrier for a (which raised) — it must time out, not
                # hold the suite for the full barrier budget
                coordinated_restore(mgr, _tiny_state(), coord,
                                    lambda: hosts, timeout=2.0)
            except (RuntimeError, OSError) as e:
                errs[h] = e

        ts = [threading.Thread(target=_run, args=("a", mgr_a)),
              threading.Thread(target=_run, args=("b", mgr_b))]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert "a" in errs and "retention" in str(errs["a"])
        mgr_a.close()
        mgr_b.close()


# ---------------------------------------------------------------------------
# comm_err residual remap + trainer reshard
# ---------------------------------------------------------------------------

class TestRemesh:
    def test_remap_comm_err_scale_down_counts_dropped_norm(
            self, fresh_registry):
        tr = _mlp_trainer(data=4)
        x, y = _loader(n=1)[0]
        tr.train_step(x, y)
        tr.train_step(x, y)
        old = {k: np.asarray(jax.device_get(v))
               for k, v in tr.state["comm_err"].items()}
        assert all(v.shape[0] == 4 for v in old.values())
        assert any(np.abs(v).sum() > 0 for v in old.values())
        tr.remesh(build_mesh({"data": 2}))
        remap_comm_err(old, tr)
        new = {k: np.asarray(jax.device_get(v))
               for k, v in tr.state["comm_err"].items()}
        for k in old:
            assert new[k].shape[0] == 2
            np.testing.assert_allclose(new[k], old[k][:2], rtol=1e-6)
        dropped = float(np.sqrt(sum(
            float((v[2:].astype(np.float64) ** 2).sum())
            for v in old.values())))
        got = _counter_total(fresh_registry,
                             "elastic_residual_dropped_norm_total")
        assert got == pytest.approx(dropped, rel=1e-5)

    def test_remap_comm_err_shrink_then_grow_round_trip(
            self, fresh_registry):
        """R=2 -> R=1 -> R=2: the surviving prefix row is carried
        bit-exact through BOTH remaps, the rejoined rank starts from
        zero, and the shrink counted exactly the dropped row's norm."""
        tr = _mlp_trainer(data=2)
        x, y = _loader(n=1)[0]
        tr.train_step(x, y)
        tr.train_step(x, y)
        orig = {k: np.asarray(jax.device_get(v))
                for k, v in tr.state["comm_err"].items()}
        assert any(np.abs(v).sum() > 0 for v in orig.values())
        # shrink: host lost, degree 2 -> 1
        tr.remesh(build_mesh({"data": 1}))
        remap_comm_err(orig, tr)
        mid = {k: np.asarray(jax.device_get(v))
               for k, v in tr.state["comm_err"].items()}
        for k in orig:
            assert mid[k].shape[0] == 1
            np.testing.assert_array_equal(mid[k][0], orig[k][0])
        dropped = float(np.sqrt(sum(
            float((v[1:].astype(np.float64) ** 2).sum())
            for v in orig.values())))
        assert _counter_total(
            fresh_registry, "elastic_residual_dropped_norm_total"
        ) == pytest.approx(dropped, rel=1e-5)
        # grow back: replacement host joined, degree 1 -> 2
        tr.remesh(build_mesh({"data": 2}))
        remap_comm_err(mid, tr)
        back = {k: np.asarray(jax.device_get(v))
                for k, v in tr.state["comm_err"].items()}
        for k in orig:
            assert back[k].shape[0] == 2
            np.testing.assert_array_equal(back[k][0], orig[k][0])
            np.testing.assert_array_equal(back[k][1], 0.0)
        # the grow dropped nothing: the counter did not move
        assert _counter_total(
            fresh_registry, "elastic_residual_dropped_norm_total"
        ) == pytest.approx(dropped, rel=1e-5)

    def test_remap_comm_err_scale_up_zero_fills(self):
        tr = _mlp_trainer(data=2)
        x, y = _loader(n=1)[0]
        tr.train_step(x, y)
        old = {k: np.asarray(jax.device_get(v))
               for k, v in tr.state["comm_err"].items()}
        tr.remesh(build_mesh({"data": 4}))
        remap_comm_err(old, tr)
        for k, v in tr.state["comm_err"].items():
            arr = np.asarray(jax.device_get(v))
            assert arr.shape[0] == 4
            np.testing.assert_allclose(arr[:2], old[k], rtol=1e-6)
            np.testing.assert_array_equal(arr[2:], 0.0)

    def test_reshard_trainer_preserves_params_across_meshes(self, tmp_path):
        tr = _mlp_trainer(data=4)
        x, y = _loader(n=1)[0]
        loss0 = float(tr.train_step(x, y))
        p0 = {k: np.asarray(jax.device_get(v))
              for k, v in tr.state["params"].items()}
        slots0 = jax.device_get(tr.state["opt"]["slots"])
        reshard_trainer(tr, build_mesh({"data": 2}), str(tmp_path / "rs"))
        assert tr.mesh.shape["data"] == 2
        for k, v in tr.state["params"].items():
            np.testing.assert_allclose(np.asarray(jax.device_get(v)),
                                       p0[k], rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6),
            jax.device_get(tr.state["opt"]["slots"]), slots0)
        # and the step programs were rebuilt for the new mesh
        loss1 = float(tr.train_step(x, y))
        assert np.isfinite(loss1)
        assert np.isfinite(loss0)


# ---------------------------------------------------------------------------
# runner integration (in-process, single host + synthetic join)
# ---------------------------------------------------------------------------

class _CountingLoader:
    """Re-iterable loader recording the index of every batch handed out,
    so cursor rewinds are observable."""

    def __init__(self, batches):
        self.batches = batches
        self.fetched = []

    def __iter__(self):
        for i, b in enumerate(self.batches):
            self.fetched.append(i)
            yield b


def _runtime_fixture(tmp_path, degrees_fn, max_remeshes=2):
    em = ElasticManager(elastic_server=str(tmp_path / "kv"), job_id="j",
                        np="1:4", host="h0", timeout=10.0)
    em.register()
    runtime = ElasticRuntime(
        em, remesh_fn=data_parallel_remesh_fn(str(tmp_path / "rs"),
                                              degrees_fn=degrees_fn),
        max_remeshes=max_remeshes, poll=0.01, stabilize_polls=2)
    return em, runtime


class TestRunnerElasticReentry:
    def test_host_join_remeshes_in_place_and_completes(self, tmp_path):
        em, runtime = _runtime_fixture(
            tmp_path, lambda hosts: {"data": 2 * len(hosts)})
        tr = _mlp_trainer(data=2)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=3,
                                use_async=False)
        loader = _CountingLoader(_loader(n=4))
        try:
            with faults.inject("host_join", at_step=2):
                res = run_resilient(tr, loader, 6, manager=mgr,
                                    save_every=1, elastic=runtime)
            assert res.exit_code == 0 and res.status == "completed"
            assert res.steps_done == 6
            assert res.remeshes == 1
            assert tr.mesh.shape["data"] == 4
            assert all(v.shape[0] == 4
                       for v in tr.state["comm_err"].values())
            # entry barrier (fresh start) + one re-entry at the drain step
            assert res.barrier_steps == [-1, 1]
            # cursor resumed from the restored checkpoint, NOT rewound:
            # 0,1 trained, then the fast-forward re-consumes 0,1 to reach
            # the saved batch cursor, then training continues 2,3,wrap 0,1.
            # A rewind-to-zero would instead retrain 0,1 (6 fetches total).
            assert loader.fetched == [0, 1, 0, 1, 2, 3, 0, 1]
        finally:
            em.close()
            mgr.close()

    def test_max_remeshes_exhausted_falls_back_to_exit_75(self, tmp_path):
        em, runtime = _runtime_fixture(
            tmp_path, lambda hosts: {"data": 2}, max_remeshes=0)
        tr = _mlp_trainer(data=2)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=3,
                                use_async=False)
        try:
            with faults.inject("host_join", at_step=1):
                res = run_resilient(tr, _loader(), 5, manager=mgr,
                                    save_every=1, elastic=runtime)
            assert res.exit_code == 75 and res.status == "restart"
            assert res.remeshes == 0
            # the drain checkpoint was still committed before giving up
            assert mgr.latest_valid_step() == 0
        finally:
            em.close()
            mgr.close()

    def test_host_loss_fault_unwinds_uncaught(self):
        tr = _mlp_trainer(data=2)
        with faults.inject("host_loss", at_step=1):
            with pytest.raises(faults.HostLost):
                run_resilient(tr, _loader(), 4)

    def test_plain_manager_restart_still_exits_75(self, tmp_path):
        """Back-compat: a reenter-less elastic object keeps the relaunch
        contract (the scheduler re-execs on 75)."""

        class FakeElastic:
            def watch(self):
                return ElasticStatus.RESTART

        tr = _mlp_trainer(data=2)
        res = run_resilient(tr, _loader(), 4, elastic=FakeElastic())
        assert res.exit_code == 75 and res.status == "restart"
        assert res.remeshes == 0 and res.barrier_steps == []


# ---------------------------------------------------------------------------
# per-host telemetry aggregation
# ---------------------------------------------------------------------------

class TestTelemetryAggregation:
    def _snap(self, steps, secs):
        reg = telemetry.Registry()
        reg.counter("steps_total", "steps").inc(steps)
        reg.histogram("step_time_seconds", "t").observe(secs)
        return reg.to_dict()

    def test_merge_keeps_per_host_series_distinct(self):
        merged = aggregate.merge_process_dicts(
            {0: self._snap(10, 0.1), 1: self._snap(10, 0.9)})
        series = merged["steps_total"]["series"]
        assert series == {"process_index=0": 10, "process_index=1": 10}
        hist = merged["step_time_seconds"]["series"]
        # the straggler's step time is still visible, not averaged away
        assert hist["process_index=0"]["sum"] == pytest.approx(0.1)
        assert hist["process_index=1"]["sum"] == pytest.approx(0.9)

    def test_merge_prefixes_existing_labels(self):
        reg = telemetry.Registry()
        reg.counter("grad_sync_bytes_total", "b").inc(5, policy="int8")
        merged = aggregate.merge_process_dicts({3: reg.to_dict()})
        assert merged["grad_sync_bytes_total"]["series"] == {
            "process_index=3,policy=int8": 5}

    def test_gather_registries_single_process(self, fresh_registry):
        fresh_registry.counter("c", "x").inc(2)
        merged = aggregate.gather_registries()
        assert merged["c"]["series"] == {"process_index=0": 2}

    def test_gather_via_coordinator_two_hosts(self, tmp_path):
        hosts = ["a", "b"]
        out = {}

        def _run(h, steps):
            reg = telemetry.Registry()
            reg.counter("steps_total", "steps").inc(steps)
            coord = FileCoordinator(str(tmp_path), job_id="j", host=h,
                                    poll=0.01)
            out[h] = aggregate.gather_via_coordinator(
                coord, lambda: hosts, registry=reg, timeout=20.0)

        ts = [threading.Thread(target=_run, args=("a", 3)),
              threading.Thread(target=_run, args=("b", 5))]
        [t.start() for t in ts]
        [t.join() for t in ts]
        for h in hosts:
            assert out[h]["steps_total"]["series"] == {
                "process_index=0": 3, "process_index=1": 5}


# ---------------------------------------------------------------------------
# full subprocess simulation (beyond the tier-1 chaos scenario)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.multihost(timeout=420)
def test_hostsim_divergent_restore_no_faults(tmp_path):
    """3 subprocess hosts, divergent seeds, NO failures: everyone must
    restore the common step 3 and complete all steps with no remesh."""
    from paddle_tpu.resilience import hostsim
    cluster = hostsim.SimCluster(str(tmp_path), n_hosts=3, np_spec="2:3",
                                 steps=8, hb_timeout=1.0, step_delay=0.05)
    cluster.seed_divergent({0: 5, 1: 3, 2: 3})
    out = cluster.run(timeout=240)
    assert out["hosts_lost"] == 0
    for h, res in out["results"].items():
        assert res is not None, (h, out["stderr"][h])
        assert res["exit_code"] == 0, (h, res)
        assert res["barrier_steps"][0] == 3
        assert res["steps_done"] == 8
        assert res["remeshes"] == 0
