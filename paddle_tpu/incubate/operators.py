"""Incubate fused operators (reference: python/paddle/incubate/operators/
softmax_mask_fuse_upper_triangle.py — CUDA-fused causal-masked softmax
for transformer attention scores).

TPU translation: expressed as mask+softmax in one jit scope — XLA fuses
the mask into the softmax's elementwise pipeline, and the flash-attention
path (ops/pallas) covers the memory-bound fused case end-to-end.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["softmax_mask_fuse_upper_triangle"]


def softmax_mask_fuse_upper_triangle(x):
    """Softmax over the last axis with the strict upper triangle masked
    out (causal attention scores). x: (batch, heads, S_q, S_k)."""
    x = jnp.asarray(x)
    s_q, s_k = x.shape[-2], x.shape[-1]
    causal = jnp.tril(jnp.ones((s_q, s_k), bool))
    neg = jnp.asarray(jnp.finfo(
        x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.float32).min, x.dtype)
    masked = jnp.where(causal, x, neg)
    out = jnp.exp(masked - jnp.max(masked, axis=-1, keepdims=True))
    out = out / jnp.sum(out, axis=-1, keepdims=True)
    # exact zeros on masked positions (softmax of -inf-like values can
    # leave denormals in low precision)
    return jnp.where(causal, out, jnp.zeros((), out.dtype))
