"""paddle_tpu.static.nn — static-graph layer functions.

Reference: python/paddle/static/nn/__init__.py (40 names re-exported from
fluid/layers/nn.py et al). In the reference these append OpDescs + create
persistable parameters in the default Program; here they are ordinary eager/
traceable functions whose parameters are created once per ``name`` in the
global scope (static/__init__.py Scope) so repeated tracing reuses weights
and ``static.save`` persists them.

LoDTensor translation: the reference's sequence_* ops consume LoDTensors
(ragged rows encoded by offset tables — framework/lod_tensor.h). The TPU
encoding is a dense padded batch (batch, max_len, ...) plus an explicit
``length`` vector — static shapes for XLA; masks express the raggedness.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..nn import functional as F
from ..nn import initializer as init_mod

__all__ = [
    "fc", "batch_norm", "embedding", "bilinear_tensor_product", "case",
    "cond", "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "crf_decoding", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "multi_box_head", "nce", "prelu",
    "py_func", "row_conv", "spectral_norm", "switch_case", "while_loop",
    "sparse_embedding", "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_concat", "sequence_first_step", "sequence_last_step",
    "sequence_slice", "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate", "sequence_reverse",
]


# -- parameter creation in the global scope ---------------------------------
def _param(name, shape, dtype="float32", initializer=None, is_bias=False):
    from . import global_scope
    scope = global_scope()
    found = scope.find_var(name)
    if found is not None:
        return found
    if initializer is None:
        initializer = (init_mod.Constant(0.0) if is_bias
                       else init_mod.XavierUniform())
    # Parameter creation must be CONCRETE even when the layer function is
    # being traced by Program.trace / jax.jit: ensure_compile_time_eval makes
    # the initializer (and its global-PRNG split) execute eagerly, so no
    # tracer leaks into the scope or the RNG state.
    with jax.ensure_compile_time_eval():
        value = initializer(tuple(shape), jnp.dtype(dtype))
    scope.var(name, value)
    return value


def _is_traced(*vals):
    """True if any value is a JAX tracer (we are under jit/Program.trace)."""
    return any(isinstance(v, jax.core.Tracer) for v in vals)


def _host_only(op_name, *vals):
    """Raise a clear error when a host-side (ragged, numpy round-trip)
    sequence op is hit under tracing — these ops produce data-dependent
    shapes and cannot be staged into an XLA program (reference LoDTensor
    raggedness has no static-shape equivalent). Use the padded+length
    variants (sequence_pool/sequence_slice/...) inside jit instead."""
    if _is_traced(*vals):
        raise TypeError(
            f"paddle_tpu.static.nn.{op_name} is eager-only: its output shape "
            "depends on runtime data (ragged sequences), which cannot be "
            "staged under jax.jit / Program.trace. Call it outside jit, or "
            "use a padded+mask formulation (e.g. sequence_pool with a "
            "length vector) which is traceable.")


def _uname(prefix):
    from ..framework.naming import unique_name
    return unique_name(prefix)


# -- dense layers ------------------------------------------------------------
def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference static/nn/common.py fc (operators/math/fc.cc)."""
    name = name or _uname("fc")
    lead = int(np.prod(x.shape[num_flatten_dims:]))
    flat = jnp.reshape(x, x.shape[:num_flatten_dims] + (lead,))
    w = _param(f"{name}.w_0", (lead, size))
    out = jnp.matmul(flat, w)
    if bias_attr is not False:
        b = _param(f"{name}.b_0", (size,), is_bias=True)
        out = out + b
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    name = name or _uname("embedding")
    table = _param(f"{name}.w_0", tuple(size), dtype,
                   initializer=init_mod.Normal(0.0, 0.02))
    out = jnp.take(table, jnp.asarray(input), axis=0)
    if padding_idx is not None:
        pad = padding_idx if padding_idx >= 0 else size[0] + padding_idx
        mask = (jnp.asarray(input) != pad)[..., None].astype(out.dtype)
        out = out * mask
    return out


def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                     is_test=False, entry=None, name=None):
    """reference static/nn/common.py sparse_embedding — the PS-backed
    trillion-row table. Dense fallback here (``entry`` admission policies
    then have nothing to gate and are accepted for source compat); the
    distributed PS path with entry-gated row admission lives in
    distributed/ps (DistributedEmbedding(entry=...), csrc/ps native
    store)."""
    if entry is not None:
        from ..distributed.entry import EntryAttr
        if not isinstance(entry, EntryAttr):
            raise ValueError(
                "entry must be a ProbabilityEntry/CountFilterEntry "
                f"(paddle.distributed), got {type(entry).__name__}")
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, name=name)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out_k = x W_k y^T (reference operators/bilinear_tensor_product_op)."""
    name = name or _uname("bilinear")
    w = _param(f"{name}.w_0", (size, x.shape[-1], y.shape[-1]))
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if bias_attr is not False:
        out = out + _param(f"{name}.b_0", (size,), is_bias=True)
    if act:
        out = getattr(F, act)(out)
    return out


def _norm_params(name, c, param_attr, bias_attr):
    scale = _param(f"{name}.w_0", (c,), initializer=init_mod.Constant(1.0))
    bias = _param(f"{name}.b_0", (c,), is_bias=True)
    return scale, bias


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               **kwargs):
    """Stateless inference-style BN over the batch statistics (training-mode
    running stats belong to nn.BatchNorm layers; reference
    static/nn/common.py batch_norm)."""
    name = name or _uname("batch_norm")
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale, bias = _norm_params(name, c, param_attr, bias_attr)
    axes = tuple(i for i in range(input.ndim)
                 if i != (1 if data_layout == "NCHW" else input.ndim - 1))
    mean = jnp.mean(input, axis=axes, keepdims=True)
    var = jnp.var(input, axis=axes, keepdims=True)
    shape = [1] * input.ndim
    shape[1 if data_layout == "NCHW" else -1] = c
    out = (input - mean) / jnp.sqrt(var + epsilon)
    out = out * scale.reshape(shape) + bias.reshape(shape)
    if act:
        out = getattr(F, act)(out)
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, name=None,
              **kwargs):
    """reference static/nn/common.py data_norm — scale-shift by accumulated
    batch statistics (PS CTR models); single-batch form here."""
    return batch_norm(input, act=act, epsilon=epsilon, name=name or
                      _uname("data_norm"), data_layout="NHWC"
                      if input.ndim == 2 else "NCHW")


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    name = name or _uname("group_norm")
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale, bias = _norm_params(name, c, param_attr, bias_attr)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=scale,
                       bias=bias, data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    name = name or _uname("instance_norm")
    c = input.shape[1]
    scale, bias = _norm_params(name, c, param_attr, bias_attr)
    return F.instance_norm(input, weight=scale, bias=bias, eps=epsilon)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    name = name or _uname("layer_norm")
    norm_shape = input.shape[begin_norm_axis:]
    n = int(np.prod(norm_shape))
    w = _param(f"{name}.w_0", (n,), initializer=init_mod.Constant(1.0)) \
        if scale else None
    b = _param(f"{name}.b_0", (n,), is_bias=True) if shift else None
    flat = jnp.reshape(input, input.shape[:begin_norm_axis] + (n,))
    out = F.layer_norm(flat, (n,), weight=w, bias=b, epsilon=epsilon)
    out = jnp.reshape(out, input.shape)
    if act:
        out = getattr(F, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Power-iteration spectral normalization of a weight tensor
    (reference operators/spectral_norm_op)."""
    w = jnp.moveaxis(jnp.asarray(weight), dim, 0)
    mat = w.reshape(w.shape[0], -1)
    u = jnp.ones((mat.shape[0],), mat.dtype)
    for _ in range(max(1, power_iters)):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ mat @ v
    out = w / (sigma + eps)
    return jnp.moveaxis(out, 0, dim)


def prelu(x, mode="all", param_attr=None, name=None):
    name = name or _uname("prelu")
    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        shape = (x.shape[1],)
    else:  # element
        shape = tuple(x.shape[1:])
    alpha = _param(f"{name}.w_0", shape,
                   initializer=init_mod.Constant(0.25))
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return jnp.where(x > 0, x, alpha * x)


# -- convolutions ------------------------------------------------------------
def _conv_param(name, shape):
    return _param(f"{name}.w_0", shape,
                  initializer=init_mod.XavierUniform(fan_in=int(
                      np.prod(shape[1:]))))


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    name = name or _uname("conv2d")
    ks = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = _conv_param(name, (num_filters, cin // groups) + ks)
    b = None if bias_attr is False else _param(f"{name}.b_0", (num_filters,),
                                               is_bias=True)
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", name=None):
    name = name or _uname("conv2d_transpose")
    ks = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = _conv_param(name, (cin, num_filters // groups) + ks)
    b = None if bias_attr is False else _param(f"{name}.b_0", (num_filters,),
                                               is_bias=True)
    out = F.conv2d_transpose(input, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size,
                             data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCDHW", name=None):
    name = name or _uname("conv3d")
    ks = (filter_size,) * 3 if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = input.shape[1]
    w = _conv_param(name, (num_filters, cin // groups) + ks)
    b = None if bias_attr is False else _param(f"{name}.b_0", (num_filters,),
                                               is_bias=True)
    out = F.conv3d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    name = name or _uname("conv3d_transpose")
    ks = (filter_size,) * 3 if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = input.shape[1]
    w = _conv_param(name, (cin, num_filters // groups) + ks)
    b = None if bias_attr is False else _param(f"{name}.b_0", (num_filters,),
                                               is_bias=True)
    out = F.conv3d_transpose(input, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size)
    if act:
        out = getattr(F, act)(out)
    return out


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    """Delegates to vision.ops.deform_conv2d (reference
    operators/deformable_conv_op.cu)."""
    from ..vision.ops import deform_conv2d as _dc
    name = name or _uname("deform_conv2d")
    ks = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    w = _conv_param(name, (num_filters, x.shape[1] // groups) + ks)
    b = None if bias_attr is False else _param(f"{name}.b_0", (num_filters,),
                                               is_bias=True)
    return _dc(x, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Lookahead row convolution (reference operators/row_conv_op.cc —
    DeepSpeech2): out[t] = sum_{k=0..K} in[t+k] * w[k] per channel.
    Input (batch, time, dim)."""
    name = name or _uname("row_conv")
    k = future_context_size + 1
    d = input.shape[-1]
    w = _param(f"{name}.w_0", (k, d),
               initializer=init_mod.Constant(1.0 / k))
    pad = jnp.pad(input, ((0, 0), (0, future_context_size), (0, 0)))
    out = sum(pad[:, i:i + input.shape[1]] * w[i] for i in range(k))
    if act:
        out = getattr(F, act)(out)
    return out


# -- control flow (lax wrappers) ---------------------------------------------
def cond(pred, true_fn=None, false_fn=None, name=None):
    """reference static/nn/control_flow cond → lax.cond (XLA-native
    conditional; both branches traced)."""
    return jax.lax.cond(jnp.asarray(pred).reshape(()), lambda _: true_fn(),
                        lambda _: false_fn(), operand=None)


def case(pred_fn_pairs, default=None, name=None):
    """First true predicate wins (reference control_flow.py case; with no
    default, the last pair's fn is the fallback — reference semantics)."""
    fns = list(pred_fn_pairs)
    if not fns:
        raise ValueError("case: pred_fn_pairs must be non-empty")
    # lax.cond traces BOTH branches, so the no-match terminal must be a
    # traceable fallback, never a raise.
    fallback = default if default is not None else fns[-1][1]

    def build(i):
        if i == len(fns):
            return fallback()
        pred, fn = fns[i]
        return jax.lax.cond(jnp.asarray(pred).reshape(()),
                            lambda _: fn(), lambda _: build(i + 1),
                            operand=None)

    return build(0)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference control_flow.py switch_case → lax.switch."""
    if isinstance(branch_fns, dict):
        max_idx = max(branch_fns)
        fns = [branch_fns.get(i, default or branch_fns[max_idx])
               for i in range(max_idx + 1)]
    else:
        fns = [f for _, f in branch_fns] if isinstance(branch_fns[0], tuple) \
            else list(branch_fns)
    if default is not None:
        fns.append(default)
    idx = jnp.clip(jnp.asarray(branch_index).reshape(()), 0, len(fns) - 1)
    return jax.lax.switch(idx, [lambda _, f=f: f() for f in fns], None)


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    """reference control_flow.py while_loop → lax.while_loop (bounded device
    loop; no per-iteration host sync, unlike the reference's WhileOp
    re-entering the executor)."""
    return jax.lax.while_loop(lambda vs: jnp.asarray(cond_fn(*vs)).reshape(()),
                              lambda vs: tuple(body(*vs)), tuple(loop_vars))


def py_func(func, x, out=None, backward_func=None, skip_vars_in_backward_input=None):
    """reference static/nn/common.py py_func → jax.pure_callback (host
    callback staged into the XLA program)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    if out is None:
        # infer the host function's output spec on zero-filled numpy inputs
        # (cannot trace it — it runs outside XLA)
        probe = func(*[np.zeros(jnp.shape(v),
                                jnp.result_type(v)) for v in xs])
        probes = probe if isinstance(probe, (list, tuple)) else [probe]
        result_shape = [jax.ShapeDtypeStruct(np.shape(o),
                                             np.asarray(o).dtype)
                        for o in probes]
        if not isinstance(probe, (list, tuple)):
            result_shape = result_shape[0]
    else:
        outs = out if isinstance(out, (list, tuple)) else [out]
        result_shape = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype)
                        for o in outs]
        if not isinstance(out, (list, tuple)):
            result_shape = result_shape[0]
    return jax.pure_callback(func, result_shape,
                             *[jnp.asarray(v) for v in xs])


# -- CRF / NCE ---------------------------------------------------------------
def crf_decoding(input, transition, length=None, label=None, name=None):
    """Viterbi decode of a linear-chain CRF (reference
    operators/crf_decoding_op.cc). ``input`` (batch, seq, n_tags) emission
    scores; ``transition`` (n_tags+2, n_tags): rows 0/1 are start/stop.
    Returns best tag path (batch, seq). The reference's per-sequence C++ loop
    becomes a lax.scan over time."""
    emis = jnp.asarray(input)
    trans = jnp.asarray(transition)
    start, stop, pair = trans[0], trans[1], trans[2:]
    b, t, n = emis.shape
    if length is None:
        lens = jnp.full((b,), t)
    else:
        lens = jnp.asarray(length).reshape(-1)

    def step(carry, et_t):
        e_t, t_idx = et_t
        alpha = carry  # (b, n)
        scores = alpha[:, :, None] + pair[None]  # (b, n_prev, n)
        best_prev = jnp.argmax(scores, axis=1)
        alpha_t = jnp.max(scores, axis=1) + e_t
        # beyond a row's length: freeze alpha and thread identity backptrs so
        # pad emissions cannot contaminate the valid prefix's backtrace
        valid = (t_idx < lens)[:, None]
        alpha_t = jnp.where(valid, alpha_t, alpha)
        best_prev = jnp.where(valid, best_prev, jnp.arange(n)[None, :])
        return alpha_t, best_prev

    alpha0 = start[None] + emis[:, 0]
    alpha_T, backptrs = jax.lax.scan(
        step, alpha0, (jnp.moveaxis(emis[:, 1:], 1, 0),
                       jnp.arange(1, t)))
    alpha_T = alpha_T + stop[None]
    last = jnp.argmax(alpha_T, axis=-1)  # (b,)

    def back(carry, bp_t):
        cur = carry
        prev = jnp.take_along_axis(bp_t, cur[:, None], axis=1)[:, 0]
        return prev, cur

    first, path_rev = jax.lax.scan(back, last, backptrs[::-1])
    # path_rev = [tag_{T-1}, ..., tag_1]; final carry = tag_0
    path = jnp.concatenate([first[None], path_rev[::-1]], axis=0)  # (t, b)
    out = jnp.moveaxis(path, 0, 1)
    if length is not None:
        mask = F.sequence_mask(length, maxlen=t, dtype="int64")
        out = out * mask
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=10, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference operators/nce_op.cc):
    binary logistic on the true class + k uniform negatives."""
    name = name or _uname("nce")
    d = input.shape[-1]
    w = _param(f"{name}.w_0", (num_total_classes, d))
    b = _param(f"{name}.b_0", (num_total_classes,), is_bias=True)
    label = jnp.asarray(label).reshape(-1)
    batch = label.shape[0]
    # fresh negatives per step from the framework PRNG (a fixed RandomState
    # would contrast against the same negative set forever); under jit the
    # key folds per-trace — pass seed for reproducible eager sampling
    from ..framework.random import get_rng_key
    key = jax.random.PRNGKey(seed) if seed else get_rng_key()
    negs = jax.random.randint(key, (batch, num_neg_samples), 0,
                              num_total_classes)
    pos_logit = jnp.sum(input * jnp.take(w, label, axis=0), -1) \
        + jnp.take(b, label)
    neg_logit = jnp.einsum("bd,bkd->bk", input, jnp.take(w, negs, axis=0)) \
        + jnp.take(b, negs)
    pos_loss = -jax.nn.log_sigmoid(pos_logit)
    neg_loss = -jnp.sum(jax.nn.log_sigmoid(-neg_logit), axis=-1)
    return (pos_loss + neg_loss)[:, None]


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, offset=0.5, flip=True,
                   kernel_size=1, pad=0, stride=1, name=None, **kwargs):
    """SSD detection head (reference static/nn/common multi_box_head +
    operators/detection/prior_box_op): per feature map, conv loc/conf heads
    + prior boxes. Returns (mbox_locs, mbox_confs, boxes, variances)."""
    name = name or _uname("multi_box_head")
    locs, confs, priors, vars_ = [], [], [], []
    img_h, img_w = image.shape[2], image.shape[3]
    n_in = len(inputs)
    if min_sizes is None:
        # reference ratio schedule
        min_ratio = min_ratio or 20
        max_ratio = max_ratio or 90
        step = int((max_ratio - min_ratio) / max(n_in - 2, 1))
        min_sizes, max_sizes = [base_size * 0.1], [base_size * 0.2]
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = min_sizes[:n_in]
        max_sizes = max_sizes[:n_in]
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        n_prior = 2 + len(ar) * (2 if flip else 1)
        fh, fw = feat.shape[2], feat.shape[3]
        # conv heads
        loc = conv2d(feat, n_prior * 4, kernel_size, stride=stride,
                     padding=pad, name=f"{name}.loc{i}")
        conf = conv2d(feat, n_prior * num_classes, kernel_size, stride=stride,
                      padding=pad, name=f"{name}.conf{i}")
        locs.append(jnp.reshape(jnp.transpose(loc, (0, 2, 3, 1)),
                                (feat.shape[0], -1, 4)))
        confs.append(jnp.reshape(jnp.transpose(conf, (0, 2, 3, 1)),
                                 (feat.shape[0], -1, num_classes)))
        # prior boxes
        sk_w = min_sizes[i] / img_w
        sk_h = min_sizes[i] / img_h
        sk2_w = (max_sizes[i] / img_w) if max_sizes else sk_w
        sk2_h = (max_sizes[i] / img_h) if max_sizes else sk_h
        widths = [sk_w, float(np.sqrt(sk_w * sk2_w))]
        heights = [sk_h, float(np.sqrt(sk_h * sk2_h))]
        for a in ar:
            widths.append(sk_w * float(np.sqrt(a)))
            heights.append(sk_h / float(np.sqrt(a)))
            if flip:
                widths.append(sk_w / float(np.sqrt(a)))
                heights.append(sk_h * float(np.sqrt(a)))
        cy, cx = np.meshgrid((np.arange(fh) + offset) / fh,
                             (np.arange(fw) + offset) / fw, indexing="ij")
        boxes_i = []
        for wd, ht in zip(widths, heights):
            boxes_i.append(np.stack([cx - wd / 2, cy - ht / 2,
                                     cx + wd / 2, cy + ht / 2], -1))
        box = np.clip(np.stack(boxes_i, 2).reshape(-1, 4), 0, 1)
        priors.append(jnp.asarray(box, jnp.float32))
        vars_.append(jnp.full((box.shape[0], 4), 0.1, jnp.float32))
    return (jnp.concatenate(locs, 1), jnp.concatenate(confs, 1),
            jnp.concatenate(priors, 0), jnp.concatenate(vars_, 0))


# -- sequence ops on padded (x, length) --------------------------------------
def _len_mask(x, length, dtype=None):
    t = x.shape[1]
    mask = F.sequence_mask(length, maxlen=t, dtype="float32")
    return mask.astype(dtype or x.dtype)


def sequence_softmax(input, length=None, name=None):
    if length is None:
        return jax.nn.softmax(input, axis=1)
    mask = _len_mask(input, length)
    while mask.ndim < input.ndim:
        mask = mask[..., None]
    neg = jnp.finfo(input.dtype).min
    return jax.nn.softmax(jnp.where(mask > 0, input, neg), axis=1) * mask


def sequence_pool(input, pool_type, length=None, pad_value=0.0):
    pool_type = pool_type.lower()
    if length is None:
        length = jnp.full((input.shape[0],), input.shape[1])
    mask = _len_mask(input, length)
    while mask.ndim < input.ndim:
        mask = mask[..., None]
    ln = jnp.maximum(jnp.asarray(length).astype(input.dtype), 1)
    ln = ln.reshape((-1,) + (1,) * (input.ndim - 2))
    if pool_type == "sum":
        return jnp.sum(input * mask, axis=1)
    if pool_type == "average":
        return jnp.sum(input * mask, axis=1) / ln
    if pool_type == "sqrt":
        return jnp.sum(input * mask, axis=1) / jnp.sqrt(ln)
    if pool_type == "max":
        neg = jnp.finfo(input.dtype).min
        return jnp.max(jnp.where(mask > 0, input, neg), axis=1)
    if pool_type == "first":
        return input[:, 0]
    if pool_type == "last":
        idx = (jnp.asarray(length) - 1).reshape(-1)
        return jnp.take_along_axis(
            input, idx.reshape((-1, 1) + (1,) * (input.ndim - 2)).astype(
                jnp.int32).repeat(input.shape[-1], -1) if input.ndim > 2
            else idx[:, None], axis=1).squeeze(1) if input.ndim > 2 else \
            input[jnp.arange(input.shape[0]), idx]
    raise ValueError(f"unknown pool_type {pool_type}")


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length)


def sequence_concat(input: Sequence, lengths=None, name=None):
    """Concatenate along time; with lengths, packs valid steps contiguously
    (host-side; ragged packing is not XLA-shapeable)."""
    if lengths is None:
        return jnp.concatenate(list(input), axis=1)
    _host_only("sequence_concat", *list(input),
               *[l for l in lengths])
    outs = []
    for b in range(input[0].shape[0]):
        parts = [np.asarray(x[b, :int(l[b])])
                 for x, l in zip(input, lengths)]
        outs.append(np.concatenate(parts, 0))
    maxlen = max(o.shape[0] for o in outs)
    padded = [np.pad(o, [(0, maxlen - o.shape[0])] + [(0, 0)] * (o.ndim - 1))
              for o in outs]
    return jnp.asarray(np.stack(padded))


def sequence_slice(input, offset, length, name=None):
    """Per-row slice [offset, offset+length) along time (reference
    operators/sequence_ops/sequence_slice_op).

    Traceable: under jit the output time dim is the static upper bound
    ``input.shape[1]`` (XLA needs static shapes); positions past ``length``
    are zero-masked. Eagerly the tight ``max(length)`` is used."""
    offset = jnp.asarray(offset).reshape(-1)
    length = jnp.asarray(length).reshape(-1)
    if _is_traced(input, offset, length):
        out_t = input.shape[1]
    else:
        out_t = int(jnp.max(length))
    idx = offset[:, None] + jnp.arange(out_t)[None]
    # clip OOB gathers (default fill mode yields NaN, which the zero mask
    # below would propagate instead of zeroing)
    gathered = jnp.take_along_axis(
        input, idx[..., None].repeat(input.shape[-1], -1) if input.ndim > 2
        else idx, axis=1, mode="clip")
    mask = (jnp.arange(out_t)[None] < length[:, None])
    while mask.ndim < gathered.ndim:
        mask = mask[..., None]
    return gathered * mask.astype(gathered.dtype)


def sequence_expand(x, y, ref_level=-1, length=None, name=None):
    """Repeat each row of x per the ragged row-count of y (reference
    sequence_expand_op). Padded form: length (batch,) gives repeats."""
    reps = jnp.asarray(length).reshape(-1) if length is not None else \
        jnp.full((x.shape[0],), y.shape[1])
    _host_only("sequence_expand", x, reps)
    out = np.repeat(np.asarray(x), np.asarray(reps), axis=0)
    return jnp.asarray(out)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y, length=jnp.full((x.shape[0],), y.shape[1]))


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Pad a packed ragged batch into dense (batch, maxlen, ...) + lengths
    (reference sequence_pad_op). x is a list of variable-length arrays or a
    (rows, dim) packed array + length."""
    if isinstance(x, (list, tuple)):
        _host_only("sequence_pad", *x)
        seqs = [np.asarray(s) for s in x]
    else:
        assert length is not None, "packed input needs length"
        _host_only("sequence_pad", x, length)
        flat = np.asarray(x)
        offs = np.concatenate([[0], np.cumsum(np.asarray(length))])
        seqs = [flat[offs[i]:offs[i + 1]] for i in range(len(length))]
    ml = maxlen or max(s.shape[0] for s in seqs)
    out = np.full((len(seqs), ml) + seqs[0].shape[1:],
                  np.asarray(pad_value), dtype=seqs[0].dtype)
    lens = []
    for i, s in enumerate(seqs):
        out[i, :s.shape[0]] = s[:ml]
        lens.append(min(s.shape[0], ml))
    return jnp.asarray(out), jnp.asarray(lens)


def sequence_unpad(x, length, name=None):
    """Dense padded (batch, maxlen, ...) → list of per-row arrays."""
    _host_only("sequence_unpad", x, length)
    length = np.asarray(length).reshape(-1)
    return [jnp.asarray(np.asarray(x)[i, :int(length[i])])
            for i in range(x.shape[0])]


def sequence_reshape(input, new_dim, name=None):
    return jnp.reshape(input, (input.shape[0], -1, new_dim))


def sequence_scatter(input, index, updates, length=None, name=None):
    """Scatter-add updates at (row, index) positions (reference
    sequence_scatter_op). index (batch, k), updates (batch, k)."""
    idx = jnp.asarray(index)
    upd = jnp.asarray(updates)
    rows = jnp.arange(idx.shape[0])[:, None].repeat(idx.shape[1], 1)
    return jnp.asarray(input).at[rows.reshape(-1),
                                 idx.reshape(-1)].add(upd.reshape(-1))


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """All win_size-grams per row (reference sequence_enumerate_op):
    (batch, t) → (batch, t, win_size) padded with pad_value."""
    x = jnp.asarray(input)
    t = x.shape[1]
    pad = jnp.pad(x, ((0, 0), (0, win_size - 1)),
                  constant_values=pad_value)
    return jnp.stack([pad[:, i:i + t] for i in range(win_size)], axis=-1)


def sequence_reverse(x, length=None, name=None):
    """Reverse valid prefix per row (reference sequence_reverse_op)."""
    if length is None:
        return x[:, ::-1]
    t = x.shape[1]
    length = jnp.asarray(length).reshape(-1)
    idx = jnp.where(jnp.arange(t)[None] < length[:, None],
                    length[:, None] - 1 - jnp.arange(t)[None],
                    jnp.arange(t)[None])
    if x.ndim > 2:
        idx_e = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
        return jnp.take_along_axis(x, jnp.broadcast_to(idx_e, x.shape), axis=1)
    return jnp.take_along_axis(x, idx, axis=1)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, act=None,
                  param_attr=None, bias_attr=None, name=None):
    """Context-window convolution over time (reference sequence_conv_op):
    concat [t+padding_start, ...] context rows then project."""
    name = name or _uname("sequence_conv")
    d = input.shape[-1]
    start = padding_start if padding_start is not None \
        else -(filter_size // 2)
    w = _param(f"{name}.w_0", (filter_size * d, num_filters))
    b = None if bias_attr is False else _param(f"{name}.b_0", (num_filters,),
                                               is_bias=True)
    t = input.shape[1]
    cols = []
    for k in range(filter_size):
        shift = start + k
        if shift < 0:
            sl = jnp.pad(input[:, :t + shift], ((0, 0), (-shift, 0), (0, 0)))
        elif shift > 0:
            sl = jnp.pad(input[:, shift:], ((0, 0), (0, shift), (0, 0)))
        else:
            sl = input
        cols.append(sl)
    ctx = jnp.concatenate(cols, axis=-1)
    out = jnp.einsum("btd,df->btf", ctx, w)
    if b is not None:
        out = out + b
    if act:
        out = getattr(F, act)(out)
    return out
