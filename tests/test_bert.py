"""Tests for the BERT/ERNIE model family (BASELINE.md config 3:
ERNIE/BERT-base AMP). Reference capability: fleet-trained BERT-architecture
encoder; here single-chip + TP-sharded variants with MLM+NSP heads."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.text.models import (BertForPretraining,
                                    BertForSequenceClassification, BertModel,
                                    ErnieModel)


def _tiny(**kw):
    cfg = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
               max_position_embeddings=64, tensor_parallel=False)
    cfg.update(kw)
    return cfg


def test_bert_forward_shapes():
    paddle.seed(0)
    m = BertModel(**_tiny())
    ids = np.random.RandomState(0).randint(0, 128, (2, 16)).astype("int32")
    seq, pooled = m(ids)
    assert seq.shape == (2, 16, 32)
    assert pooled.shape == (2, 32)
    assert ErnieModel is BertModel


def test_bert_attention_is_bidirectional():
    # token at position 0 must see position t>0 (unlike causal GPT):
    # flipping a late token changes the first token's output.
    paddle.seed(0)
    m = BertModel(**_tiny(attn_dropout=0.0, hidden_dropout=0.0))
    m.eval()
    ids = np.ones((1, 8), dtype="int32")
    ids2 = ids.copy()
    ids2[0, 7] = 5
    s1, _ = m(ids)
    s2, _ = m(ids2)
    assert not np.allclose(np.asarray(s1)[0, 0], np.asarray(s2)[0, 0])


@pytest.mark.slow
def test_bert_pretraining_loss_decreases():
    paddle.seed(0)
    m = BertForPretraining(**_tiny(attn_dropout=0.0, hidden_dropout=0.0))
    opt = paddle.optimizer.AdamW(5e-3, parameters=m.parameters())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (4, 16)).astype("int32")
    mlm_labels = np.full((4, 16), -100, dtype="int32")
    mlm_labels[:, ::4] = rng.randint(0, 128, (4, 4))
    nsp_labels = rng.randint(0, 2, (4,)).astype("int32")

    def closure():
        mlm_logits, nsp_logits = m(ids)
        return m.loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels)

    l0 = float(closure())
    for _ in range(10):
        paddle.autograd.backward(m, closure)
        opt.step()
        opt.clear_grad()
    l1 = float(closure())
    assert l1 < l0


def test_bert_tied_decoder_weight():
    m = BertForPretraining(**_tiny())
    assert m.cls.decoder_weight is m.bert.embeddings.word_embeddings.weight


def test_bert_sequence_classification_and_amp():
    paddle.seed(0)
    m = BertForSequenceClassification(num_classes=3, **_tiny())
    ids = np.random.RandomState(0).randint(0, 128, (2, 16)).astype("int32")
    logits = m(ids)
    assert logits.shape == (2, 3)
    with paddle.amp.auto_cast():
        logits_amp = m(ids)
    assert logits_amp.shape == (2, 3)


def test_bert_token_types_change_output():
    paddle.seed(0)
    m = BertModel(**_tiny(attn_dropout=0.0, hidden_dropout=0.0))
    m.eval()
    ids = np.ones((1, 8), dtype="int32")
    tt = np.zeros((1, 8), dtype="int32")
    tt2 = np.ones((1, 8), dtype="int32")
    s1, _ = m(ids, token_type_ids=tt)
    s2, _ = m(ids, token_type_ids=tt2)
    assert not np.allclose(np.asarray(s1), np.asarray(s2))


@pytest.mark.slow
def test_bert_tp_forward_matches_dense():
    """Vocab-sharded TP forward under shard_map must match the dense
    single-device forward (regression: decoder bias/weight pspecs)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.jit.functionalization import functional_call, state_of

    paddle.seed(0)
    build_mesh({"model": 8})
    m = BertForPretraining(tensor_parallel=True, vocab_size=128,
                           hidden_size=64, num_layers=2, num_heads=8,
                           max_position_embeddings=64, attn_dropout=0.0,
                           hidden_dropout=0.0)
    m.eval()
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
    ref_mlm, ref_nsp = m(ids)  # dense fallback (no axis bound)
    params, buffers = state_of(m)
    specs = {n: (p.pspec if p.pspec is not None else P())
             for n, p in m.named_parameters()}
    mesh = Mesh(np.array(jax.devices()).reshape(1, 1, 1, 1, 8),
                ("data", "pipe", "sharding", "sep", "model"))

    def f(params, ids):
        (mlm, nsp), _ = functional_call(m, params, buffers, ids)
        return mlm, nsp

    fm = jax.shard_map(f, mesh=mesh, in_specs=(specs, P()),
                       out_specs=(P(None, None, "model"), P()),
                       check_vma=False)
    mlm, nsp = fm(dict(params), ids)
    np.testing.assert_allclose(np.asarray(mlm), np.asarray(ref_mlm),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(nsp), np.asarray(ref_nsp),
                               rtol=2e-2, atol=2e-2)
