"""Unique-name generator (reference: python/paddle/fluid/unique_name.py)."""
from __future__ import annotations

import threading


class _Namer(threading.local):
    def __init__(self):
        self.counters = {}


_namer = _Namer()


def unique_name(prefix: str = "tmp") -> str:
    idx = _namer.counters.get(prefix, 0)
    _namer.counters[prefix] = idx + 1
    return f"{prefix}_{idx}"


def reset():
    _namer.counters = {}


class guard:
    """Counter save/restore (reference unique_name.guard). The static tier
    replays a Program's trace-time counters on retrace so auto names stay
    stable (fc_0 stays fc_0 instead of minting fc_1).

    ``initial``: counters to install on enter (default: keep current).
    ``commit``: if True, keep the advanced counters on exit (first trace of
    a program must advance the global namer or the NEXT program traced would
    collide on the same names); if False, restore the previous state
    (retraces must not re-advance)."""

    def __init__(self, initial=None, commit=False):
        self._initial = initial
        self._commit = commit

    def __enter__(self):
        self._saved = dict(_namer.counters)
        if self._initial is not None:
            _namer.counters = dict(self._initial)
        return self

    def __exit__(self, *exc):
        if not self._commit:
            _namer.counters = self._saved
        return False
