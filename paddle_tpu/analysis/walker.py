"""Canonical recursive jaxpr walker.

The repo's IR is the jaxpr (SURVEY.md §7: jaxprs + XLA replace
ProgramDesc/Graph), but until this module three call sites each grew
their own partial walker: ``onnx/_trace_writer.py`` (inline dispatch for
pjit/remat/custom_vjp that silently missed ``remat2``),
``static.Program.num_ops`` (top-level equations only), and
``tools/pipeline_flops.py`` (``_sub_jaxprs`` generic param scan). This is
the one shared traversal they all use now: it knows every higher-order
primitive's inner-jaxpr layout (pjit/scan/while/cond/checkpoint/
custom_jvp/custom_vjp/shard_map), tracks the axis names each shard_map
binds, and carries loop trip counts so cost models can price scan bodies
per-iteration (XLA's cost_analysis prices a While body once).

Three entry points:
- ``walk(jaxpr)``       — yield an :class:`EqnSite` for every equation,
  recursively, with path/bound-axes/trip-count context.
- ``subjaxprs(eqn)``    — the inner jaxprs of one equation, labeled and
  classified (call/scan/while/cond), for structure-aware recursion.
- ``inline_target(eqn)``— the single transparently-inlineable body of a
  call-like equation (pjit/jit/remat/checkpoint/custom_jvp/custom_vjp),
  or None — the ONNX converter's dispatch predicate.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

__all__ = [
    "EqnSite", "SubJaxpr", "INLINE_CALL_PRIMS", "unwrap", "inline_target",
    "subjaxprs", "has_inner", "walk", "iter_jaxprs", "count_eqns",
    "source_summary", "SchedNode", "linear_schedule",
]

# call-like primitives whose single inner jaxpr is semantically the
# equation itself (no control flow, no axis binding): safe to inline.
# Spellings across jax versions: remat/remat2/checkpoint, jit/pjit,
# custom_{jvp,vjp}_call[_jaxpr].
INLINE_CALL_PRIMS = frozenset({
    "jit", "pjit", "xla_call", "closed_call", "core_call", "call",
    "remat", "remat2", "checkpoint",
    "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "custom_lin",
})

# params that hold the inline body, in lookup order (pjit/scan use
# "jaxpr", call primitives "call_jaxpr", older custom_vjp "fun_jaxpr")
_INLINE_PARAM_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


def _is_jaxpr(obj) -> bool:
    return hasattr(obj, "eqns") or hasattr(obj, "jaxpr")


def unwrap(obj) -> Tuple[object, list]:
    """(raw jaxpr, consts) from a ClosedJaxpr or raw Jaxpr."""
    if hasattr(obj, "jaxpr"):
        return obj.jaxpr, list(getattr(obj, "consts", ()) or ())
    return obj, []


def inline_target(eqn):
    """Inner jaxpr (ClosedJaxpr or raw) of a transparently-inlineable
    call equation; None for control flow / shard_map / leaf primitives."""
    if eqn.primitive.name not in INLINE_CALL_PRIMS:
        return None
    for k in _INLINE_PARAM_KEYS:
        v = eqn.params.get(k)
        if v is not None and _is_jaxpr(v):
            return v
    return None


@dataclass(frozen=True)
class SubJaxpr:
    """One inner jaxpr of an equation, with traversal semantics.

    kind:  "call" (transparent), "scan" (trips = static length),
           "while" (trips unknown), "cond" (one of mutually-exclusive
           branches), "shard_map" (binds mesh axes), "other".
    trips: per-entry execution count of the body, None when unknown
           (while loops).
    """
    label: str
    jaxpr: object  # raw Jaxpr
    consts: tuple
    kind: str = "call"
    trips: Optional[float] = 1.0


def _label(eqn) -> str:
    name = eqn.primitive.name
    fn_name = eqn.params.get("name")
    if isinstance(fn_name, str) and fn_name:
        return f"{name}:{fn_name}"
    return name


def subjaxprs(eqn) -> Iterator[SubJaxpr]:
    """The inner jaxprs of one equation, labeled and classified."""
    name = eqn.primitive.name
    params = eqn.params
    if name == "scan":
        j, c = unwrap(params["jaxpr"])
        yield SubJaxpr("scan", j, tuple(c), kind="scan",
                       trips=float(params.get("length", 1)))
        return
    if name == "while":
        for key in ("cond_jaxpr", "body_jaxpr"):
            j, c = unwrap(params[key])
            yield SubJaxpr(f"while[{key.split('_')[0]}]", j, tuple(c),
                           kind="while", trips=None)
        return
    if name == "cond":
        for i, br in enumerate(params.get("branches", ())):
            j, c = unwrap(br)
            yield SubJaxpr(f"cond[{i}]", j, tuple(c), kind="cond")
        return
    if name == "shard_map":
        j, c = unwrap(params["jaxpr"])
        yield SubJaxpr("shard_map", j, tuple(c), kind="shard_map")
        return
    inner = inline_target(eqn)
    if inner is not None:
        j, c = unwrap(inner)
        yield SubJaxpr(_label(eqn), j, tuple(c), kind="call")
        return
    # generic fallback: any params value that is (or contains) a jaxpr —
    # keeps the walker total over primitives it has never heard of
    for v in params.values():
        if _is_jaxpr(v):
            j, c = unwrap(v)
            yield SubJaxpr(_label(eqn), j, tuple(c), kind="other")
        elif isinstance(v, (list, tuple)):
            for x in v:
                if _is_jaxpr(x):
                    j, c = unwrap(x)
                    yield SubJaxpr(_label(eqn), j, tuple(c), kind="other")


def has_inner(eqn) -> bool:
    """True when the equation carries any inner jaxpr (higher-order)."""
    for _ in subjaxprs(eqn):
        return True
    return False


@dataclass(frozen=True)
class EqnSite:
    """One equation plus its traversal context."""
    eqn: object
    path: Tuple[str, ...]      # labels of the enclosing call stack
    index: int                 # position within its own jaxpr
    bound_axes: frozenset      # mesh axes bound by enclosing shard_maps
    trips: float               # product of enclosing static trip counts
    in_loop: bool              # inside any scan/while body
    in_branch: bool            # inside a cond branch

    @property
    def depth(self) -> int:
        return len(self.path)

    @property
    def primitive(self) -> str:
        return self.eqn.primitive.name

    def where(self) -> str:
        loc = "/".join(self.path) or "<top>"
        return f"{loc}#{self.index}"


def walk(jaxpr, bound_axes=frozenset(), _path=(), _trips=1.0,
         _in_loop=False, _in_branch=False) -> Iterator[EqnSite]:
    """Yield an EqnSite for every equation, outer-before-inner."""
    raw, _ = unwrap(jaxpr)
    for i, eqn in enumerate(raw.eqns):
        yield EqnSite(eqn, _path, i, bound_axes, _trips, _in_loop,
                      _in_branch)
        for sub in subjaxprs(eqn):
            axes = bound_axes
            if sub.kind == "shard_map":
                mesh = eqn.params.get("mesh")
                axes = bound_axes | set(getattr(mesh, "axis_names", ()))
            trips = _trips * (sub.trips if sub.trips else 1.0)
            yield from walk(
                sub.jaxpr, axes, _path + (sub.label,), trips,
                _in_loop or sub.kind in ("scan", "while"),
                _in_branch or sub.kind == "cond")


def iter_jaxprs(jaxpr, _path=()) -> Iterator[Tuple[Tuple[str, ...], object]]:
    """Yield (path, raw jaxpr) for the program and every nested jaxpr —
    for per-scope analyses (liveness, producer maps)."""
    raw, _ = unwrap(jaxpr)
    yield _path, raw
    for eqn in raw.eqns:
        for sub in subjaxprs(eqn):
            yield from iter_jaxprs(sub.jaxpr, _path + (sub.label,))


@dataclass(frozen=True)
class SchedNode:
    """One step of the linearized program order (see
    :func:`linear_schedule`).

    atomic: the node stands for a whole inner program (scan/while/cond/
    unknown higher-order) billed as one compute block; transparent call
    shells and shard_map never appear — their bodies are flattened in.
    in_ids/out_ids: canonical variable identities for dataflow (opaque
    hashables) — call / shard_map boundary variables are aliased
    through, so a consumer's in_id matches the producer's out_id across
    those boundaries. Identities are namespaced per inlined body
    INSTANCE: jax caches and shares jaxpr bodies across call sites, so
    the same Var object shows up in every instantiation and raw id()
    would weld unrelated call sites together.
    """
    eqn: object
    path: Tuple[str, ...]
    index: int
    bound_axes: frozenset
    trips: float
    atomic: bool
    in_ids: Tuple
    out_ids: Tuple

    @property
    def primitive(self) -> str:
        return self.eqn.primitive.name


_TRANSPARENT_KINDS = ("call", "shard_map")


def linear_schedule(jaxpr) -> list:
    """Linearize a program into the flat equation order a sequential
    executor would run: transparent call shells (pjit/remat/custom_vjp)
    and shard_map are flattened into their bodies with boundary-variable
    aliasing, while control flow (scan/while/cond) stays atomic — one
    node billed as its whole body. This is the schedule the overlap model
    (analysis/cost.py) simulates: equation order IS issue order, and the
    aliased ids give true producer->consumer edges across call shells, so
    a collective issued mid-backward is visibly separated from the
    compute that consumes its result."""
    nodes = []
    alias = {}
    frames = iter(range(1 << 62))  # fresh namespace per inlined body

    def canon(fid, v):
        i = (fid, id(v))
        seen = 0
        while i in alias and seen < 1000:
            i = alias[i]
            seen += 1
        return i

    def is_var(a):
        return hasattr(a, "aval") and not hasattr(a, "val")

    def go(raw, bound_axes, path, trips, fid):
        for i, eqn in enumerate(raw.eqns):
            subs = list(subjaxprs(eqn))
            sub = subs[0] if len(subs) == 1 else None
            if sub is not None and sub.kind in _TRANSPARENT_KINDS:
                inner = sub.jaxpr
                gid = next(frames)
                axes = bound_axes
                if sub.kind == "shard_map":
                    mesh = eqn.params.get("mesh")
                    axes = bound_axes | set(
                        getattr(mesh, "axis_names", ()))
                outer_in = list(eqn.invars)
                inner_in = list(inner.invars)
                # call consts ride first in the outer invars: align the
                # body's invars with the outer TAIL; skip aliasing
                # entirely on an unexpected arity mismatch (the body
                # still linearizes, its inputs just read as ready)
                if len(outer_in) > len(inner_in):
                    outer_in = outer_in[len(outer_in) - len(inner_in):]
                if len(outer_in) == len(inner_in):
                    for ov, iv in zip(outer_in, inner_in):
                        if is_var(ov):
                            alias[(gid, id(iv))] = canon(fid, ov)
                for ov, iv in zip(eqn.outvars, inner.outvars):
                    if is_var(iv):
                        alias[(fid, id(ov))] = canon(gid, iv)
                go(inner, axes, path + (sub.label,), trips, gid)
                continue
            nodes.append(SchedNode(
                eqn=eqn, path=path, index=i, bound_axes=bound_axes,
                trips=trips, atomic=bool(subs),
                in_ids=tuple(canon(fid, a) for a in eqn.invars
                             if is_var(a)),
                out_ids=tuple(canon(fid, v) for v in eqn.outvars)))

    raw, _ = unwrap(jaxpr)
    go(raw, frozenset(), (), 1.0, next(frames))
    return nodes


def count_eqns(jaxpr) -> int:
    """Total equation count, recursively through all inner jaxprs."""
    return sum(1 for _ in walk(jaxpr))


def source_summary(eqn) -> Optional[str]:
    """Best-effort user-code provenance ("file.py:42 (fn)") for an
    equation; None when jax's source-info internals moved."""
    try:
        from jax._src import source_info_util
        s = source_info_util.summarize(eqn.source_info)
        return s or None
    except Exception:
        return None
