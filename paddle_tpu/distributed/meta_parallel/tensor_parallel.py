"""TensorParallel wrapper (reference: fleet/meta_parallel/tensor_parallel.py:25).

On TPU there is nothing to rewrite at wrap time: TP layers already carry
PartitionSpecs; this wrapper 1) validates the mesh has a model axis, 2) seeds
the model-parallel RNG tracker so dropout is consistent across the model
group (reference: parallel_layers/random.py), 3) provides grad sync over the
data axis like DataParallel (the model-axis collectives are inside the
layers / GSPMD).
"""
from __future__ import annotations

from jax import lax

from ...framework.random import get_rng_state_tracker
from ...nn.layer import Layer


class TensorParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        tracker = get_rng_state_tracker()
        if "model_parallel_rng" not in tracker.states_:
            # distinct dropout streams per mp rank for sharded activations,
            # same stream for replicated ones (reference random.py:24)
            tracker.add("model_parallel_rng",
                        100 + hcg.get_model_parallel_rank())

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def sync_gradients(self, grads: dict) -> dict:
        try:
            lax.axis_index("data")
        except Exception:
            return grads
        return {k: None if g is None else lax.pmean(g, "data")
                for k, g in grads.items()}

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
