"""Device-mesh management.

TPU-native replacement for the reference's 4-D communicator topology
(reference: fleet/base/topology.py:36 CommunicateTopology, :117
HybridCommunicateGroup, axis order ["data", "pipe", "sharding", "model"]).
Instead of NCCL rings per axis-subgroup (collective_helper.h), we build ONE
jax.sharding.Mesh whose named axes are the hybrid-parallel axes; XLA derives
every subgroup collective from shardings/axis names.

An extra "sep" (sequence/context-parallel) axis is supported beyond the
reference — used by ring attention (SURVEY.md §5 long-context gap).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: F401

AXES_ORDER = ("data", "pipe", "sharding", "sep", "model")


class _MeshState(threading.local):
    def __init__(self):
        self.mesh: Optional[Mesh] = None


_state = _MeshState()


def build_mesh(degrees: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Create (and set current) a named mesh from per-axis degrees.

    Axis order follows the reference's topology.py ordering so that
    neighboring ranks in the fastest-varying axis ("model") are
    ICI-adjacent — TP traffic rides the fastest links, DP the slowest, the
    same locality reasoning as the reference's ring assignment.
    """
    devices = list(devices if devices is not None else jax.devices())
    shape = [int(degrees.get(a, 1)) for a in AXES_ORDER]
    total = int(np.prod(shape))
    if total != len(devices):
        if total < len(devices):
            devices = devices[:total]
        else:
            raise ValueError(f"mesh degrees {degrees} need {total} devices, "
                             f"have {len(devices)}")
    arr = np.asarray(devices).reshape(shape)
    mesh = Mesh(arr, AXES_ORDER)
    _state.mesh = mesh
    return mesh


def set_mesh(mesh: Mesh):
    _state.mesh = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _state.mesh


def require_mesh() -> Mesh:
    if _state.mesh is None:
        # default: pure data-parallel over all local devices
        return build_mesh({"data": len(jax.devices())})
    return _state.mesh


def named_sharding(*spec) -> NamedSharding:
    return NamedSharding(require_mesh(), P(*spec))


def axis_size(axis: str) -> int:
    mesh = get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


class CommunicateTopology:
    """reference: fleet/base/topology.py:36 — coordinate math over the mesh."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = None
        shape = tuple(dims)
        self._world = int(np.prod(shape))
        self._coords = {}
        for rank in range(self._world):
            self._coords[rank] = tuple(
                int(x) for x in np.unravel_index(rank, shape))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coords = tuple(kwargs[n] for n in self._parallel_names)
        return int(np.ravel_multi_index(coords, tuple(self._dims)))

    def get_coord(self, rank):
        return self._coords[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in self._coords.items() if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All subgroups along `axis_name` (list of rank lists)."""
        axis = self._parallel_names.index(axis_name)
        groups = {}
        for r, c in self._coords.items():
            key = tuple(v for i, v in enumerate(c) if i != axis)
            groups.setdefault(key, []).append(r)
        return [sorted(v) for _, v in sorted(groups.items())]


class HybridCommunicateGroup:
    """reference: fleet/base/topology.py:117 — per-rank view of the 4-D mesh.

    On TPU the "groups" are mesh axes; this object provides the reference's
    rank/degree queries for code (pipeline schedules, TP layers) that needs
    explicit coordinates.
    """

    def __init__(self, topology: CommunicateTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._mp_degree = topology.get_dim("model")
        coord = topology.get_coord(global_rank)
        names = topology.get_hybrid_group_names()
        self._coord = dict(zip(names, coord))

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_rank(self):
        return self._coord["data"]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_rank(self):
        return self._coord["model"]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_stage_id(self):
        return self._coord["pipe"]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_rank(self):
        return self._coord["sharding"]

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_next_rank(self):
        names = self._topo.get_hybrid_group_names()
        coords = dict(self._coord)
        coords["pipe"] = (coords["pipe"] + 1) % self._pp_degree
        return self._topo.get_rank(**coords)

    def get_p2p_prev_rank(self):
        coords = dict(self._coord)
        coords["pipe"] = (coords["pipe"] - 1) % self._pp_degree
        return self._topo.get_rank(**coords)
