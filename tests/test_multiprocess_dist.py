"""True multi-PROCESS distributed tests — the SURVEY §4 translation of the
reference's TestDistBase (tests/unittests/test_dist_base.py:744): spawn
separate OS processes on localhost, initialize the jax.distributed
coordinator (the reference's TCP ncclUniqueId bootstrap analogue,
gen_comm_id_helper.cc:297), and run REAL cross-process collectives.

This exercises the DCN/multi-host code path that the in-process 8-device
virtual mesh cannot: separate runtimes, a coordinator rendezvous, and
collectives spanning process boundaries.
"""
import os
import socket
import subprocess
import sys
import textwrap

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# jax's default CPU collectives cannot cross OS processes; the workers
# select the gloo implementation, so this whole module needs a jax build
# that ships it (probe the flag registry read-only — setting the flag in
# the pytest process would leak into in-process tests).
pytestmark = pytest.mark.skipif(
    "jax_cpu_collectives_implementation" not in getattr(jax.config, "values", {}),
    reason="jax build has no gloo CPU collectives; cross-process "
           "collectives unsupported on CPU")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


WORKER = """
    import os, sys
    import jax
    # the axon plugin ignores JAX_PLATFORMS env — force via config before use
    jax.config.update("jax_platforms", "cpu")
    # default CPU collectives cannot span OS processes; gloo can, and it
    # must be selected before the coordinator rendezvous
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    nproc = int(os.environ["PADDLE_TRAINERS_NUM"])
    import paddle_tpu as paddle
    from paddle_tpu.distributed import env as dist_env
    dist_env.init_parallel_env(
        coordinator_address=os.environ["COORD_ADDR"],
        num_processes=nproc, process_id=rank)
    assert jax.process_count() == nproc, jax.process_count()
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # one CPU device per process -> a 2-device global mesh across processes
    devs = np.array(jax.devices()[:nproc])
    assert len(devs) == nproc, devs
    mesh = Mesh(devs, ("data",))
    from paddle_tpu.distributed import collective

    def f(x):
        return collective.all_reduce(x, group=None)

    fm = jax.shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
                       check_vma=False)
    # global array (2,): process r contributes value (r+1)
    local = jnp.asarray([float(rank + 1)])
    garr = jax.make_array_from_single_device_arrays(
        (nproc,), NamedSharding(mesh, P("data")),
        [jax.device_put(local, jax.local_devices()[0])])
    out = fm(garr)
    got = float(np.asarray(out.addressable_shards[0].data)[0])
    expect = float(sum(range(1, nproc + 1)))
    assert got == expect, (got, expect)
    print(f"rank {rank} psum ok: {got}")
"""


def test_cross_process_allreduce(tmp_path):
    nproc = 2
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(WORKER))
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nproc),
            "COORD_ADDR": f"127.0.0.1:{port}",
            # one cpu device per process
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("cross-process worker timed out")
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-2000:]}"
        assert "psum ok" in out


TRAIN_WORKER = """
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    nproc = int(os.environ["PADDLE_TRAINERS_NUM"])
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import env as dist_env
    dist_env.init_parallel_env(
        coordinator_address=os.environ["COORD_ADDR"],
        num_processes=nproc, process_id=rank)
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.distributed.engine import ParallelTrainer
    from paddle_tpu.distributed.mesh import build_mesh

    # global 2-device mesh spanning the two OS processes
    build_mesh({"data": nproc})
    paddle.seed(11)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    loss_fn = lambda o, y: nn.functional.cross_entropy(o, y)
    tr = ParallelTrainer(net, paddle.optimizer.SGD(
        0.1, parameters=net.parameters()), loss_fn)
    rs = np.random.RandomState(0)
    x = rs.randn(32, 16).astype("float32")
    y = ((x.sum(1) > 0).astype("int64") * 2)
    dp_losses = [float(tr.train_step(x, y)) for _ in range(5)]

    # reference trajectory: plain single-device jit on the SAME global
    # batch with identically-initialized params
    from paddle_tpu.jit.functionalization import functional_call, state_of
    paddle.seed(11)
    net2 = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    params, buffers = state_of(net2)

    @jax.jit
    def step(params):
        def lf(p):
            out, _ = functional_call(net2, p, buffers, jnp.asarray(x))
            return loss_fn(out, jnp.asarray(y))
        loss, g = jax.value_and_grad(lf)(params)
        return loss, {k: v - 0.1 * g[k] for k, v in params.items()}

    ref_losses = []
    for _ in range(5):
        l, params = step(params)
        ref_losses.append(float(l))
    np.testing.assert_allclose(dp_losses, ref_losses, rtol=2e-4)
    print(f"rank {rank} dp-train ok: {dp_losses[-1]:.6f}")
"""


def test_cross_process_dp_training_matches_dense(tmp_path):
    """End-to-end 2-OS-process data-parallel training through
    ParallelTrainer (coordinator rendezvous + cross-process grad pmean),
    trajectory-equal to a single-device dense run — the multi-host DP
    capability of the reference's NCCL trainer (reducer.cc:798) over the
    jax.distributed DCN path."""
    nproc = 2
    port = _free_port()
    script = tmp_path / "train_worker.py"
    script.write_text(textwrap.dedent(TRAIN_WORKER))
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nproc),
            "COORD_ADDR": f"127.0.0.1:{port}",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("cross-process train worker timed out")
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-2000:]}"
        assert "dp-train ok" in out
