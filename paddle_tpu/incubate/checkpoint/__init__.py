"""paddle.incubate.checkpoint (reference:
python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py)."""
from . import auto_checkpoint  # noqa: F401
