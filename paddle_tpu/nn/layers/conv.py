"""Conv layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from .. import functional as F
from ..initializer import KaimingUniform, Uniform, _to_initializer
from ..layer import Layer


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, transpose,
                 stride=1, padding=0, output_padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None):
        super().__init__()
        self._n = n
        self._transpose = transpose
        self.in_channels = in_channels
        self.out_channels = out_channels
        k = (kernel_size,) * n if isinstance(kernel_size, int) else tuple(kernel_size)
        self.kernel_size = k
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.padding_mode = padding_mode
        self.data_format = data_format
        if transpose:
            wshape = (in_channels, out_channels // groups) + k
        else:
            wshape = (out_channels, in_channels // groups) + k
        fan_in = in_channels // groups * int(np.prod(k))
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            initializer=_to_initializer(weight_attr, None) or KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
        else:
            bound = 1.0 / np.sqrt(fan_in)
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                initializer=_to_initializer(bias_attr, None) or Uniform(-bound, bound))

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={self.kernel_size}, stride={self.stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, False,
                         stride, padding, 0, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight.value, self.bias, self.stride,
                        self.padding, self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, False,
                         stride, padding, 0, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight.value, self.bias, self.stride,
                        self.padding, self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, False,
                         stride, padding, 0, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight.value, self.bias, self.stride,
                        self.padding, self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, True,
                         stride, padding, output_padding, dilation, groups,
                         "zeros", weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight.value, self.bias, self.stride,
                                  self.padding, self.output_padding, self.groups,
                                  self.dilation, output_size, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, True,
                         stride, padding, output_padding, dilation, groups,
                         "zeros", weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight.value, self.bias, self.stride,
                                  self.padding, self.output_padding, self.groups,
                                  self.dilation, output_size, self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, True,
                         stride, padding, output_padding, dilation, groups,
                         "zeros", weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight.value, self.bias, self.stride,
                                  self.padding, self.output_padding, self.groups,
                                  self.dilation, output_size, self.data_format)
