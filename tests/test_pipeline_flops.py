"""Pipeline schedule FLOPs regression (VERDICT r4 weak #3: the uniform
schedules burned ~3-4x the ideal FLOPs; round 5 packed fwd+bwd into
single ticks — M+2S-2, was 2(M+S-1) — and cond-gated pre/post + the
fill/drain bubble).

Asserts tools/pipeline_flops.py's jaxpr matmul-FLOPs count (which,
unlike XLA cost_analysis, multiplies scan bodies by trip count) stays
<= 1.5x the remat-matched dense ideal at M=32, S=4 — and that count is
itself an upper bound (cond-max billing; see the tool docstring).
"""
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import pipeline_flops as pf  # noqa: E402  (forces cpu platform itself)


def test_schedule_overhead_within_1p5x_of_remat_ideal():
    M = 32
    rng = np.random.RandomState(0)
    x = rng.randint(0, pf.CFG["vocab_size"], (M * 2, 16)).astype("int32")
    y = rng.randint(0, pf.CFG["vocab_size"], (M * 2, 16)).astype("int32")
    _, tr_remat = pf._build(None, M, 1)
    ideal = pf._step_flops(tr_remat, x, y) / pf.S
    for schedule, bound in (("gpipe", 1.5), ("1f1b", 1.5)):
        got = pf._step_flops(pf._build(schedule, M, pf.S), x, y)
        ratio = got / ideal
        assert ratio <= bound, (
            f"{schedule}: {ratio:.3f}x remat ideal (> {bound}) — the "
            "packed-tick/cond-gate optimizations regressed")


def test_packed_1f1b_tick_count():
    """The scan runs M + 2S - 2 ticks (packed), not 2(M + S - 1)."""
    import jax

    M, S = 8, pf.S
    rng = np.random.RandomState(0)
    x = rng.randint(0, pf.CFG["vocab_size"], (M * 2, 16)).astype("int32")
    y = rng.randint(0, pf.CFG["vocab_size"], (M * 2, 16)).astype("int32")
    tr = pf._build("1f1b", M, S)
    import jax.numpy as jnp
    import jax.tree_util as jtu
    inputs, labels = jnp.asarray(x), jnp.asarray(y)
    step = tr._make_step(jtu.tree_map(tr._leaf_spec, inputs),
                         jtu.tree_map(tr._leaf_spec, labels))
    from paddle_tpu.framework.random import get_rng_key
    jaxpr = jax.make_jaxpr(lambda *a: step(*a))(
        tr.state["params"], tr.state["buffers"], tr.state["opt"],
        tr.state["comm_err"], tr.state["guard"], get_rng_key(),
        0.05, 1.0, inputs, labels)

    lengths = []

    def walk(j):
        for eqn in j.eqns:
            if eqn.primitive.name == "scan":
                lengths.append(eqn.params.get("length"))
            for sub in pf._sub_jaxprs(eqn):
                walk(sub)

    walk(jaxpr.jaxpr)
    assert (M + 2 * S - 2) in lengths, lengths
    assert 2 * (M + S - 1) not in lengths, lengths
