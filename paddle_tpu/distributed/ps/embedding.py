"""DistributedEmbedding — sparse PS-backed embedding inside jitted TPU code.

Capability map (reference): operators/pscore/distributed_lookup_table_op.cc
(worker-side pull), distributed/service/communicator.h:197 (async grad push),
c_embedding / SelectedRows sparse-grad path. TPU-native shape: the lookup is
a jax.pure_callback into the host C++ table (device never materializes the
vocab), and the backward pass pushes gradients with an ordered
jax.experimental.io_callback — the server-side optimizer applies the update
(PS semantics: push IS the optimizer step, so the dense optimizer must not
also own these rows).

Why the ``grad_hook`` parameter exists: the table rows are not jax arrays,
so no *requested* gradient mathematically depends on the lookup's
cotangent — JAX would dead-code-eliminate the backward pass (and with it
the grad push). Threading one trainable scalar through the opaque
custom_vjp forces its backward to run exactly when parameter gradients are
computed; the hook's own gradient is always zero, so it never moves.

Padding: ids < 0 are padding — pulled as zero rows, their grads dropped at
push.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...nn.layer import Layer
from .table import SparseTable


def make_lookup(table: SparseTable):
    """Build lookup(ids, lr, hook) -> (..., dim) with grad-push backward."""
    dim = table.dim

    def _pull(ids):
        def host_pull(ids_np):
            # padded slots (-1) never touch the table: no phantom pulls of
            # key 0 (which would create rows, skew entry-admission counts,
            # and bump LRU stats for a feature that was never seen)
            ids_np = np.asarray(ids_np)
            flat = ids_np.reshape(-1)
            valid = flat >= 0
            emb = np.zeros((flat.size, dim), np.float32)
            if valid.any():
                emb[valid] = np.asarray(
                    table.pull(flat[valid])).reshape(-1, dim)
            return emb.reshape(ids_np.shape + (dim,))

        out = jax.ShapeDtypeStruct(tuple(ids.shape) + (dim,), jnp.float32)
        # pure_callback — deliberately, although pull() is effectful on
        # the table (row creation, admission counts, LRU stats): an
        # ordered io_callback here is a side-effecting HLO that the SPMD
        # partitioner refuses to shard (RET_CHECK: "side-effect HLO
        # cannot have replicated sharding"), crashing any data-parallel
        # lookup at compile time. pure_callback partitions per-shard
        # (each device pulls its own ids — exactly the PS fan-out we
        # want); the cost is that a re-traced/rematted pull may bump
        # admission counts twice — a stats-accuracy wobble, not a value
        # error, since pull() returns the same rows either way. The grad
        # push below stays an ORDERED io_callback: updates must neither
        # dedupe nor reorder.
        return jax.pure_callback(host_pull, out, ids)

    @jax.custom_vjp
    def lookup(ids, lr, hook):
        return _pull(ids) + hook * 0.0

    def fwd(ids, lr, hook):
        return _pull(ids) + hook * 0.0, (ids, lr)

    def bwd(res, g):
        ids, lr = res

        def host_push(ids_np, g_np, lr_np):
            ids_np = np.asarray(ids_np).reshape(-1)
            g_np = np.asarray(g_np).reshape(ids_np.size, dim)
            mask = ids_np >= 0
            if mask.any():
                table.push(ids_np[mask], g_np[mask], float(lr_np))
            return np.zeros((), np.float32)

        jax.experimental.io_callback(
            host_push, jax.ShapeDtypeStruct((), jnp.float32),
            ids, g, lr, ordered=True)
        return (np.zeros(ids.shape, jax.dtypes.float0), jnp.zeros_like(lr),
                jnp.zeros(()))

    lookup.defvjp(fwd, bwd)
    return lookup


class DistributedEmbedding(Layer):
    """Embedding over an unbounded sparse vocabulary stored host-side.

    The table's rows are NOT jax parameters: the host optimizer updates them
    at gradient-push time (``lr`` here), exactly the PS division of labor —
    keep these rows out of the device optimizer. (The only jax parameter is
    the zero ``grad_hook``; see module docstring.)
    """

    def __init__(self, dim: int, optimizer: str = "adagrad", lr: float = 0.05,
                 seed: int = 0, init_range: float = 0.01, pooling=None,
                 table=None, entry=None):
        super().__init__()
        from ...nn.initializer import Constant
        self.dim = dim
        self.lr = lr
        self.pooling = pooling  # None | "sum" | "mean"
        # `table` may be a DistributedSparseTable (service.py): lookups then
        # route pull/push RPCs to the hash-owning PS server — the
        # multi-host reference topology (brpc_ps_client fan-out)
        self.table = table if table is not None else SparseTable(
            dim, optimizer=optimizer, seed=seed, init_range=init_range)
        assert self.table.dim == dim
        if entry is not None:
            # feature-admission gate (reference static.nn.sparse_embedding
            # entry=ProbabilityEntry/CountFilterEntry)
            from ..entry import _AdmissionTable
            self.table = _AdmissionTable(self.table, entry)
        self.grad_hook = self.create_parameter((), initializer=Constant(0.0))
        self._lookup = make_lookup(self.table)

    def forward(self, ids):
        ids = jnp.asarray(ids)
        emb = self._lookup(ids, jnp.asarray(self.lr, jnp.float32),
                           self.grad_hook.value)
        if self.pooling is None:
            return emb
        # pooled bag-of-ids: (B, L) -> (B, dim), padding ids excluded
        mask = (ids >= 0).astype(jnp.float32)[..., None]
        s = jnp.sum(emb * mask, axis=-2)
        if self.pooling == "sum":
            return s
        cnt = jnp.maximum(jnp.sum(mask, axis=-2), 1.0)
        return s / cnt

    def save(self, path: str):
        self.table.save(path)

    def load(self, path: str):
        self.table.load(path)
