"""Collective-exchange micro-benchmark: fp32 / bf16 / int8 / int4 grad sync.

Measures the bucketed compressed exchange (distributed/compressed.py) over
a forced-host-device mesh (or real TPU devices when present) and prints ONE
JSON line:

    {"metric": "int8_vs_fp32_bytes_x", "value": ..., "unit": "x",
     "extra": {per-policy: {wire_bytes_per_rank, ms_per_exchange,
                            buckets, rel_err},
               "int4_vs_fp32_bytes_x": ...,          # expect >= 7
               "per_axis_int4_dcn": {...}}}          # DCN-gated case

Bytes-on-wire come from the analytic ring model in
``compressed.wire_bytes_per_rank`` (what each rank moves for one mean:
all-reduce counts 2(n-1)/n payloads, the quantized figures count both
phases plus every scale exchange — int4 moves nibbles plus bf16 scales).
The per-axis case splits the devices into a 2-axis mesh, marks the outer
axis "dcn" and runs int4 there with an exact fp32 pre-reduction on the
inner ("ici") axis — the DCN-gating deployment shape. Latency is
wall-clock on whatever backend runs — on forced host devices it measures
the code path, not ICI; on TPUs it is the real exchange time.

The ``overlap`` suite instead stages the bench-GPT train step through
``ParallelTrainer`` at bucket counts K=1 and K=``--buckets`` on the data
mesh and reports the analysis overlap model's ``overlap_efficiency`` —
the fraction of collective wire time hidden under backward/optimizer
compute. Bucketed (K>=2) must strictly beat monolithic.

The ``calibrate`` suite is the fitting sweep behind
``telemetry.calibration``: it times a psum size ladder and real
train-step walltimes, runs ``calibration.fit()`` to regress corrected
per-link bandwidth/latency constants and an effective
``peak_flops_per_sec``, persists them to the calibration-DB overlay
(``--calibration-db`` / ``PADDLE_TPU_CALIBRATION_DB``), and reports the
predicted-vs-measured step-time drift before and after the fit (after
must shrink; ``--smoke`` asserts it).

Every suite prints one JSON line at ``schema_version`` 2: the
``calibration`` block carries the run's ``{predicted, measured, drift}``
triples so BENCH_*.json files double as model-accuracy evidence.

Usage:
    python tools/bench_collectives.py                     # defaults
    python tools/bench_collectives.py --numel 4194304 --devices 4 \
        --block 256 --int4-block 64 --bucket-mb 4 --iters 20
    python tools/bench_collectives.py --smoke   # tiny shapes + telemetry
                                                # self-check (CI)
    python tools/bench_collectives.py --suite overlap --json
    python tools/bench_collectives.py --suite calibrate --smoke
"""
from __future__ import annotations

import argparse
import json
import time


def _overlap_trainer(buckets: int, smoke: bool, devices: int, policy: str):
    """The lint_program/bench GPT configuration on a data mesh with the
    given grad-sync bucket count."""
    import numpy as np

    import paddle_tpu as paddle
    from _mesh_setup import data_mesh
    from paddle_tpu import nn
    from paddle_tpu.distributed.engine import ParallelTrainer
    from paddle_tpu.text.models import GPTForPretraining

    if smoke:
        vocab, h, layers, heads, seq, batch = 256, 64, 1, 2, 32, 4
    else:  # the bench.py CPU gpt_base shape
        vocab, h, layers, heads, seq, batch = 1024, 128, 2, 4, 128, 4
    paddle.seed(0)
    model = GPTForPretraining(
        tensor_parallel=False, vocab_size=vocab, hidden_size=h,
        num_layers=layers, num_heads=heads, max_position_embeddings=seq,
        attn_dropout=0.0, hidden_dropout=0.0)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters())
    trainer = ParallelTrainer(
        model, opt,
        lambda logits, lbl: nn.functional.cross_entropy(logits, lbl),
        mesh=data_mesh(devices), grad_sync=policy,
        grad_sync_buckets=buckets)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, seq)).astype("int32")
    labels = rng.randint(0, vocab, (batch, seq)).astype("int32")
    return trainer, ids, labels


def overlap_case(buckets: int, smoke: bool, devices: int,
                 policy: str) -> dict:
    """Stage one train step and run the overlap model over its jaxpr."""
    from paddle_tpu.analysis import cost

    trainer, ids, labels = _overlap_trainer(buckets, smoke, devices, policy)
    closed = trainer.staged_jaxpr(ids, labels)
    out = cost.overlap_summary(closed, trainer.mesh)
    out["buckets"] = [len(b) for b in trainer.grad_sync_bucket_keys]
    return out


def _timed_trainer_steps(trainer, ids, labels, warmup: int,
                         iters: int) -> float:
    """Median per-step wall seconds of real train steps (loss fetch is
    the sync point, like bench.py's _timed_steps)."""
    for _ in range(max(1, warmup)):
        loss = trainer.train_step(ids, labels)
    float(loss)
    times = []
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        loss = trainer.train_step(ids, labels)
        float(loss)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def run_overlap(args) -> None:
    from paddle_tpu.telemetry import calibration

    k = max(2, args.buckets)
    base = overlap_case(1, args.smoke, args.devices, args.policy)
    bucketed = overlap_case(k, args.smoke, args.devices, args.policy)
    eff1 = base["overlap_efficiency"]
    effk = bucketed["overlap_efficiency"]
    if args.smoke:
        assert effk is not None and effk > 0, bucketed
        assert eff1 is None or effk > eff1, (base, bucketed)
    # predicted-vs-measured: the bucketed schedule's modeled makespan vs
    # a couple of real steps of the same trainer configuration
    trainer, ids, labels = _overlap_trainer(k, args.smoke, args.devices,
                                            args.policy)
    dt = _timed_trainer_steps(trainer, ids, labels, warmup=2, iters=3)
    calibration.record("step_time", bucketed["makespan"], dt)
    extra = {"k": k, "devices": args.devices, "policy": args.policy,
             "smoke": bool(args.smoke),
             "overlap_efficiency_k1": eff1,
             "hidden_wire_seconds": (
                 None if effk is None
                 else effk * bucketed["collective_time"])}
    if args.json:
        extra["k1"] = base
        extra[f"k{k}"] = bucketed
    print(json.dumps({
        "schema_version": 2,
        "metric": "grad_sync_overlap_efficiency",
        "value": effk,
        "unit": "frac",
        "vs_baseline": eff1,
        "calibration": calibration.pair("step_time"),
        "extra": extra,
    }))


def run_calibrate(args) -> None:
    """The fitting sweep: measured collectives + measured train steps ->
    calibration.fit() -> overlay DB -> drift before/after."""
    import math
    import os
    import tempfile

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from _mesh_setup import data_mesh
    from paddle_tpu.analysis import cost
    from paddle_tpu.telemetry import calibration

    db_path = args.calibration_db
    if not db_path and args.smoke:
        # keep the smoke self-test hermetic: never write ~/.cache
        db_path = os.path.join(tempfile.mkdtemp(prefix="paddle_calib_"),
                               "calibration_db.json")
    if db_path:
        os.environ["PADDLE_TPU_CALIBRATION_DB"] = db_path
    calibration.clear_cache()

    mesh = data_mesh(args.devices)
    n = mesh.devices.size

    # -- collective ladder: psum wall time across payload sizes ---------
    sizes = ([1 << 12, 1 << 14, 1 << 16] if args.smoke
             else [1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 22])
    coll_samples = []
    for numel in sizes:
        xd = jax.device_put(
            jnp.ones((n, numel), jnp.float32),
            NamedSharding(mesh, P("data", None)))
        jfn = jax.jit(jax.shard_map(
            lambda xs: jax.lax.psum(xs, "data"), mesh=mesh,
            in_specs=P("data", None), out_specs=P(None, None),
            check_vma=False))
        jfn(xd).block_until_ready()
        times = []
        for _ in range(max(2, args.iters)):
            t0 = time.perf_counter()
            jfn(xd).block_until_ready()
            times.append(time.perf_counter() - t0)
        times.sort()
        dt = times[len(times) // 2]
        # the cost model's ring figure for psum: 2(n-1)/n payload bytes
        wire = 2.0 * (n - 1) / n * numel * 4
        coll_samples.append({"link": "ici", "wire_bytes": wire,
                             "seconds": dt})

    # -- compute samples: real steps of the bench-GPT trainer -----------
    trainer, ids, labels = _overlap_trainer(
        max(2, args.buckets), args.smoke, args.devices, args.policy)
    closed = trainer.staged_jaxpr(ids, labels)
    ov_before = cost.overlap_summary(closed, trainer.mesh)
    flops = ov_before["compute_time"] * ov_before["peak_flops"]
    steps = 3 if args.smoke else max(5, args.iters)
    dts = []
    _timed_trainer_steps(trainer, ids, labels, warmup=1, iters=1)
    for _ in range(steps):
        dts.append(_timed_trainer_steps(trainer, ids, labels,
                                        warmup=0, iters=1))
    dts.sort()
    measured = dts[len(dts) // 2]
    compute_samples = [{"flops": flops, "seconds": d} for d in dts]

    drift_before = measured / ov_before["makespan"]
    fitted = calibration.fit(collective_samples=coll_samples,
                             compute_samples=compute_samples,
                             save=True, db_path=db_path)
    # every consumer reads the fitted constants through the same choke
    # points, so re-pricing the identical jaxpr shows the correction
    ov_after = cost.overlap_summary(closed, trainer.mesh)
    drift_after = measured / ov_after["makespan"]
    calibration.record("step_time", ov_after["makespan"], measured)
    links = fitted["entry"].get("links", {}).get("ici", {})
    if links.get("bandwidth_bps"):
        for s in coll_samples:
            calibration.record(
                "collective_ici",
                s["wire_bytes"] / links["bandwidth_bps"]
                + links.get("latency_s", 0.0),
                s["seconds"])
    if args.smoke:
        assert abs(math.log(drift_after)) < abs(math.log(drift_before)), (
            drift_before, drift_after, fitted)
    print(json.dumps({
        "schema_version": 2,
        "metric": "calibration_step_time_drift",
        "value": drift_after,
        "unit": "x",
        "vs_baseline": drift_before,
        "calibration": {
            "step_time": calibration.pair("step_time"),
            "collective_ici": calibration.pair("collective_ici"),
        },
        "extra": {
            "devices": n, "smoke": bool(args.smoke),
            "db_path": fitted["path"],
            "predicted_before_s": ov_before["makespan"],
            "predicted_after_s": ov_after["makespan"],
            "measured_s": measured,
            "n_collective_samples": len(coll_samples),
            "n_compute_samples": len(compute_samples),
            "fitted": fitted["entry"],
        },
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--suite", choices=("exchange", "overlap", "calibrate"),
                    default="exchange",
                    help="exchange: wire bytes/latency per policy; "
                         "overlap: staged-step overlap_efficiency at "
                         "K=1 vs K=--buckets; calibrate: fit corrected "
                         "wire/peak constants into the calibration DB "
                         "from measured collectives + train steps")
    ap.add_argument("--calibration-db", default=None,
                    help="calibrate suite: overlay DB path to write "
                         "(default: PADDLE_TPU_CALIBRATION_DB or the "
                         "user cache overlay; --smoke uses a tempdir)")
    ap.add_argument("--numel", type=int, default=1 << 22,
                    help="total gradient elements (fp32)")
    ap.add_argument("--devices", type=int, default=4,
                    help="forced host device count when no accelerator")
    ap.add_argument("--block", type=int, default=256,
                    help="int8 quantization block")
    ap.add_argument("--int4-block", type=int, default=64,
                    help="int4 quantization block (smaller: 4-bit steps "
                         "are coarse)")
    ap.add_argument("--bucket-mb", type=int, default=4,
                    help="flat bucket size in MiB")
    ap.add_argument("--buckets", type=int, default=4,
                    help="overlap suite: grad-sync bucket count K")
    ap.add_argument("--policy", default="fp32",
                    help="overlap suite: grad_sync policy")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--json", action="store_true",
                    help="overlap suite: include the full per-K overlap "
                         "summaries in the JSON line")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes + telemetry self-check; asserts the "
                         "registry saw the per-policy wire-byte counters "
                         "and (overlap) that K>=2 hides wire time")
    args = ap.parse_args()
    if args.smoke:
        args.numel, args.devices, args.block = 4096, 2, 64
        args.int4_block = 64
        args.iters, args.warmup = 2, 1

    from _mesh_setup import (data_mesh, ensure_repo_on_path,
                             force_host_devices)
    force_host_devices(args.devices)
    ensure_repo_on_path()
    if args.suite == "overlap":
        return run_overlap(args)
    if args.suite == "calibrate":
        return run_calibrate(args)

    import math

    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu import telemetry
    from paddle_tpu.distributed.compressed import (
        QUANTIZED_POLICIES, bucket_sizes, compressed_tree_mean,
        init_residuals, wire_bytes_per_rank)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = data_mesh(args.devices)
    n = mesh.devices.size
    bucket_bytes = args.bucket_mb << 20
    blocks = {"fp32": args.block, "bf16": args.block, "int8": args.block,
              "int4": args.int4_block}
    # one numel for every policy: align to the lcm of the block sizes
    align = n * math.lcm(args.block, args.int4_block)
    numel = ((args.numel + align - 1) // align) * align
    nbuckets = len(bucket_sizes(numel, max(bucket_bytes // 4, align), align))

    rng = np.random.RandomState(0)
    # per-rank distinct gradients, replica-major then sharded over "data"
    g = rng.randn(n, numel).astype(np.float32)
    g_dev = jax.device_put(jnp.asarray(g),
                           NamedSharding(mesh, P("data", None)))
    exact = g.mean(axis=0)

    tel_cm = telemetry.scope(profile=False)
    tel = tel_cm.__enter__()
    reg = tel.registry
    extra = {}

    def run_case(run_mesh, axis, policy, block):
        residuals = ({"g": jnp.zeros((n, numel), jnp.float32)}
                     if (policy in QUANTIZED_POLICIES
                         or (isinstance(policy, dict)
                             and any(p in QUANTIZED_POLICIES
                                     for p in policy.values())))
                     else None)
        dspec = P(tuple(a for a in run_mesh.axis_names), None) \
            if len(run_mesh.axis_names) > 1 else P("data", None)

        def exchange(x, res):
            def f(xs, rs):
                tree = {"g": xs[0]}
                r = {"g": rs["g"][0]} if rs else None
                mean, r = compressed_tree_mean(
                    tree, axis, policy=policy, block=block,
                    bucket_bytes=bucket_bytes, residuals=r)
                out_r = {"g": r["g"][None]} if rs else {}
                return mean["g"][None], out_r

            return jax.shard_map(
                f, mesh=run_mesh,
                in_specs=(dspec, {"g": dspec} if res else {}),
                out_specs=(dspec, {"g": dspec} if res else {}),
                check_vma=False)(x, res if res else {})

        jfn = jax.jit(exchange)
        res_in = residuals if residuals is not None else {}
        gd = jax.device_put(jnp.asarray(g), NamedSharding(run_mesh, dspec))
        out, _ = jfn(gd, res_in)
        for _ in range(args.warmup):
            out, _ = jfn(gd, res_in)
        np.asarray(out)  # sync
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out, _ = jfn(gd, res_in)
        np.asarray(out)
        dt = (time.perf_counter() - t0) / args.iters
        got = np.asarray(out)[0]
        rel = float(np.abs(got - exact).max() /
                    (np.abs(exact).max() + 1e-12))
        return dt, rel

    for policy in ("fp32", "bf16", "int8", "int4"):
        dt, rel = run_case(mesh, "data", policy, blocks[policy])
        wire = wire_bytes_per_rank(numel, n, policy, block=blocks[policy])
        if policy == "fp32":
            # predicted-vs-measured wire time of the plain exchange: the
            # ring model over link_bandwidth/link_latency vs wall clock
            # (on forced host devices this measures the code path, not
            # ICI — still the honest drift of the model on this backend)
            from paddle_tpu.distributed.mesh import (link_bandwidth,
                                                     link_latency)
            from paddle_tpu.telemetry import calibration
            calibration.record(
                "collective_ici",
                wire / link_bandwidth("ici") + link_latency("ici"), dt)
        telemetry.counter(
            "grad_sync_bytes_total",
            "logical wire bytes per rank of the bucketed grad "
            "exchange").inc(wire * args.iters, policy=policy)
        telemetry.histogram(
            "grad_sync_exchange_seconds",
            "one compressed_tree_mean wall time").observe(dt, policy=policy)
        extra[policy] = {
            "wire_bytes_per_rank": wire,
            "ms_per_exchange": round(dt * 1e3, 3),
            "ms_per_bucket": round(dt * 1e3 / nbuckets, 3),
            "buckets": nbuckets,
            "rel_err": rel,
        }

    # per-axis policy (DCN gating): outer "data" axis quantizes int4 (the
    # slow cross-slice hop), inner "model" axis pre-reduces exact fp32
    # (the fast ICI hop). Wire model = the two sequential group exchanges.
    if n >= 4 and n % 2 == 0:
        mesh2 = Mesh(np.asarray(jax.devices()[:n]).reshape(n // 2, 2),
                     ("data", "model"))
        per_axis = {"data": "int4", "model": "fp32"}
        dt, rel = run_case(mesh2, ("data", "model"), per_axis, None)
        wire = (wire_bytes_per_rank(numel, n // 2, "int4",
                                    block=args.int4_block)
                + wire_bytes_per_rank(numel, 2, "fp32"))
        extra["per_axis_int4_dcn"] = {
            "policy": per_axis,
            "wire_bytes_per_rank": wire,
            "ms_per_exchange": round(dt * 1e3, 3),
            "rel_err": rel,
        }

    ratio = (extra["fp32"]["wire_bytes_per_rank"] /
             max(extra["int8"]["wire_bytes_per_rank"], 1e-9))
    ratio4 = (extra["fp32"]["wire_bytes_per_rank"] /
              max(extra["int4"]["wire_bytes_per_rank"], 1e-9))
    extra["int4_vs_fp32_bytes_x"] = round(ratio4, 3)
    extra["telemetry"] = {
        "wire_bytes": {p: reg.get("grad_sync_bytes_total").value(policy=p)
                       for p in ("fp32", "bf16", "int8", "int4")},
        "prometheus_bytes": len(telemetry.prometheus_text(reg)),
    }
    tel_cm.__exit__(None, None, None)
    if args.smoke:
        prom = telemetry.prometheus_text(reg)
        wb = extra["telemetry"]["wire_bytes"]
        assert "grad_sync_bytes_total" in prom, "telemetry missing metric"
        assert wb["int8"] > 0 and wb["fp32"] > wb["int8"], wb
        assert wb["int4"] > 0 and wb["int8"] > wb["int4"], wb
        assert ratio4 >= 7.0, f"int4 must beat fp32 by >=7x, got {ratio4}"
        # the bucketed exchange must hide wire time: K=2 on the CPU mesh
        # shows a strictly positive overlap_efficiency in the schedule
        # model of the staged train step
        ov = overlap_case(2, smoke=True, devices=args.devices,
                          policy="fp32")
        assert ov["overlap_efficiency"] is not None \
            and ov["overlap_efficiency"] > 0, ov
        extra["overlap_smoke"] = {
            "overlap_efficiency": ov["overlap_efficiency"],
            "n_collectives": ov["n_collectives"],
            "buckets": ov["buckets"]}
    from paddle_tpu.telemetry import calibration as _calibration
    print(json.dumps({
        "schema_version": 2,
        "metric": "int8_vs_fp32_bytes_x",
        "value": round(ratio, 3),
        "unit": "x",
        "vs_baseline": 1.0,
        "calibration": _calibration.pair("collective_ici"),
        "extra": {"numel": numel, "devices": n, "block": args.block,
                  "int4_block": args.int4_block,
                  "bucket_mb": args.bucket_mb, "smoke": bool(args.smoke),
                  **extra},
    }))


if __name__ == "__main__":
    main()
