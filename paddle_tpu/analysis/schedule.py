"""Static collective-schedule verifier: prove SPMD programs can't
deadlock before they run (ISSUE 17).

The reference platform's SSA-graph executor keeps multi-device programs
hang-free with graph-level dependency passes; our runtime equivalent was
a hang *watchdog* that fires minutes after a rank has already wedged.
This module closes the gap statically. From any staged jaxpr it extracts
the canonical ordered **collective schedule** — for every collective
equation: kind, named axes, wire dtype, payload bucket (next power of
two of the operand bytes, so padding-insensitive), link class (ici/dcn
via ``distributed.mesh.axis_links``) and control-flow context (the
enclosing scan/while/cond/shard_map stack, with transparent pjit/remat
shells stripped so re-traces don't shift it) — and hashes it into a
stable **schedule fingerprint**.

Three properties are verified on top of the schedule:

1. **Intra-program deadlock-freedom** (rules
   ``collective-order-divergence``, ``collective-in-data-dependent-while``,
   ``rank-dependent-collective-schedule``): cond branches must carry
   identical collective sequences, while bodies containing collectives
   must have provably rank-invariant trip counts (scalar-integer counter
   predicates), and no collective may sit under a predicate tainted by
   ``axis_index`` — divergent rank predicates select different HLO
   collective instructions (different channel ids) and hang every peer
   even when the sequences *look* identical.

2. **Cross-program family consistency** (rule
   ``program-family-schedule-drift``): host-side multi-program caches —
   LocalSGD's sync/no-sync pair, integrity's do_check pair, the
   decode/mixed/verify executor router — register as a
   :class:`ProgramFamily` with a declared rank-invariant host predicate.
   Members are *allowed* to differ (that is the point of the family) iff
   the selector is rank-invariant; undeclared drift is an error.

3. **Cross-rank agreement at runtime** (:func:`crossrank_verify`): a
   cheap bootstrap allgather of per-program fingerprints through the
   ``FileCoordinator`` at trainer start and after every elastic remesh,
   aborting with a per-host diff (``collective_schedule_mismatch_total``)
   instead of wedging until the watchdog deadline.
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .rules import (COLLECTIVE_AXIS_PARAMS, _aval_nbytes, collective_axes,
                    register_rule)
from .walker import source_summary, subjaxprs, unwrap, walk

__all__ = [
    "COLLECTIVE_PRIMS", "SCHEDULE_RULE_IDS", "CollectiveSite",
    "extract_schedule", "fingerprint", "program_fingerprint",
    "format_schedule", "schedule_rows",
    "ProgramFamily", "FamilyContext", "FAMILIES", "register_family",
    "verify_family", "verify_all_families",
    "ScheduleMismatch", "crossrank_verify",
]

# shard_map's check_vma/check_rep rewrite renames psum to psum2 (jax
# 0.4.37): normalize so fingerprints agree across the two trace modes
_PRIM_ALIASES = {"psum2": "psum"}
_AXIS_PARAMS = dict(COLLECTIVE_AXIS_PARAMS)
_AXIS_PARAMS["psum2"] = "axes"

# communicating collectives only: axis_index reads the rank — it moves no
# data and matches no peer, so it is a *taint source*, not a schedule entry
COLLECTIVE_PRIMS = frozenset(_AXIS_PARAMS) - {"axis_index"}

SCHEDULE_RULE_IDS = (
    "collective-order-divergence",
    "collective-in-data-dependent-while",
    "rank-dependent-collective-schedule",
    "program-family-schedule-drift",
)


# ---------------------------------------------------------------------------
# schedule extraction + fingerprint
# ---------------------------------------------------------------------------

def _context(path: Tuple[str, ...]) -> Tuple[str, ...]:
    """The control-flow-relevant slice of a walker path: scan/while/cond/
    shard_map frames only. Transparent call shells (pjit:fn, remat2,
    custom_vjp clones) are dropped so a re-trace under a different
    wrapper stack cannot shift the fingerprint."""
    return tuple(
        lbl for lbl in path
        if lbl in ("scan", "shard_map")
        or lbl.startswith("while[") or lbl.startswith("cond["))


def _bucket(nbytes: float) -> int:
    """Next power of two >= nbytes (0 for empty): padding- and
    micro-batch-jitter-insensitive payload identity."""
    n = int(nbytes)
    if n <= 0:
        return 0
    b = 1
    while b < n:
        b <<= 1
    return b


def _links_for(mesh) -> Dict[str, str]:
    if mesh is None:
        return {}
    try:
        from ..distributed.mesh import axis_links
        return dict(axis_links(mesh))
    except Exception:
        return {}


def _wire_dtype(eqn) -> str:
    if not eqn.invars:
        return "?"
    aval = getattr(eqn.invars[0], "aval", None)
    return getattr(getattr(aval, "dtype", None), "name", "?")


def _coll_axes(eqn) -> tuple:
    """Named axes of a collective, like rules.collective_axes but alias-
    aware (psum2)."""
    if eqn.primitive.name in COLLECTIVE_AXIS_PARAMS:
        return collective_axes(eqn)
    key = _AXIS_PARAMS.get(eqn.primitive.name)
    axes = eqn.params.get(key) if key else None
    if axes is None:
        return ()
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _eqn_key(eqn, links: Dict[str, str], context: Tuple[str, ...]) -> tuple:
    axes = tuple(sorted(_coll_axes(eqn)))
    link = "dcn" if any(links.get(a) == "dcn" for a in axes) else "ici"
    payload = _bucket(sum(_aval_nbytes(v) for v in eqn.invars))
    name = eqn.primitive.name
    return (_PRIM_ALIASES.get(name, name), axes, _wire_dtype(eqn),
            payload, link, context)


@dataclass(frozen=True)
class CollectiveSite:
    """One collective in program order, with its schedule identity.

    ``key()`` is the canonical identity two ranks must agree on for this
    collective to match at runtime; path/eqn_index/source are provenance
    only and excluded from the fingerprint (jax is free to renumber)."""
    kind: str                  # primitive name (psum, all_gather, ...)
    axes: Tuple[str, ...]      # named mesh axes, sorted
    wire_dtype: str            # dtype actually on the wire
    payload_bucket: int        # next-pow2 operand bytes
    link: str                  # "ici" | "dcn"
    context: Tuple[str, ...]   # enclosing scan/while/cond/shard_map stack
    trips: float
    in_loop: bool
    in_branch: bool
    path: str
    eqn_index: int
    source: Optional[str]

    def key(self) -> tuple:
        return (self.kind, self.axes, self.wire_dtype,
                self.payload_bucket, self.link, self.context)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "axes": list(self.axes),
                "wire_dtype": self.wire_dtype,
                "payload_bucket": self.payload_bucket, "link": self.link,
                "context": list(self.context), "trips": self.trips,
                "in_loop": self.in_loop, "in_branch": self.in_branch,
                "path": self.path, "eqn_index": self.eqn_index,
                "source": self.source}


def extract_schedule(closed, mesh=None) -> List[CollectiveSite]:
    """The ordered collective schedule of one staged program. Walk order
    is equation order, outer-before-inner — deterministic for a given
    trace, which is exactly the property the fingerprint leans on."""
    links = _links_for(mesh)
    out: List[CollectiveSite] = []
    for site in walk(closed):
        if site.primitive not in COLLECTIVE_PRIMS:
            continue
        kind, axes, wire, payload, link, context = _eqn_key(
            site.eqn, links, _context(site.path))
        out.append(CollectiveSite(
            kind=kind, axes=axes, wire_dtype=wire, payload_bucket=payload,
            link=link, context=context, trips=site.trips,
            in_loop=site.in_loop, in_branch=site.in_branch,
            path="/".join(site.path) or "<top>", eqn_index=site.index,
            source=source_summary(site.eqn)))
    return out


def fingerprint(schedule: List[CollectiveSite]) -> str:
    """Stable sha256 over the ordered canonical keys. Identical programs
    traced on different hosts (different device ids, different source
    checkouts' line numbers aside — provenance is excluded) agree."""
    blob = json.dumps([list(s.key()) for s in schedule],
                      separators=(",", ":"), default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


def program_fingerprint(closed, mesh=None) -> str:
    return fingerprint(extract_schedule(closed, mesh=mesh))


def schedule_rows(schedule: List[CollectiveSite]) -> List[dict]:
    return [s.to_dict() for s in schedule]


def format_schedule(schedule: List[CollectiveSite]) -> str:
    """The --dump-collectives text table."""
    if not schedule:
        return "  (no collectives)"
    lines = [f"  {'#':>3s} {'kind':<14s} {'axes':<16s} {'wire':<9s} "
             f"{'payload':>10s} {'link':<4s} context"]
    for i, s in enumerate(schedule):
        ctx = "/".join(s.context) or "-"
        lines.append(
            f"  {i:>3d} {s.kind:<14s} {','.join(s.axes) or '-':<16s} "
            f"{s.wire_dtype:<9s} {s.payload_bucket:>10d} {s.link:<4s} "
            f"{ctx}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# program families
# ---------------------------------------------------------------------------

@dataclass
class ProgramFamily:
    """A host-side multi-program cache, declared for verification.

    members maps member name -> zero-arg tracer returning the member's
    ClosedJaxpr (lazy: tracing is deferred to :func:`verify_family`).
    ``selector`` documents the host predicate that picks the member;
    ``rank_invariant`` is the author's declaration that the predicate
    depends only on rank-invariant state (a step counter, a batch-shape
    bucket) — the property that makes divergent member schedules safe."""
    name: str
    selector: str
    rank_invariant: bool
    members: Dict[str, Callable]
    mesh: object = None

    @property
    def primary(self) -> str:
        return next(iter(self.members))


FAMILIES: Dict[str, ProgramFamily] = {}


def register_family(family: ProgramFamily,
                    replace: bool = False) -> ProgramFamily:
    if not family.members:
        raise ValueError(f"family {family.name!r} has no members")
    if family.name in FAMILIES and not replace:
        raise ValueError(f"duplicate program family {family.name!r}")
    FAMILIES[family.name] = family
    return family


@dataclass(frozen=True)
class FamilyContext:
    """Attached to a RuleContext (``ctx.family``) while a family member
    is under analysis, so family-aware rules see the whole family."""
    name: str
    member: str
    primary: str
    selector: str
    rank_invariant: bool
    fingerprints: Tuple[Tuple[str, str], ...]  # ordered (member, fp)


def verify_family(family: ProgramFamily, config=None) -> dict:
    """Trace every member, fingerprint it, and run the schedule rules
    over each with family context attached. Returns a JSON-able result;
    ``ok`` is False when any member carries an error finding."""
    from .rules import RuleContext, run_rules
    closed_members = {name: fn() for name, fn in family.members.items()}
    mesh = family.mesh
    fps = {name: program_fingerprint(c, mesh=mesh)
           for name, c in closed_members.items()}
    members_out, ok = {}, True
    for name, closed in closed_members.items():
        ctx = RuleContext(closed, mesh=mesh, config=config)
        ctx.family = FamilyContext(
            name=family.name, member=name, primary=family.primary,
            selector=family.selector,
            rank_invariant=family.rank_invariant,
            fingerprints=tuple(fps.items()))
        findings = run_rules(closed, config=config,
                             rules=SCHEDULE_RULE_IDS, ctx=ctx)
        errors = [f for f in findings if f.severity == "error"]
        ok = ok and not errors
        members_out[name] = {
            "ok": not errors,
            "fingerprint": fps[name],
            "num_collectives": len(extract_schedule(closed, mesh=mesh)),
            "findings": [f.to_dict() for f in findings],
        }
    return {"family": family.name, "selector": family.selector,
            "rank_invariant": family.rank_invariant, "ok": ok,
            "fingerprints": fps, "members": members_out}


def verify_all_families(config=None) -> Dict[str, dict]:
    return {name: verify_family(fam, config=config)
            for name, fam in FAMILIES.items()}


# ---------------------------------------------------------------------------
# cross-rank runtime agreement
# ---------------------------------------------------------------------------

class ScheduleMismatch(RuntimeError):
    """Raised when hosts disagree on a program's schedule fingerprint.
    ``diff`` maps program name -> {host: fingerprint} for every program
    whose fingerprints diverge."""

    def __init__(self, message: str, diff: Optional[dict] = None):
        super().__init__(message)
        self.diff = dict(diff or {})


def crossrank_verify(coordinator, fingerprints: Dict[str, str], hosts_fn,
                     timeout: float = 60.0,
                     name: str = "schedule_fp") -> dict:
    """Allgather every host's {program: fingerprint} map through the
    FileCoordinator and abort (raise :class:`ScheduleMismatch` with a
    per-host diff) on any disagreement — the bootstrap check that turns
    a would-be collective hang into an immediate diffed failure. Runs at
    trainer start and after every elastic remesh; increments
    ``schedule_verify_total`` per verification and
    ``collective_schedule_mismatch_total`` per diverged program."""
    from .. import telemetry
    # freeze the participant set up front: a peer that detects the
    # mismatch first aborts and DEREGISTERS, and a live hosts_fn would
    # then shrink past it mid-exchange — silently hiding the divergent
    # fingerprint from the slower rank (its value file is still on disk)
    expected = sorted(set(hosts_fn()) | {coordinator.host})
    got = coordinator.allgather(name, dict(fingerprints),
                                lambda: expected, timeout=timeout)
    if telemetry.enabled():
        telemetry.counter(
            "schedule_verify_total",
            "cross-rank schedule-fingerprint verifications").inc()
    programs = set()
    for fps in got.values():
        programs |= set((fps or {}).keys())
    diff = {}
    for prog in sorted(programs):
        vals = {h: (got[h] or {}).get(prog) for h in sorted(got)}
        if len(set(vals.values())) > 1:
            diff[prog] = vals
    if diff:
        if telemetry.enabled():
            telemetry.counter(
                "collective_schedule_mismatch_total",
                "programs whose schedule fingerprints diverged "
                "across hosts").inc(len(diff))
        lines = []
        for prog, vals in diff.items():
            per = ", ".join(f"{h}={str(v)[:12]}" for h, v in vals.items())
            lines.append(f"{prog}: {per}")
        raise ScheduleMismatch(
            "collective schedule fingerprints diverge across hosts — "
            "refusing to start (one rank would emit a different "
            "collective sequence and hang every peer): "
            + "; ".join(lines), diff)
    return got


# ---------------------------------------------------------------------------
# rules 19-22: the static deadlock checks
# ---------------------------------------------------------------------------

def _is_var(a) -> bool:
    return hasattr(a, "aval") and not hasattr(a, "val")


def _collective_keys(jaxpr, bound_axes, links) -> tuple:
    """Ordered canonical keys of every collective in a (sub)jaxpr —
    context-relative, for branch-sequence comparison."""
    keys = []
    for site in walk(jaxpr, bound_axes=bound_axes):
        if site.primitive in COLLECTIVE_PRIMS:
            keys.append(_eqn_key(site.eqn, links, _context(site.path)))
    return tuple(keys)


def _contains_collective(jaxpr, bound_axes=frozenset()) -> bool:
    return any(s.primitive in COLLECTIVE_PRIMS
               for s in walk(jaxpr, bound_axes=bound_axes))


def _has_axis_index(jaxpr) -> bool:
    return any(s.primitive == "axis_index" for s in walk(jaxpr))


def _fmt_seq(keys: tuple, limit: int = 4) -> str:
    if not keys:
        return "none"
    parts = [f"{k[0]}({','.join(k[1]) or '-'}:{k[2]})"
             for k in keys[:limit]]
    if len(keys) > limit:
        parts.append(f"+{len(keys) - limit} more")
    return " ".join(parts)


@register_rule("collective-order-divergence", "error")
def collective_order_divergence(ctx):
    """cond branches carrying different collective sequences: ranks
    taking different branches emit mismatched collectives — every peer
    of the first divergent collective hangs. SPMD cond predicates are
    usually uniform, but nothing enforces it; the only safe shapes are
    identical branch schedules or collective-free branches."""
    links = _links_for(ctx.mesh)
    seen = set()
    for site in ctx.sites:
        if site.primitive != "cond":
            continue
        branches = tuple(
            _collective_keys(sub.jaxpr, site.bound_axes, links)
            for sub in subjaxprs(site.eqn))
        if len(branches) < 2 or len(set(branches)) <= 1:
            continue
        dedup = (source_summary(site.eqn), branches)
        if dedup in seen:
            continue
        seen.add(dedup)
        yield ctx.finding(
            site, "cond branches carry different collective sequences "
                  f"({' | '.join(_fmt_seq(b) for b in branches)}): a "
                  "rank taking a different branch emits a mismatched "
                  "collective and every peer hangs — make the branch "
                  "schedules identical or hoist the collectives out of "
                  "the cond")


def _counter_cond(cond_jaxpr) -> bool:
    """True when the while predicate is a pure scalar-integer/bool
    computation (the fori_loop-style bounded counter): such trip counts
    derive from host scalars and shapes, which SPMD ranks share. Any
    float involvement ('stop when loss < eps') or collective in the
    predicate makes the trip count data-dependent."""
    for site in walk(cond_jaxpr):
        if site.primitive in COLLECTIVE_PRIMS:
            return False
        for v in list(site.eqn.invars) + list(site.eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is None:
                continue
            if getattr(aval, "shape", None) not in ((), None):
                return False
            kind = getattr(getattr(aval, "dtype", None), "kind", "i")
            if kind not in ("i", "u", "b"):
                return False
    return True


@register_rule("collective-in-data-dependent-while", "error")
def collective_in_data_dependent_while(ctx):
    """A collective inside a while body (or predicate) whose trip count
    cannot be proven rank-invariant: ranks exiting the loop on different
    iterations have emitted different numbers of collectives — the
    longest-running rank waits forever. Scalar-integer counter
    predicates (the fori_loop pattern) are accepted as rank-invariant;
    float or collective-bearing predicates are not."""
    seen = set()
    for site in ctx.sites:
        if site.primitive != "while":
            continue
        params = site.eqn.params
        cond_j, _ = unwrap(params["cond_jaxpr"])
        body_j, _ = unwrap(params["body_jaxpr"])
        n_coll = sum(
            1 for j in (body_j, cond_j)
            for s in walk(j, bound_axes=site.bound_axes)
            if s.primitive in COLLECTIVE_PRIMS)
        if not n_coll:
            continue
        rank_varying = _has_axis_index(cond_j)
        if not rank_varying and _counter_cond(cond_j):
            continue
        why = ("the predicate reads axis_index (trip count varies per "
               "rank by construction)" if rank_varying else
               "the trip count is data-dependent (non-counter "
               "predicate), so ranks may exit on different iterations")
        dedup = (source_summary(site.eqn), n_coll, why)
        if dedup in seen:
            continue
        seen.add(dedup)
        yield ctx.finding(
            site, f"while loop contains {n_coll} collective(s) but {why} "
                  "— the last rank still looping waits on peers that "
                  "already exited; bound the loop with a shared integer "
                  "counter or hoist the collectives out")


def _while_pred_invar_positions(eqn) -> List[int]:
    """Indices into the while eqn's invars that feed its predicate:
    cond consts plus the carry slots the cond jaxpr actually reads."""
    cond_j, _ = unwrap(eqn.params["cond_jaxpr"])
    cond_n = int(eqn.params.get("cond_nconsts", 0))
    body_n = int(eqn.params.get("body_nconsts", 0))
    used = set()
    for s in walk(cond_j):
        for a in s.eqn.invars:
            if _is_var(a):
                used.add(id(a))
    for v in cond_j.outvars:
        if _is_var(v):
            used.add(id(v))
    out = []
    for pos, v in enumerate(cond_j.invars):
        if id(v) not in used:
            continue
        # cond invars = [cond_consts..., carry...]; while invars =
        # [cond_consts..., body_consts..., carry...]
        out.append(pos if pos < cond_n else pos + body_n)
    return out


@register_rule("rank-dependent-collective-schedule", "error")
def rank_dependent_collective_schedule(ctx):
    """A collective-bearing cond/while whose predicate is tainted by
    ``axis_index``: even when the branch sequences look identical, a
    rank-varying predicate selects *different staged program points* —
    different HLO collective instructions with different channel ids —
    so matching kinds do not rendezvous and the program hangs. Taint is
    propagated through the linearized dataflow (transparent call shells
    and shard_map boundaries aliased through)."""
    from .walker import linear_schedule
    try:
        nodes = linear_schedule(ctx.closed)
    except Exception:
        return
    tainted = set()
    seen = set()
    for node in nodes:
        eqn = node.eqn
        prim = node.primitive
        if prim == "axis_index":
            tainted.update(node.out_ids)
            continue
        hit = any(i in tainted for i in node.in_ids)
        hazard = None
        if prim == "cond" and eqn.invars and _is_var(eqn.invars[0]):
            pred_id = node.in_ids[0] if node.in_ids else None
            if pred_id in tainted and any(
                    _contains_collective(sub.jaxpr, node.bound_axes)
                    for sub in subjaxprs(eqn)):
                hazard = ("cond predicate is derived from axis_index "
                          "and its branches contain collectives")
        elif prim == "while":
            cond_j, _ = unwrap(eqn.params["cond_jaxpr"])
            var_pos = [i for i, a in enumerate(eqn.invars) if _is_var(a)]
            pred_tainted = False
            for widx in _while_pred_invar_positions(eqn):
                if widx in var_pos:
                    cid = node.in_ids[var_pos.index(widx)]
                    if cid in tainted:
                        pred_tainted = True
                        break
            if (pred_tainted or _has_axis_index(cond_j)) and any(
                    _contains_collective(j, node.bound_axes)
                    for j in (unwrap(eqn.params["body_jaxpr"])[0],
                              cond_j)):
                hazard = ("while predicate is derived from axis_index "
                          "and the loop contains collectives")
        if hazard is not None:
            dedup = (prim, source_summary(eqn))
            if dedup not in seen:
                seen.add(dedup)
                yield ctx.finding_at(
                    f"{hazard}: a rank-varying predicate selects "
                    "different staged collectives (different channel "
                    "ids), so peers never rendezvous — derive the "
                    "predicate from rank-invariant state or restructure "
                    "with jnp.where on the collective RESULT",
                    primitive=prim, path=node.path,
                    eqn_index=node.index, source=source_summary(eqn))
        if hit:
            tainted.update(node.out_ids)


@register_rule("program-family-schedule-drift", "error")
def program_family_schedule_drift(ctx):
    """Members of a registered :class:`ProgramFamily` emit different
    collective schedules while the family's selector is NOT declared
    rank-invariant: a rank whose host predicate disagrees (clock skew,
    divergent step counter, different batch composition) runs a
    different member and hangs the fleet. Only fires during
    :func:`verify_family` (needs family context); reported once, on the
    primary member."""
    fam = getattr(ctx, "family", None)
    if fam is None or fam.member != fam.primary:
        return
    fps = dict(fam.fingerprints)
    if len(set(fps.values())) <= 1 or fam.rank_invariant:
        return
    per = ", ".join(f"{m}={fp[:12]}" for m, fp in fps.items())
    yield ctx.finding_at(
        f"program family {fam.name!r} members emit different collective "
        f"schedules ({per}) but its selector ({fam.selector!r}) is not "
        "declared rank-invariant: one rank picking a different member "
        "deadlocks every peer — derive the selection from rank-invariant "
        "state (step counter, batch-shape bucket) and register with "
        "rank_invariant=True, or make the member schedules identical",
        primitive="<family>", path="<family>")
