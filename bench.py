"""Benchmark: GPT-base (124M) bf16 training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-repo numbers (BASELINE.md), so vs_baseline is
reported against BASELINE.json's empty "published" table as 1.0 when the run
succeeds; the absolute tokens/sec (and derived MFU) is the tracked number.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.engine import ParallelTrainer
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.text.models import GPTForPretraining

    paddle.seed(0)
    n_dev = len(jax.devices())
    build_mesh({"data": 1})

    vocab, hidden, layers, heads, seq = 50304, 768, 12, 12, 1024
    batch = 8
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:  # CPU smoke config
        vocab, hidden, layers, heads, seq, batch = 1024, 256, 2, 4, 256, 4

    model = GPTForPretraining(
        tensor_parallel=False, vocab_size=vocab, hidden_size=hidden,
        num_layers=layers, num_heads=heads, max_position_embeddings=seq,
        attn_dropout=0.0, hidden_dropout=0.0)
    model.bfloat16()

    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters())

    def loss_fn(logits, labels):
        # bf16 logits straight into the fused lse-gather CE fast path
        # (fp32 accumulation happens inside; an astype here would
        # materialize a full fp32 (b, s, vocab) tensor)
        return nn.functional.cross_entropy(logits, labels)

    trainer = ParallelTrainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, seq)).astype("int32")
    labels = rng.randint(0, vocab, (batch, seq)).astype("int32")

    # warmup (compile + flush; NOTE: under the axon tunnel
    # block_until_ready returns early — a host fetch is the reliable sync)
    for _ in range(12):
        loss = trainer.train_step(ids, labels)
    float(loss)

    iters = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.train_step(ids, labels)
    final_loss = float(loss)  # device->host sync
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_tok = 6 * n_params
    mfu = None
    if on_tpu:
        peak = 197e12  # v5e bf16 peak FLOP/s
        mfu = tokens_per_sec * flops_per_tok / peak

    result = {
        "metric": "gpt_base_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
    }
    if mfu is not None:
        result["mfu"] = round(mfu, 4)
        result["params"] = n_params
        result["final_loss"] = round(final_loss, 4)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
