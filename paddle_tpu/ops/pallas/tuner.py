"""Search-based Pallas kernel autotuner with a persistent tuning DB.

The flash-attention kernel shipped with hand-swept block constants
(``DEFAULT_BLOCK_Q/K``) frozen for one chip and one shape; CUDA-L2
(arXiv:2512.02551) shows grid search over the tile/config space beats
hand-tuned constants per (shape, dtype, arch).  This module is that
search for the repo's Pallas tier:

- **Keys** — ``kernel|device_kind|dtype|dim=..,dim=..`` with sequence /
  token dims bucketed to the next power of two (the step-cache idea:
  one entry serves every shape that lands in the bucket, so a DB tuned
  at s=1024 also covers s=900 after the wrapper pads).
- **DB** — a JSON file shipped in-repo
  (``paddle_tpu/ops/pallas/tuning_db.json``, interpret-validated seeds)
  plus a user-writable overlay (``PADDLE_TPU_TUNING_DB`` or
  ``~/.cache/paddle_tpu/tuning_db.json``).  Overlay entries win per key;
  a corrupt file is treated as empty (warn once), never a crash.
- **Resolution** — kernels call :func:`resolve` at trace time: DB hit →
  tuned blocks, miss → the kernel's compiled-in defaults, unsupported
  shape → the caller's XLA fallback (counted via
  :func:`record_fallback`).  Every outcome increments
  ``pallas_config_resolved_total{kernel, source=db|default|fallback}``.
- **Tuning** — :func:`tune` grid-searches candidate configs per (kernel,
  shape bucket, dtype, device kind).  Each candidate is validated
  against the XLA reference for numerics BEFORE it may be timed; on CPU
  (no TPU) the sweep runs the kernels in interpret mode and is
  correctness-only — winners are the validated defaults with a null
  timing, so the first real-TPU run only has to refresh timings, not
  re-establish correctness.  Timing reuses ``tools/op_bench.py``'s
  steady-state loop.

CLI (writes the overlay by default)::

    python -m paddle_tpu.ops.pallas.tuner --suite quick        # CPU ok
    python -m paddle_tpu.ops.pallas.tuner --suite bench --db ops/pallas/tuning_db.json --generic
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "TuningDB", "default_db_path", "overlay_db_path", "get_db",
    "clear_cache", "shape_bucket", "device_kind", "make_key", "resolve",
    "record_fallback", "tune", "flash_candidates", "ce_candidates",
    "paged_candidates", "entry_for_traced_call", "GENERIC_DEVICE",
]

GENERIC_DEVICE = "any"  # device-agnostic seed entries (interpret-validated)

_VERSION = 1


# ---------------------------------------------------------------------------
# keys
# ---------------------------------------------------------------------------

def shape_bucket(n: int, floor: int = 128) -> int:
    """Next power of two >= n (min ``floor``): the shape-bucket axis of
    the DB key. Wrappers pad ragged shapes anyway, so one tuned entry
    serves the whole bucket."""
    n = max(int(n), 1)
    b = floor
    while b < n:
        b <<= 1
    return b


def device_kind() -> str:
    """Normalized accelerator name for DB keys ("cpu", "tpu-v5e", ...)."""
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:
        return "cpu"
    return str(kind).strip().lower().replace(" ", "-")


def _dtype_name(dtype) -> str:
    try:
        import jax.numpy as jnp
        return jnp.dtype(dtype).name
    except Exception:
        return str(dtype)


def make_key(kernel: str, device: str, dtype, dims: Dict[str, int]) -> str:
    dt = _dtype_name(dtype)
    dim_s = ",".join(f"{k}{int(v)}" for k, v in sorted(dims.items()))
    return f"{kernel}|{device}|{dt}|{dim_s}"


def flash_dims(d: int, sq: int, sk: int) -> Dict[str, int]:
    """Bucketed dims for a flash-attention call: head_dim exact (it is a
    hardware tile), sequence lengths bucketed. ``sq`` buckets with
    floor=1 so DECODE-shaped calls (sq = 1..8) keep exact small keys
    instead of collapsing into — and colliding with — the 128 prefill
    bucket; keys for sq >= 128 are unchanged."""
    return {"d": int(d), "sq": shape_bucket(sq, floor=1),
            "sk": shape_bucket(sk)}


def ce_dims(h: int, v: int, tokens: int) -> Dict[str, int]:
    """Bucketed dims for a fused-CE call: hidden and vocab exact (vocab
    is a model constant, not a batch axis), token count bucketed."""
    return {"h": int(h), "v": int(v), "t": shape_bucket(tokens)}


# ---------------------------------------------------------------------------
# DB
# ---------------------------------------------------------------------------

def default_db_path() -> str:
    """The in-repo seed DB shipped next to the kernels."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tuning_db.json")


def overlay_db_path() -> str:
    """User-writable overlay: ``PADDLE_TPU_TUNING_DB`` or a cache-dir
    default. Tuner runs write here so the shipped seed stays pristine."""
    env = os.environ.get("PADDLE_TPU_TUNING_DB")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "tuning_db.json")


class TuningDB:
    """A {key: entry} map with JSON round-trip. An entry is::

        {"config": {"block_q": 256, "block_k": 512},
         "kernel": "flash_attention", "device": "tpu-v5e",
         "dtype": "bfloat16", "dims": {"d": 64, "sq": 1024, "sk": 1024},
         "mean_us": 123.4 | None,        # None = correctness-only sweep
         "validated": "interpret" | "device" | "seed",
         "swept": 6}                     # candidates that passed numerics

    ``validated: "seed"`` marks a shipped config-only entry for a shape
    too large to interpret-validate on CPU (the TPU bench buckets): the
    blocks are legal for the shape but unmeasured — a device tuner run
    (``--suite bench``) refreshes them in place.
    """

    def __init__(self, entries: Optional[Dict[str, dict]] = None,
                 path: Optional[str] = None):
        self.entries: Dict[str, dict] = dict(entries or {})
        self.path = path

    # -- io -----------------------------------------------------------------
    @classmethod
    def load(cls, path: str) -> "TuningDB":
        """Load a DB file; missing or corrupt files yield an EMPTY db
        (warn once on corruption) — a broken overlay must never take
        down trace time."""
        if not os.path.exists(path):
            return cls(path=path)
        try:
            with open(path) as f:
                raw = json.load(f)
            if not isinstance(raw, dict) or \
                    not isinstance(raw.get("entries", {}), dict):
                raise ValueError("not a tuning DB object")
            return cls(raw.get("entries", {}), path=path)
        except (OSError, ValueError) as e:
            warnings.warn(f"tuning DB {path!r} unreadable ({e}); "
                          "treating as empty", stacklevel=2)
            return cls(path=path)

    def save(self, path: Optional[str] = None):
        path = path or self.path
        if not path:
            raise ValueError("TuningDB.save: no path")
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": _VERSION, "entries": self.entries}, f,
                      indent=1, sort_keys=True)
        os.replace(tmp, path)

    # -- access -------------------------------------------------------------
    def lookup(self, key: str) -> Optional[dict]:
        return self.entries.get(key)

    def put(self, key: str, entry: dict):
        self.entries[key] = entry

    def merged_over(self, base: "TuningDB") -> "TuningDB":
        """self (overlay) wins per key over ``base``."""
        merged = dict(base.entries)
        merged.update(self.entries)
        return TuningDB(merged)

    def __len__(self):
        return len(self.entries)


_db_cache: Dict[str, Any] = {}


def get_db(refresh: bool = False) -> TuningDB:
    """The merged (seed + overlay) DB, cached per (seed, overlay) paths."""
    key = (default_db_path(), overlay_db_path())
    if refresh or _db_cache.get("key") != key:
        base = TuningDB.load(key[0])
        overlay = TuningDB.load(key[1])
        _db_cache["key"] = key
        _db_cache["db"] = overlay.merged_over(base)
    return _db_cache["db"]


def clear_cache():
    """Drop the cached merged DB (tests / after a tuner run)."""
    _db_cache.clear()


# ---------------------------------------------------------------------------
# trace-time resolution
# ---------------------------------------------------------------------------

def _count(kernel: str, source: str):
    from ... import telemetry
    if telemetry.enabled():
        telemetry.counter(
            "pallas_config_resolved_total",
            "Pallas kernel config resolutions, by source"
        ).inc(kernel=kernel, source=source)


def resolve(kernel: str, dtype, dims: Dict[str, int],
            defaults: Dict[str, int]) -> Tuple[Dict[str, int], str]:
    """Trace-time config lookup: (config, source).

    Tries the exact device kind, then the :data:`GENERIC_DEVICE` seed
    entries. Hit → the tuned config (source "db"); miss → ``defaults``
    (source "default"). Either way the outcome is counted in
    ``pallas_config_resolved_total{kernel, source}``.
    """
    db = get_db()
    for dev in (device_kind(), GENERIC_DEVICE):
        entry = db.lookup(make_key(kernel, dev, dtype, dims))
        if entry and isinstance(entry.get("config"), dict):
            _count(kernel, "db")
            cfg = dict(defaults)
            cfg.update({k: int(v) for k, v in entry["config"].items()})
            return cfg, "db"
    _count(kernel, "default")
    return dict(defaults), "default"


def record_fallback(kernel: str):
    """Count an XLA-fallback resolution (unsupported shape / backend):
    the third ``source`` label of ``pallas_config_resolved_total``."""
    _count(kernel, "fallback")


def entry_for_traced_call(kernel_name: str, avals: List, grid) -> \
        Tuple[Optional[str], Optional[dict]]:
    """Map a traced ``pallas_call`` equation back to its DB entry — the
    analysis rule's hook (``pallas-config-untuned``).

    ``kernel_name`` is the pallas_call's kernel function name; ``avals``
    the input abstract values; ``grid`` the launch grid.  Returns
    ``(key, entry_or_None)``; ``(None, None)`` when the kernel is not
    one the tuner knows.  For fused CE the vocab seen in the jaxpr is
    the block-padded one, so the match accepts any DB entry whose true
    vocab pads to the traced width.
    """
    db = get_db()
    if kernel_name in ("_fwd_kernel", "_bwd_dq_kernel", "_bwd_dkv_kernel"):
        # flash attention: invars (lens, seed, q, k, v, ...) — q at 2
        if len(avals) < 5:
            return None, None
        q, k = avals[2], avals[3]
        dims = flash_dims(q.shape[-1], q.shape[1], k.shape[1])
        for dev in (device_kind(), GENERIC_DEVICE):
            key = make_key("flash_attention", dev, q.dtype, dims)
            entry = db.lookup(key)
            if entry:
                return key, entry
        return make_key("flash_attention", device_kind(), q.dtype,
                        dims), None
    if kernel_name in ("_ce_fwd_kernel", "_ce_bwd_dh_kernel",
                       "_ce_bwd_dw_kernel"):
        # fused CE: hid (N, H) and w (H, Vpad) are the two matrix invars,
        # identified by the shape relation hid.shape[1] == w.shape[0]
        mats = [a for a in avals if len(getattr(a, "shape", ())) == 2]
        hid = w = None
        for a in mats:
            for b in mats:
                if a is not b and a.shape[1] == b.shape[0]:
                    hid, w = a, b
                    break
            if hid is not None:
                break
        if hid is None:
            return None, None
        n, h = hid.shape
        vpad = w.shape[1]
        tb = shape_bucket(n)
        for dev in (device_kind(), GENERIC_DEVICE):
            prefix = f"fused_ce|{dev}|{_dtype_name(hid.dtype)}|"
            for key, entry in db.entries.items():
                if not key.startswith(prefix):
                    continue
                d = entry.get("dims", {})
                bv = entry.get("config", {}).get("block_vocab", 0)
                if d.get("h") == h and d.get("t") == tb and bv and \
                        d.get("v", 0) <= vpad and \
                        -(-d.get("v", 1) // bv) * bv == vpad:
                    return key, entry
        return make_key(
            "fused_ce", device_kind(), hid.dtype,
            {"h": int(h), "v": int(vpad), "t": tb}), None
    if kernel_name in ("_paged_decode_kernel", "_paged_verify_kernel"):
        # paged decode/verify attention: invars (tables, lens, q, k_pool,
        # v_pool) with q (B, H, rows, D) and k_pool (P, page_size, H, D).
        # For the verify kernel the traced row count IS the bucketed
        # speculative chunk width, so it keys the ``sq`` dim directly.
        if len(avals) < 4:
            return None, None
        tables, q, kpool = avals[0], avals[2], avals[3]
        from .paged_attention import paged_dims
        tq = q.shape[2] if kernel_name == "_paged_verify_kernel" else 1
        dims = paged_dims(q.shape[-1], kpool.shape[1], tables.shape[1],
                          tq=tq)
        for dev in (device_kind(), GENERIC_DEVICE):
            key = make_key("paged_attention", dev, q.dtype, dims)
            entry = db.lookup(key)
            if entry:
                return key, entry
        return make_key("paged_attention", device_kind(), q.dtype,
                        dims), None
    return None, None


# ---------------------------------------------------------------------------
# candidate grids
# ---------------------------------------------------------------------------

def flash_candidates(sq: int, sk: int) -> List[Dict[str, int]]:
    """(block_q, block_k) grid: lane-aligned powers of two that divide
    the bucketed sequence lengths (the wrapper's clamp would mangle
    anything else)."""
    out = []
    for bq in (128, 256, 512):
        if bq > sq or sq % bq:
            continue
        for bk in (128, 256, 512, 1024):
            if bk > sk or sk % bk:
                continue
            out.append({"block_q": bq, "block_k": bk})
    return out or [{"block_q": min(sq, 128), "block_k": min(sk, 128)}]


def ce_candidates(tokens: int, vocab: int) -> List[Dict[str, int]]:
    """(block_tokens, block_vocab) grid for the fused CE kernel."""
    out = []
    for bt in (128, 256, 512):
        if bt > tokens or tokens % bt:
            continue
        for bv in (512, 1024, 2048, 4096):
            if bv > max(vocab, 512):
                continue
            out.append({"block_tokens": bt, "block_vocab": bv})
    return out or [{"block_tokens": min(tokens, 128),
                    "block_vocab": min(shape_bucket(vocab), 512)}]


def paged_candidates(sq: Optional[int] = None) -> List[Dict[str, int]]:
    """q_pad grid for the paged decode kernel: the sublane rows the
    single query is broadcast to — 8 matches the f32 tile, 16 the bf16
    tile shape. For the speculative-verify path (``sq`` set) the rows
    ARE the bucketed chunk width, so there is exactly one candidate."""
    if sq is not None:
        return [{"q_pad": int(sq)}]
    return [{"q_pad": 8}, {"q_pad": 16}]


# ---------------------------------------------------------------------------
# validation + timing
# ---------------------------------------------------------------------------

def _time_op(fn, args, iters: int = 20, warmup: int = 3) -> float:
    """tools/op_bench.py's steady-state timing loop (shared so op
    timings and tuner timings are the same measurement); inline twin
    when the tools dir is not importable (installed package)."""
    try:
        from tools.op_bench import time_op
        return time_op(fn, args, iters=iters, warmup=warmup)
    except ImportError:
        pass
    import jax
    import numpy as np
    jfn = jax.jit(fn)
    out = None
    for _ in range(warmup):
        out = jfn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jfn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    return (time.perf_counter() - t0) / iters


def _validate_flash(cfg, b, h, d, sq, sk, dtype, interpret,
                    loss_tol=1e-3, grad_tol=2e-2) -> bool:
    """Candidate gate: fwd output AND input grads must match the XLA
    attention reference before the candidate may be timed."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ...nn.functional.attention import _xla_attention
    from .flash_attention import flash_attention

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, sq, h, d), dtype)
    k = jnp.asarray(rs.randn(b, sk, h, d), dtype)
    v = jnp.asarray(rs.randn(b, sk, h, d), dtype)

    def f_fl(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=True, block_q=cfg["block_q"],
            block_k=cfg["block_k"], interpret=interpret) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, causal=True) ** 2)

    try:
        lf, gf = jax.value_and_grad(f_fl, argnums=(0, 1, 2))(q, k, v)
        lr, gr = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    except Exception:
        return False
    tol = 2e-2 if jnp.dtype(dtype) == jnp.bfloat16 else loss_tol
    if not np.allclose(float(lf), float(lr),
                       rtol=tol, atol=tol * max(1.0, abs(float(lr)))):
        return False
    gtol = 1e-1 if jnp.dtype(dtype) == jnp.bfloat16 else grad_tol
    for a, b_ in zip(gf, gr):
        err = np.max(np.abs(np.asarray(a, np.float32)
                            - np.asarray(b_, np.float32)))
        scale = max(1.0, float(np.max(np.abs(np.asarray(b_, np.float32)))))
        if err / scale > gtol:
            return False
    return True


def _validate_ce(cfg, tokens, h, v, dtype, interpret,
                 loss_tol=1e-3) -> bool:
    """Candidate gate: loss AND grads vs the chunked_lm_ce oracle."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..chunked_ce import chunked_lm_ce
    from .fused_ce import fused_lm_ce

    rs = np.random.RandomState(0)
    hid = jnp.asarray(rs.randn(tokens, h) * 0.1, dtype)
    w = jnp.asarray(rs.randn(h, v) * 0.1, dtype)
    lbl = jnp.asarray(rs.randint(0, v, (tokens,)), jnp.int32)

    def f_fu(hid, w):
        return fused_lm_ce(hid, w, lbl,
                           block_tokens=cfg["block_tokens"],
                           block_vocab=cfg["block_vocab"],
                           interpret=interpret)

    def f_ref(hid, w):
        return chunked_lm_ce(hid, w, lbl, min(4096, shape_bucket(v)))

    try:
        lf, gf = jax.value_and_grad(f_fu, argnums=(0, 1))(hid, w)
        lr, gr = jax.value_and_grad(f_ref, argnums=(0, 1))(hid, w)
    except Exception:
        return False
    tol = 1e-2 if jnp.dtype(dtype) == jnp.bfloat16 else loss_tol
    if abs(float(lf) - float(lr)) > tol * max(1.0, abs(float(lr))):
        return False
    gtol = 5e-2 if jnp.dtype(dtype) == jnp.bfloat16 else 1e-2
    for a, b_ in zip(gf, gr):
        err = np.max(np.abs(np.asarray(a, np.float32)
                            - np.asarray(b_, np.float32)))
        scale = max(1.0, float(np.max(np.abs(np.asarray(b_, np.float32)))))
        if err / scale > gtol:
            return False
    return True


def _time_flash(cfg, b, h, d, sq, sk, dtype, interpret, iters) -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .flash_attention import flash_attention

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, sq, h, d), dtype)
    k = jnp.asarray(rs.randn(b, sk, h, d), dtype)
    v = jnp.asarray(rs.randn(b, sk, h, d), dtype)

    def step(q, k, v):
        def f(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, block_q=cfg["block_q"],
                block_k=cfg["block_k"], interpret=interpret) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    return _time_op(step, (q, k, v), iters=iters)


def _time_ce(cfg, tokens, h, v, dtype, interpret, iters) -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .fused_ce import fused_lm_ce

    rs = np.random.RandomState(0)
    hid = jnp.asarray(rs.randn(tokens, h) * 0.1, dtype)
    w = jnp.asarray(rs.randn(h, v) * 0.1, dtype)
    lbl = jnp.asarray(rs.randint(0, v, (tokens,)), jnp.int32)

    def step(hid, w):
        return jax.grad(
            lambda hid, w: fused_lm_ce(
                hid, w, lbl, block_tokens=cfg["block_tokens"],
                block_vocab=cfg["block_vocab"], interpret=interpret),
            argnums=(0, 1))(hid, w)

    return _time_op(step, (hid, w), iters=iters)


def _paged_case_arrays(b, h, d, ps, pages, dtype, tq=1):
    import jax.numpy as jnp
    import numpy as np

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, tq, h, d), dtype)
    kp = jnp.asarray(rs.randn(pages, ps, h, d), dtype)
    vp = jnp.asarray(rs.randn(pages, ps, h, d), dtype)
    # shuffled tables + ragged lens exercise the gather and masking
    tables = jnp.asarray(
        np.stack([rs.permutation(pages) for _ in range(b)]), jnp.int32)
    lens = jnp.asarray(rs.randint(0, ps * pages + 1, (b,)), jnp.int32)
    kn = jnp.asarray(rs.randn(b, tq, h, d), dtype)
    vn = jnp.asarray(rs.randn(b, tq, h, d), dtype)
    return q, kp, vp, tables, lens, kn, vn


def _validate_paged(cfg, b, h, d, ps, pages, dtype, interpret,
                    tol=2e-3, tq=1) -> bool:
    """Candidate gate: the Pallas paged decode/verify output must match
    the XLA gather baseline (the mandatory reference path) for the same
    pool."""
    import jax.numpy as jnp
    import numpy as np

    from .paged_attention import paged_decode_attention

    q, kp, vp, tables, lens, kn, vn = _paged_case_arrays(
        b, h, d, ps, pages, dtype, tq=tq)
    try:
        got = paged_decode_attention(q, kp, vp, tables, lens, k_new=kn,
                                     v_new=vn, kernel="pallas",
                                     q_pad=cfg["q_pad"],
                                     interpret=interpret)
        ref = paged_decode_attention(q, kp, vp, tables, lens, k_new=kn,
                                     v_new=vn, kernel="xla")
    except Exception:
        return False
    t = 2e-2 if jnp.dtype(dtype) == jnp.bfloat16 else tol
    err = np.max(np.abs(np.asarray(got, np.float32)
                        - np.asarray(ref, np.float32)))
    return err / max(1.0, float(np.max(np.abs(np.asarray(
        ref, np.float32))))) <= t


def _time_paged(cfg, b, h, d, ps, pages, dtype, interpret, iters,
                tq=1) -> float:
    from .paged_attention import paged_decode_attention

    q, kp, vp, tables, lens, kn, vn = _paged_case_arrays(
        b, h, d, ps, pages, dtype, tq=tq)

    def step(q, kp, vp):
        return paged_decode_attention(q, kp, vp, tables, lens, k_new=kn,
                                      v_new=vn, kernel="pallas",
                                      q_pad=cfg["q_pad"],
                                      interpret=interpret)

    return _time_op(step, (q, kp, vp), iters=iters)


# ---------------------------------------------------------------------------
# the sweep
# ---------------------------------------------------------------------------

def _flash_defaults():
    from .flash_attention import DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q
    return {"block_q": DEFAULT_BLOCK_Q, "block_k": DEFAULT_BLOCK_K}


def _ce_defaults():
    from .fused_ce import DEFAULT_BLOCK_TOKENS, DEFAULT_BLOCK_VOCAB
    return {"block_tokens": DEFAULT_BLOCK_TOKENS,
            "block_vocab": DEFAULT_BLOCK_VOCAB}


def tune_case(kernel: str, case: Dict[str, int], dtype,
              iters: int = 10, device: Optional[str] = None,
              log: Callable[[str], None] = lambda s: None) -> \
        Tuple[str, Optional[dict]]:
    """Sweep ONE (kernel, shape case, dtype): validate every candidate,
    time the survivors when a real accelerator is present, and return
    (key, winning entry | None when nothing validates)."""
    import jax

    on_tpu = jax.default_backend() == "tpu"
    interpret = not on_tpu
    dev = device or device_kind()
    if kernel == "flash_attention":
        b, h = case.get("b", 1), case.get("h", 2)
        d, sq, sk = case["d"], shape_bucket(case["sq"], floor=1), \
            shape_bucket(case["sk"])
        dims = flash_dims(d, sq, sk)
        cands = flash_candidates(sq, sk)
        validate = lambda c: _validate_flash(c, b, h, d, sq, sk, dtype,  # noqa: E731
                                             interpret)
        timeit = lambda c: _time_flash(c, b, h, d, sq, sk, dtype,  # noqa: E731
                                       interpret, iters)
        defaults = _flash_defaults()
    elif kernel == "fused_ce":
        hdim, v = case["h"], case["v"]
        tokens = shape_bucket(case["t"], floor=128)
        dims = ce_dims(hdim, v, tokens)
        cands = ce_candidates(tokens, v)
        validate = lambda c: _validate_ce(c, tokens, hdim, v, dtype,  # noqa: E731
                                          interpret)
        timeit = lambda c: _time_ce(c, tokens, hdim, v, dtype,  # noqa: E731
                                    interpret, iters)
        defaults = _ce_defaults()
    elif kernel == "paged_attention":
        from .paged_attention import (DEFAULT_Q_PAD, paged_dims,
                                      verify_rows)
        b, h = case.get("b", 4), case.get("h", 2)
        d, ps, pages = case["d"], case["ps"], case["pages"]
        tq = case.get("tq", 1)          # 1 + K for speculative verify
        dims = paged_dims(d, ps, pages, tq=tq)
        cands = paged_candidates(verify_rows(tq) if tq > 1 else None)
        validate = lambda c: _validate_paged(c, b, h, d, ps, pages,  # noqa: E731
                                             dtype, interpret, tq=tq)
        timeit = lambda c: _time_paged(c, b, h, d, ps, pages, dtype,  # noqa: E731
                                       interpret, iters, tq=tq)
        defaults = ({"q_pad": DEFAULT_Q_PAD} if tq == 1
                    else {"q_pad": verify_rows(tq)})
    else:
        raise ValueError(f"unknown kernel {kernel!r}")

    key = make_key(kernel, dev, dtype, dims)
    valid: List[Dict[str, int]] = []
    for cfg in cands:
        ok = validate(cfg)
        log(f"  {kernel} {dims} {cfg}: "
            f"{'ok' if ok else 'FAILED numerics'}")
        if ok:
            valid.append(cfg)
    if not valid:
        return key, None

    if on_tpu:
        timed = [(timeit(cfg), cfg) for cfg in valid]
        timed.sort(key=lambda t: t[0])
        best_us, best = timed[0][0] * 1e6, timed[0][1]
        validated = "device"
    else:
        # correctness-only (interpret): keep the compiled-in default when
        # it validated, else the first survivor; timing stays null so a
        # real-TPU refresh knows it still owes a measurement
        best = next((c for c in valid
                     if all(c.get(k) == v for k, v in defaults.items())),
                    valid[0])
        best_us, validated = None, "interpret"
    return key, {
        "config": best, "kernel": kernel, "device": dev,
        "dtype": _dtype_name(dtype), "dims": dims,
        "mean_us": round(best_us, 2) if best_us is not None else None,
        "validated": validated, "swept": len(valid),
    }


def tune(cases: List[Tuple[str, Dict[str, int], Any]],
         db_path: Optional[str] = None, iters: int = 10,
         device: Optional[str] = None, verbose: bool = False) -> TuningDB:
    """Run the sweep over ``cases`` (list of (kernel, case, dtype)) and
    persist winners into ``db_path`` (default: the user overlay),
    merging with whatever that file already holds."""
    path = db_path or overlay_db_path()
    db = TuningDB.load(path)
    log = (lambda s: print(s, flush=True)) if verbose else (lambda s: None)
    for kernel, case, dtype in cases:
        t0 = time.perf_counter()
        key, entry = tune_case(kernel, case, dtype, iters=iters,
                               device=device, log=log)
        if entry is None:
            log(f"{key}: no candidate passed numerics — not recorded")
            continue
        if entry["mean_us"] is not None:
            # predicted-vs-measured (telemetry.calibration): the DB's
            # prior timing for this key is the "prediction" a fresh
            # device sweep just re-measured — drift here means the
            # stored entry went stale (driver bump, thermals, new part)
            prior = get_db().lookup(key)
            prior_us = prior.get("mean_us") if prior else None
            if prior_us:
                from paddle_tpu.telemetry import calibration
                calibration.record(f"tuner:{kernel}", prior_us * 1e-6,
                                   entry["mean_us"] * 1e-6)
        db.put(key, entry)
        log(f"{key} -> {entry['config']} "
            f"({entry['mean_us']} us, {entry['swept']} valid, "
            f"{time.perf_counter() - t0:.1f}s)")
    db.save(path)
    clear_cache()
    return db


# ---------------------------------------------------------------------------
# suites + CLI
# ---------------------------------------------------------------------------

def _suite(name: str) -> List[Tuple[str, Dict[str, int], Any]]:
    import jax.numpy as jnp
    f32, bf16 = jnp.float32, jnp.bfloat16
    if name == "smoke":       # seconds on CPU — CI plumbing check
        return [
            ("flash_attention", {"b": 1, "h": 1, "d": 64, "sq": 128,
                                 "sk": 128}, f32),
            ("fused_ce", {"h": 64, "v": 512, "t": 128}, f32),
        ]
    if name == "quick":       # the CPU-bench GPT shapes
        return [
            ("flash_attention", {"b": 1, "h": 2, "d": 64, "sq": 256,
                                 "sk": 256}, f32),
            ("flash_attention", {"b": 1, "h": 2, "d": 64, "sq": 512,
                                 "sk": 512}, f32),
            ("fused_ce", {"h": 128, "v": 1024, "t": 512}, f32),
            ("fused_ce", {"h": 64, "v": 512, "t": 128}, f32),
        ]
    if name == "decode":      # bench_serving decode-shape buckets
        return [
            ("paged_attention", {"b": 4, "h": 2, "d": 32, "ps": 16,
                                 "pages": 16}, f32),
            ("paged_attention", {"b": 4, "h": 2, "d": 32, "ps": 16,
                                 "pages": 8}, f32),
            ("paged_attention", {"b": 4, "h": 2, "d": 64, "ps": 16,
                                 "pages": 16}, bf16),
            # speculative-verify chunks (tq = 1 + K for K in {4, 8})
            ("paged_attention", {"b": 4, "h": 2, "d": 32, "ps": 16,
                                 "pages": 16, "tq": 5}, f32),
            ("paged_attention", {"b": 4, "h": 2, "d": 32, "ps": 16,
                                 "pages": 8, "tq": 5}, f32),
            ("paged_attention", {"b": 4, "h": 2, "d": 32, "ps": 16,
                                 "pages": 16, "tq": 9}, f32),
            ("paged_attention", {"b": 4, "h": 2, "d": 32, "ps": 16,
                                 "pages": 8, "tq": 9}, f32),
            ("paged_attention", {"b": 4, "h": 2, "d": 64, "ps": 16,
                                 "pages": 16, "tq": 5}, bf16),
            ("paged_attention", {"b": 4, "h": 2, "d": 64, "ps": 16,
                                 "pages": 16, "tq": 9}, bf16),
        ]
    if name == "bench":       # the TPU bench GPT-base shapes
        return [
            ("flash_attention", {"b": 2, "h": 4, "d": 64, "sq": 1024,
                                 "sk": 1024}, bf16),
            ("flash_attention", {"b": 1, "h": 2, "d": 64, "sq": 2048,
                                 "sk": 2048}, bf16),
            ("fused_ce", {"h": 768, "v": 50304, "t": 8192}, bf16),
        ]
    raise SystemExit(f"unknown suite {name!r}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--suite", default="quick",
                    choices=("smoke", "quick", "decode", "bench"),
                    help="shape-case set to sweep")
    ap.add_argument("--db", default=None,
                    help="DB file to update (default: the user overlay "
                         "path)")
    ap.add_argument("--iters", type=int, default=10,
                    help="timing iterations per candidate (TPU only)")
    ap.add_argument("--generic", action="store_true",
                    help=f"record under device {GENERIC_DEVICE!r} — used "
                         "to build the shipped interpret-validated seed "
                         "DB")
    args = ap.parse_args(argv)
    db = tune(_suite(args.suite), db_path=args.db, iters=args.iters,
              device=GENERIC_DEVICE if args.generic else None,
              verbose=True)
    print(json.dumps({"tuning_db": db.path, "entries": len(db)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
