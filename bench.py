"""Benchmark suite: training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The primary metric is GPT-base (124M) bf16 tokens/sec/chip; "extra" carries
the additional BASELINE.md configs (ResNet-50 images/sec, BERT-base AMP
samples/sec) so the perf story is not a single model. Each config is
independently guarded — a failure records {"error": ...} for that config
instead of crashing the whole bench (round-1 lesson: backend init died and
the bench emitted nothing).

FLOPs convention (stated per round-2 verdict): MFU uses the 6N
approximation — 6 FLOPs per parameter per token (fwd 2N + bwd 4N),
EXCLUDING attention score/context FLOPs (the PaLM-appendix convention
without the 12·L·H·Q·T term). Peak is the v5e bf16 197 TFLOP/s figure.

The reference publishes no in-repo numbers (BASELINE.md), so vs_baseline
is 1.0 on success; the absolute numbers are the tracked quantity.
"""
from __future__ import annotations

import json
import sys
import time
import traceback

import numpy as np

V5E_BF16_PEAK = 197e12


def _init_backend(retries: int = 4, backoff_s: float = 15.0,
                  attempt_timeout_s: float = 300.0):
    """Import jax and force backend init, retrying with backoff AND a
    per-attempt watchdog.

    Round 1's rc=1 was a one-shot crash in axon backend setup; round 3
    additionally observed jax.devices() HANGING indefinitely when the
    tunnel wedges — an exception-only retry never fires then. Init runs
    on a daemon thread with a hard join timeout so the bench always emits
    its JSON line instead of blocking the driver."""
    import threading
    last = None
    for attempt in range(retries):
        result: dict = {}

        def _try():
            try:
                import jax
                devs = jax.devices()  # forces platform/plugin init
                # one tiny computation proves the runtime actually works
                float(jax.numpy.zeros(()).sum())
                result["jax"], result["devs"] = jax, devs
            except Exception as e:  # noqa: BLE001 — init errors are fatal-ish
                result["err"] = e

        t = threading.Thread(target=_try, daemon=True)
        t.start()
        t.join(attempt_timeout_s)
        if "jax" in result:
            return result["jax"], result["devs"]
        last = result.get(
            "err",
            RuntimeError(f"init hung > {attempt_timeout_s:.0f}s "
                         "(tunnel wedged)"))
        sys.stderr.write(
            f"bench: backend init attempt {attempt + 1}/{retries} "
            f"failed: {last}\n")
        if t.is_alive():
            # the stuck native call poisons this process's plugin state;
            # further in-process retries would block on the same lock
            break
        if attempt < retries - 1:
            time.sleep(backoff_s * (attempt + 1))
    raise RuntimeError(f"backend init failed: {last}")


def _timed_steps(trainer, inputs, labels, warmup: int, iters: int):
    """Run warmup + timed steps; host-fetch the loss as the sync point
    (under the axon tunnel block_until_ready can return early)."""
    for _ in range(warmup):
        loss = trainer.train_step(inputs, labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.train_step(inputs, labels)
    final_loss = float(loss)
    return time.perf_counter() - t0, final_loss


def bench_gpt(on_tpu: bool):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.engine import ParallelTrainer
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.text.models import GPTForPretraining

    paddle.seed(0)
    build_mesh({"data": 1})
    vocab, hidden, layers, heads, seq = 50304, 768, 12, 12, 1024
    batch = 8
    if not on_tpu:  # CPU smoke config
        vocab, hidden, layers, heads, seq, batch = 1024, 256, 2, 4, 256, 4

    model = GPTForPretraining(
        tensor_parallel=False, vocab_size=vocab, hidden_size=hidden,
        num_layers=layers, num_heads=heads, max_position_embeddings=seq,
        attn_dropout=0.0, hidden_dropout=0.0)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters())

    def loss_fn(logits, labels):
        # bf16 logits straight into the fused lse-gather CE fast path
        # (fp32 accumulation inside; astype here would materialize a full
        # fp32 (b, s, vocab) tensor)
        return nn.functional.cross_entropy(logits, labels)

    trainer = ParallelTrainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, seq)).astype("int32")
    labels = rng.randint(0, vocab, (batch, seq)).astype("int32")
    iters = 20 if on_tpu else 3
    dt, final_loss = _timed_steps(trainer, ids, labels,
                                  warmup=12 if on_tpu else 2, iters=iters)
    tokens_per_sec = batch * seq * iters / dt
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    out = {"tokens_per_sec": round(tokens_per_sec, 1),
           "params": n_params, "final_loss": round(final_loss, 4)}
    if on_tpu:
        out["mfu_6N"] = round(tokens_per_sec * 6 * n_params / V5E_BF16_PEAK,
                              4)
    return out


def bench_resnet50(on_tpu: bool):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.engine import ParallelTrainer
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.vision.models import resnet50, resnet18

    paddle.seed(0)
    build_mesh({"data": 1})
    if on_tpu:
        model, batch, size, iters, warmup = resnet50(), 128, 224, 20, 8
    else:
        model, batch, size, iters, warmup = resnet18(), 4, 32, 2, 1
    model.bfloat16()  # TPU AMP O2 equivalent: bf16 params + compute
    opt = paddle.optimizer.Momentum(0.1, momentum=0.9,
                                    parameters=model.parameters())
    trainer = ParallelTrainer(
        model, opt, lambda o, y: nn.functional.cross_entropy(o, y))
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    # inputs must match the bf16 conv weights (XLA convs are same-dtype)
    imgs = jnp.asarray(rng.randn(batch, 3, size, size), dtype=jnp.bfloat16)
    lbls = rng.randint(0, 1000, (batch,)).astype("int32")
    dt, final_loss = _timed_steps(trainer, imgs, lbls, warmup, iters)
    return {"images_per_sec": round(batch * iters / dt, 1),
            "final_loss": round(final_loss, 4)}


def bench_widedeep(on_tpu: bool):
    """BASELINE configs[4]: sparse recommender throughput (Criteo-shaped
    synthetic CTR: 26 categorical fields + 13 dense)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.engine import ParallelTrainer
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.rec import WideDeep

    paddle.seed(0)
    build_mesh({"data": 1})
    if on_tpu:
        fields, batch, iters, warmup = [100_000] * 26, 4096, 20, 8
        hidden = (400, 400, 400)
    else:
        fields, batch, iters, warmup = [1000] * 8, 256, 2, 1
        hidden = (64, 32)
    model = WideDeep(fields, dense_dim=13, embedding_dim=16,
                     hidden_sizes=hidden)
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())

    def bce(logit, y):
        return jnp.mean(jnp.maximum(logit, 0) - logit * y
                        + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    trainer = ParallelTrainer(model, opt, bce)
    rng = np.random.RandomState(0)
    ids = np.stack([rng.randint(0, d, batch) for d in fields], 1) \
        .astype("int64")
    dense = rng.randn(batch, 13).astype("float32")
    label = rng.randint(0, 2, batch).astype("float32")
    dt, final_loss = _timed_steps(trainer, (ids, dense), label,
                                  warmup, iters)
    return {"samples_per_sec": round(batch * iters / dt, 1),
            "final_loss": round(final_loss, 4)}


def bench_bert_amp(on_tpu: bool):
    """BERT-base MLM+NSP, bf16 (the TPU AMP: reference fp16_utils.py:322
    cast_model_to_fp16 O2 maps to whole-model bf16 on TPU)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.engine import ParallelTrainer
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.text.models import BertForPretraining

    paddle.seed(0)
    build_mesh({"data": 1})
    if on_tpu:
        cfg = dict(vocab_size=30528, hidden_size=768, num_layers=12,
                   num_heads=12, max_position_embeddings=512)
        batch, seq, iters, warmup = 16, 128, 20, 8
    else:
        cfg = dict(vocab_size=1024, hidden_size=128, num_layers=2,
                   num_heads=4, max_position_embeddings=128)
        batch, seq, iters, warmup = 4, 64, 2, 1
    model = BertForPretraining(tensor_parallel=False, attn_dropout=0.0,
                               hidden_dropout=0.0, **cfg)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())

    def loss_fn(outputs, labels):
        mlm_logits, nsp_logits = outputs
        mlm_labels, nsp_labels = labels
        return model.loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels)

    trainer = ParallelTrainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg["vocab_size"], (batch, seq)).astype("int32")
    mlm = np.full((batch, seq), -100, dtype="int32")
    mlm[:, ::8] = rng.randint(0, cfg["vocab_size"], (batch, seq // 8))
    nsp = rng.randint(0, 2, (batch,)).astype("int32")
    dt, final_loss = _timed_steps(trainer, ids, (mlm, nsp), warmup, iters)
    return {"samples_per_sec": round(batch * iters / dt, 1),
            "final_loss": round(final_loss, 4)}


def main():
    try:
        jax, _ = _init_backend()
    except Exception as e:  # emit a parseable line even on total failure
        print(json.dumps({
            "metric": "gpt_base_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/sec", "vs_baseline": 0.0,
            "error": f"backend init failed: {e}"}))
        return 1
    on_tpu = jax.default_backend() == "tpu"

    extra = {}
    for name, fn in (("gpt_base", bench_gpt),
                     ("resnet50", bench_resnet50),
                     ("bert_base_amp", bench_bert_amp),
                     ("widedeep_ctr", bench_widedeep)):
        try:
            extra[name] = fn(on_tpu)
        except Exception as e:  # partial results beat an empty bench
            sys.stderr.write(f"bench[{name}] failed:\n"
                             f"{traceback.format_exc()}\n")
            extra[name] = {"error": f"{type(e).__name__}: {e}"}

    gpt = extra.get("gpt_base", {})
    ok = "tokens_per_sec" in gpt
    result = {
        "metric": "gpt_base_train_tokens_per_sec_per_chip",
        "value": gpt.get("tokens_per_sec", 0.0),
        "unit": "tokens/sec",
        "vs_baseline": 1.0 if ok else 0.0,
        "flops_convention": "6N per token (no attention term)",
        "extra": extra,
    }
    if "mfu_6N" in gpt:
        result["mfu"] = gpt["mfu_6N"]
        result["params"] = gpt["params"]
        result["final_loss"] = gpt["final_loss"]
    print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
