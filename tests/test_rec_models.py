"""Wide&Deep / DeepFM recommender models (BASELINE configs[4] workload;
reference fixture analogue: tests dist_fleet_ctr.py)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.engine import ParallelTrainer
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.rec import DeepFM, WideDeep

FIELDS = [50, 30, 20]
DENSE = 4


def _ctr_data(n=256, seed=0):
    rs = np.random.RandomState(seed)
    ids = np.stack([rs.randint(0, d, n) for d in FIELDS], axis=1) \
        .astype(np.int64)
    dense = rs.randn(n, DENSE).astype(np.float32)
    # clickiness driven by field-0 id parity + a dense feature
    label = ((ids[:, 0] % 2 == 0) ^ (dense[:, 0] > 0.5)).astype(np.float32)
    return ids, dense, label


def _bce(logit, y):
    return jnp.mean(jnp.maximum(logit, 0) - logit * y
                    + jnp.log1p(jnp.exp(-jnp.abs(logit))))


@pytest.mark.parametrize("cls", [WideDeep, DeepFM])
def test_trains_on_synthetic_ctr(cls):
    build_mesh({"data": 1})
    paddle.seed(0)
    model = cls(FIELDS, dense_dim=DENSE, embedding_dim=8,
                hidden_sizes=(32, 16))
    opt = paddle.optimizer.Adam(5e-3, parameters=model.parameters())
    tr = ParallelTrainer(model, opt,
                         lambda logit, y: _bce(logit, y))
    ids, dense, label = _ctr_data()
    losses = [float(tr.train_step((ids, dense), label)) for _ in range(30)]
    assert losses[-1] < losses[0] - 0.1, (losses[0], losses[-1])


def test_deepfm_second_order_matches_manual():
    """FM pairwise term 0.5[(Σv)²−Σv²] == Σ_{i<j} <v_i, v_j>."""
    paddle.seed(1)
    m = DeepFM([4, 4], dense_dim=1, embedding_dim=3, hidden_sizes=(4,))
    ids = np.asarray([[1, 2]], np.int64)
    dense = np.zeros((1, 1), np.float32)
    folded = m._fold_ids(ids)
    v = np.asarray(m._lookup(m.embedding, folded))[0]      # (2, 3)
    manual = float(np.dot(v[0], v[1]))
    sum_sq = np.square(v.sum(0)); sq_sum = np.square(v).sum(0)
    np.testing.assert_allclose(0.5 * (sum_sq - sq_sum).sum(), manual,
                               rtol=1e-5)


def test_missing_ids_contribute_zero():
    paddle.seed(2)
    m = WideDeep(FIELDS, dense_dim=DENSE, embedding_dim=8)
    dense = np.zeros((2, DENSE), np.float32)
    ids_a = np.asarray([[3, 5, 7], [3, 5, 7]], np.int64)
    ids_b = ids_a.copy()
    ids_b[1, 2] = -1                       # missing field
    out_a = np.asarray(m(ids_a, dense))
    out_b = np.asarray(m(ids_b, dense))
    assert out_a[0] == pytest.approx(out_b[0])   # row 0 unchanged
    assert out_a[1] != pytest.approx(out_b[1])   # row 1 lost a field


def test_wide_deep_dp_mesh_runs():
    build_mesh({"data": 8})
    paddle.seed(3)
    model = WideDeep(FIELDS, dense_dim=DENSE, embedding_dim=8,
                     hidden_sizes=(16,))
    opt = paddle.optimizer.Adam(5e-3, parameters=model.parameters())
    tr = ParallelTrainer(model, opt, lambda lo, y: _bce(lo, y))
    ids, dense, label = _ctr_data(64)
    first = float(tr.train_step((ids, dense), label))
    for _ in range(10):
        last = float(tr.train_step((ids, dense), label))
    assert last < first


def test_sparse_ps_backed_mode_trains():
    """sparse=True routes id features through the native PS table."""
    build_mesh({"data": 1})
    paddle.seed(4)
    model = WideDeep(FIELDS, dense_dim=DENSE, embedding_dim=8,
                     hidden_sizes=(16,), sparse=True, sparse_lr=0.1)
    opt = paddle.optimizer.Adam(5e-3, parameters=model.parameters())
    ids, dense, label = _ctr_data(64, seed=5)

    from paddle_tpu.jit.functionalization import functional_call, state_of
    params, buffers = state_of(model)
    ids_j, dense_j, y = map(jnp.asarray, (ids, dense, label))

    @jax.jit
    def step(params):
        def lf(p):
            out, _ = functional_call(model, p, buffers, ids_j, dense_j)
            return _bce(out, y)
        loss, g = jax.value_and_grad(lf)(params)
        return loss, {k: v - 0.05 * g[k] for k, v in params.items()}

    losses = []
    for _ in range(15):
        l, params = step(params)
        losses.append(float(l))
    assert losses[-1] < losses[0] - 0.05, (losses[0], losses[-1])
    assert len(model.embedding.table) > 0     # PS rows materialized


def test_jit_save_load_widedeep(tmp_path):
    """Serving-path roundtrip for the CTR model (StableHLO export)."""
    from paddle_tpu.jit import InputSpec
    build_mesh({"data": 1})
    paddle.seed(9)
    m = WideDeep(FIELDS, dense_dim=DENSE, embedding_dim=8,
                 hidden_sizes=(16,))
    m.eval()
    path = str(tmp_path / "wd" / "model")
    paddle.jit.save(m, path, input_spec=[
        InputSpec([2, len(FIELDS)], dtype="int64"),
        InputSpec([2, DENSE], dtype="float32")])
    loaded = paddle.jit.load(path)
    ids, dense, _ = _ctr_data(2, seed=7)
    np.testing.assert_allclose(np.asarray(m(ids, dense)),
                               np.asarray(loaded(ids, dense)),
                               rtol=1e-5, atol=1e-5)
