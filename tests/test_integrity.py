"""Silent-corruption defense (ISSUE 9): replica-divergence
fingerprinting, deep checkpoint verify, deterministic step replay, and
the hang watchdog — plus the runner's detect -> quarantine -> rollback
loop and the fault kinds that drive it."""
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, telemetry
from paddle_tpu.distributed import checkpoint as ck
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.engine import ParallelTrainer
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.resilience import faults, integrity, run_resilient


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    faults.reset()
    yield
    faults.reset()
    integrity.hang_event.clear()


@pytest.fixture()
def fresh_registry():
    old_reg = telemetry.get_registry()
    old_on = telemetry.enabled()
    reg = telemetry.Registry()
    telemetry._set_registry(reg)
    telemetry.enable(True)
    yield reg
    telemetry._set_registry(old_reg)
    telemetry.enable(old_on)


def _series_total(reg, name):
    series = reg.to_dict().get(name, {}).get("series", {})
    return sum(series.values())


def _mlp_trainer(data=4, check_every=2, seed=7):
    paddle.seed(seed)
    mesh = build_mesh({"data": data})

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(8, 16)
            self.l2 = nn.Linear(16, 4)

        def forward(self, x):
            return self.l2(nn.functional.relu(self.l1(x)))

    model = MLP()
    opt = paddle.optimizer.Momentum(0.05, momentum=0.9,
                                    parameters=model.parameters())
    return ParallelTrainer(model, opt,
                           lambda out, y: jnp.mean((out - y) ** 2),
                           mesh=mesh, grad_sync="int8", grad_sync_block=8,
                           integrity_check_every=check_every)


def _loader(n=8, batch=8, seed=3):
    rng = np.random.RandomState(seed)
    return [(rng.randn(batch, 8).astype(np.float32),
             rng.randn(batch, 4).astype(np.float32)) for _ in range(n)]


# ---------------------------------------------------------------------------
# fingerprint + digest primitives
# ---------------------------------------------------------------------------

class TestFingerprint:
    def test_deterministic_and_order_stable(self):
        x = jnp.asarray(np.random.RandomState(0).randn(37, 5),
                        dtype=jnp.float32)
        a = int(integrity.fingerprint_array(x))
        b = int(integrity.fingerprint_array(x))
        assert a == b
        assert int(jax.jit(integrity.fingerprint_array)(x)) == a

    def test_single_low_bit_flip_changes_fingerprint(self):
        x = np.random.RandomState(1).randn(64).astype(np.float32)
        base = int(integrity.fingerprint_array(jnp.asarray(x)))
        flipped = x.copy()
        flipped.view(np.uint32)[17] ^= np.uint32(1)  # lowest mantissa bit
        assert int(integrity.fingerprint_array(jnp.asarray(flipped))) != base

    def test_bf16_and_int_dtypes_supported(self):
        for arr in (jnp.asarray([1.5, -2.25, 3.0], dtype=jnp.bfloat16),
                    jnp.asarray([1, 2, 3], dtype=jnp.int32),
                    jnp.asarray([True, False, True]),
                    jnp.asarray([1.0, 2.0], dtype=jnp.float64)
                    if jax.config.jax_enable_x64 else
                    jnp.asarray([1.0, 2.0], dtype=jnp.float16)):
            fp = integrity.fingerprint_array(arr)
            assert fp.dtype == jnp.uint32
            assert int(fp) == int(integrity.fingerprint_array(arr))

    def test_same_bytes_different_dtype_differ(self):
        # dtype is mixed into the checksum: a bitcast must not collide
        f = jnp.asarray([1.0, 2.0, 3.0], dtype=jnp.float32)
        i = jax.lax.bitcast_convert_type(f, jnp.int32)
        assert int(integrity.fingerprint_array(f)) != int(
            integrity.fingerprint_array(i))

    def test_tree_digests_and_compare(self):
        tree = {"a": np.arange(6, dtype=np.float32),
                "b": {"c": np.ones(3, dtype=np.int32)}}
        d1 = integrity.tree_digests(tree)
        assert set(d1) == {"['a']", "['b']['c']"}
        tree2 = {"a": tree["a"].copy(), "b": {"c": tree["b"]["c"].copy()}}
        tree2["a"][2] += 1.0
        d2 = integrity.tree_digests(tree2)
        assert integrity.compare_digests(d1, d2) == ["['a']"]
        assert integrity.compare_digests(d1, d1) == []


# ---------------------------------------------------------------------------
# in-graph divergence check (engine integration)
# ---------------------------------------------------------------------------

class TestDivergenceCheck:
    def test_two_program_cache_and_zero_overhead_contract(self):
        tr = _mlp_trainer()
        x, y = _loader(n=1)[0]
        for _ in range(5):
            tr.train_step(x, y)
            assert tr.consume_divergence() == []
        # cadence 2 over 5 steps: exactly two programs, no recompiles
        assert len(tr._step_cache) == 2
        # the plain program carries ZERO fingerprint collectives; the
        # check program carries them (walker-verified on the jaxpr)
        assert integrity.count_fingerprint_collectives(
            tr.staged_jaxpr(x, y, do_check=False)) == 0
        assert integrity.count_fingerprint_collectives(
            tr.staged_jaxpr(x, y, do_check=True)) > 0

    def test_flip_detected_on_next_check_step_and_quarantined(
            self, fresh_registry):
        tr = _mlp_trainer()
        x, y = _loader(n=1)[0]
        tr.train_step(x, y)
        tr.train_step(x, y)  # steps_run=2 (check) — clean
        assert tr.consume_divergence() == []
        info = integrity.inject_param_flip(tr, seed=3, step=5)
        assert info["replica"] >= 1  # replica 0 (the save source) stays clean
        tr.train_step(x, y)          # steps_run=3: no check, flip invisible
        assert tr.consume_divergence() == []
        tr.train_step(x, y)          # steps_run=4: check — flip detected
        diverged = tr.consume_divergence()
        assert diverged and any(info["leaf"] in n for n in diverged)
        assert tr.consume_divergence() == []  # consumed
        q = integrity.quarantine_outliers(tr, diverged)
        assert q["outlier_replicas"] == [info["replica"]]
        assert q["action"] == "rollback"  # single process
        assert q["quarantined"] == 1
        assert _series_total(fresh_registry, "hosts_quarantined_total") == 1

    def test_param_flip_is_deterministic_in_seed_and_step(self):
        tr = _mlp_trainer()
        a = integrity.inject_param_flip(tr, seed=11, step=5)
        b = integrity.inject_param_flip(_mlp_trainer(), seed=11, step=5)
        assert (a["leaf"], a["replica"], a["element"], a["bit"]) == \
            (b["leaf"], b["replica"], b["element"], b["bit"])

    def test_runner_detects_quarantines_and_rolls_back(
            self, fresh_registry, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "ckpt"), use_async=False,
                                max_to_keep=12)
        with faults.inject("param_flip", at_step=5, seed=11) as f:
            res = run_resilient(_mlp_trainer(), _loader(), steps=8,
                                manager=mgr, save_every=1,
                                handle_signals=False)
        assert f.fired == 1
        assert res.exit_code == 0
        assert res.divergences == 1
        assert res.hosts_quarantined == 1
        assert res.rollback_steps and res.rollback_steps[0] >= 3
        assert res.steps_done == 8
        assert _series_total(
            fresh_registry, "replica_divergence_total") >= 1
        assert _series_total(
            fresh_registry, "integrity_check_steps_total") >= 1
        mgr.close()


# ---------------------------------------------------------------------------
# quarantine vote (partial views, quorum)
# ---------------------------------------------------------------------------

class TestQuarantineVote:
    def test_unobserved_replicas_do_not_vote(self):
        # multi-process regression: remote replicas this host cannot
        # address must be absent from the vote, not counted as digest 0
        # (which made every host vote itself the outlier)
        outliers, quorum = integrity.vote_outliers({2: 123}, n_rep=4)
        assert outliers == [] and quorum is False

    def test_partial_view_has_no_quorum(self):
        # two observed chains out of four replicas: an outlier is named
        # but the agreeing group cannot be proven a global majority
        outliers, quorum = integrity.vote_outliers({0: 7, 1: 9}, n_rep=4)
        assert outliers == [1] and quorum is False

    def test_full_view_majority_has_quorum(self):
        outliers, quorum = integrity.vote_outliers(
            {0: 7, 1: 7, 2: 7, 3: 9}, n_rep=4)
        assert outliers == [3] and quorum is True

    def test_tie_breaks_toward_save_source_replica(self):
        outliers, quorum = integrity.vote_outliers({0: 7, 1: 9}, n_rep=2)
        assert outliers == [1]   # replica 0's group wins the tie
        assert quorum is False   # 1 of 2 is not a majority

    def test_gather_merges_coordinator_views(self):
        class FakeCoord:
            def allgather(self, name, value, hosts_fn):
                assert hosts_fn() == ["h0", "h1"]
                return {"h0": value, "h1": {"2": 7, "3": 7}}

        class FakeRuntime:
            coordinator = FakeCoord()

            def _coord_hosts(self):
                return ["h0", "h1"]

        merged = integrity._gather_digest_chains({0: 7, 1: 9},
                                                 FakeRuntime())
        assert merged == {0: 7, 1: 9, 2: 7, 3: 7}

    def test_gather_without_coordinator_keeps_local_view(self):
        assert integrity._gather_digest_chains({0: 7}, None) == {0: 7}


# ---------------------------------------------------------------------------
# deep checkpoint verify
# ---------------------------------------------------------------------------

def _largest_payload(step_dir):
    """The merged-d/ data blob reads actually hit (NOT the per-process
    ocdbt duplicate)."""
    best, size = None, -1
    for r, _d, names in os.walk(step_dir):
        if "ocdbt.process_" in r:
            continue
        for n in names:
            if n.startswith("MANIFEST"):
                continue
            p = os.path.join(r, n)
            sz = os.path.getsize(p)
            if sz > size:
                best, size = p, sz
    return best


def _tamper_reattested(step_dir):
    """Flip one payload byte, then re-attest the file CRC: the shallow
    layer passes, only content digests can catch it."""
    p = _largest_payload(step_dir)
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0x01]))
    mpath = os.path.join(step_dir, ck.MANIFEST_NAME)
    with open(mpath) as f:
        man = json.load(f)
    man["files"][os.path.relpath(p, step_dir)] = {
        "size": os.path.getsize(p), "crc32": ck._crc_file(p)}
    with open(mpath, "w") as f:
        json.dump(man, f)


class TestDeepVerify:
    def _saved_mgr(self, tmp_path, steps=(1, 2, 3)):
        mgr = CheckpointManager(str(tmp_path / "ckpt"), use_async=False,
                                max_to_keep=8, deep_digests=True)
        rng = np.random.RandomState(0)
        state = {"w": rng.randn(64, 8).astype(np.float32),
                 "b": rng.randn(8).astype(np.float32)}
        for s in steps:
            assert mgr.save(s, state)
        return mgr, state

    def test_manifest_records_array_digests_and_sizes(self, tmp_path):
        mgr, state = self._saved_mgr(tmp_path)
        with open(os.path.join(mgr._step_dir(2), ck.MANIFEST_NAME)) as f:
            man = json.load(f)
        assert man["files"] and all(
            "size" in m and "crc32" in m for m in man["files"].values())
        assert set(man["arrays"]) == {"['w']", "['b']"}
        assert man["arrays"]["['w']"] == integrity.array_digest(state["w"])
        mgr.close()

    def test_shallow_passes_deep_catches_reattested_tamper(self, tmp_path):
        mgr, _ = self._saved_mgr(tmp_path)
        assert mgr.verify(2) is True
        assert mgr.verify(2, deep=True) is True
        _tamper_reattested(mgr._step_dir(2))
        assert mgr.verify(2) is True            # file layer is fooled
        assert mgr.verify(2, deep=True) is False  # content digests are not
        mgr.close()

    def test_restore_falls_back_past_deep_corrupt_with_reason(
            self, tmp_path, fresh_registry):
        mgr, _ = self._saved_mgr(tmp_path)
        _tamper_reattested(mgr._step_dir(3))
        out = mgr.restore(deep=True)
        assert out is not None
        assert mgr.last_restored_step == 2
        reg = fresh_registry.get("ckpt_restore_fallbacks_total")
        assert reg.value(reason="deep") == 1
        mgr.close()

    def test_explicit_step_deep_restore_raises_on_mismatch(self, tmp_path):
        mgr, _ = self._saved_mgr(tmp_path)
        mpath = os.path.join(mgr._step_dir(2), ck.MANIFEST_NAME)
        with open(mpath) as f:
            man = json.load(f)
        man["arrays"]["['w']"] = "crc32:00000000:0"
        with open(mpath, "w") as f:
            json.dump(man, f)
        with pytest.raises(OSError, match="deep verification"):
            mgr.restore(step=2, deep=True)
        mgr.restore(step=2)  # shallow path still restores
        mgr.close()

    def test_latest_valid_step_size_prereject(self, tmp_path):
        mgr, _ = self._saved_mgr(tmp_path)
        assert mgr.latest_valid_step() == 3
        p = _largest_payload(mgr._step_dir(3))
        with open(p, "r+b") as f:
            f.truncate(max(1, os.path.getsize(p) // 2))
        mgr._vcache.clear()
        # the size-only pre-pass rejects step 3 without any CRC read
        assert ck.verify_manifest(mgr._step_dir(3), level="size") is False
        assert mgr.latest_valid_step() == 2
        mgr.close()

    def test_deep_digests_off_by_default(self, tmp_path):
        # digests cost a full device->host transfer + CRC per save, so
        # they are opt-in: a default manager records none
        mgr = CheckpointManager(str(tmp_path / "ckpt"), use_async=False)
        mgr.save(1, {"w": np.ones(4, dtype=np.float32)})
        with open(os.path.join(mgr._step_dir(1), ck.MANIFEST_NAME)) as f:
            man = json.load(f)
        assert "arrays" not in man
        # verify(deep=True) degrades to the shallow verdict, not False
        assert mgr.verify(1, deep=True) is True
        mgr.close()


# ---------------------------------------------------------------------------
# deterministic step replay
# ---------------------------------------------------------------------------

class TestReplay:
    def test_replay_ok_then_sdc_on_tampered_record(self, tmp_path):
        root = str(tmp_path / "ckpt")
        loader = _loader(n=3)  # short epoch: exercises cursor rollover

        def factory():
            return _mlp_trainer(check_every=0)

        mgr = CheckpointManager(root, use_async=False, max_to_keep=8,
                                deep_digests=True)
        res = run_resilient(factory(), loader, steps=4, manager=mgr,
                            save_every=1, handle_signals=False)
        mgr.close()
        assert res.exit_code == 0
        rep = integrity.replay_step(root, 3, factory, loader)
        assert rep["verdict"] == "ok", rep
        assert rep["restored_from"] == 2
        assert rep["mismatched_keys"] == []
        # tamper ONE recorded digest: replays still agree with each
        # other, so the mismatch is pinned on the record — SDC
        mpath = os.path.join(root, "3", ck.MANIFEST_NAME)
        with open(mpath) as f:
            man = json.load(f)
        key = sorted(k for k in man["arrays"] if "params" in k)[0]
        man["arrays"][key] = "crc32:deadbeef:1"
        with open(mpath, "w") as f:
            json.dump(man, f)
        rep2 = integrity.replay_step(root, 3, factory, loader)
        assert rep2["verdict"] == "sdc", rep2
        assert rep2["mismatched_keys"] == [key]
        assert rep2["replay_mismatch_keys"] == []

    def test_replay_without_digests_reports_no_reference(self, tmp_path):
        root = str(tmp_path / "ckpt")
        loader = _loader(n=3)

        def factory():
            return _mlp_trainer(check_every=0)

        mgr = CheckpointManager(root, use_async=False, deep_digests=False)
        run_resilient(factory(), loader, steps=3, manager=mgr,
                      save_every=1, handle_signals=False)
        mgr.close()
        rep = integrity.replay_step(root, 2, factory, loader)
        assert rep["verdict"] == "no_reference"


# ---------------------------------------------------------------------------
# hang watchdog
# ---------------------------------------------------------------------------

class TestHangWatchdog:
    def test_fires_on_deadline_and_sets_event(self, fresh_registry):
        beats = []
        wd = integrity.HangWatchdog(0.15, heartbeat_fn=lambda:
                                    beats.append(time.monotonic()),
                                    poll=0.02).start()
        try:
            wd.arm(step=0)
            integrity.simulate_hang(max_seconds=5.0)
            assert integrity.hang_event.is_set()
            assert wd.fired == 1
        finally:
            wd.stop()
        assert beats  # it pumped heartbeats while healthy
        assert _series_total(
            fresh_registry, "hang_watchdog_fired_total") == 1

    def test_disarm_prevents_firing(self):
        wd = integrity.HangWatchdog(0.1, poll=0.02).start()
        try:
            wd.arm(step=0)
            wd.disarm()
            time.sleep(0.3)
            assert wd.fired == 0
            assert not integrity.hang_event.is_set()
        finally:
            wd.stop()

    def test_heartbeats_stop_after_firing(self):
        beats = []
        wd = integrity.HangWatchdog(0.1, heartbeat_fn=lambda:
                                    beats.append(1), poll=0.02).start()
        try:
            wd.arm(step=0)
            integrity.simulate_hang(max_seconds=5.0)
            n = len(beats)
            time.sleep(0.2)
            assert len(beats) == n  # a fired watchdog stops advertising
        finally:
            wd.stop()

    def test_runner_host_hang_in_process(self, fresh_registry, tmp_path):
        """host_hang wedges one step; with hang_exit=None the watchdog
        firing releases the wedge and the run completes, counting the
        hang."""
        mgr = CheckpointManager(str(tmp_path / "ckpt"), use_async=False)
        with faults.inject("host_hang", at_step=2) as f:
            res = run_resilient(_mlp_trainer(check_every=0), _loader(),
                                steps=4, manager=mgr, save_every=2,
                                handle_signals=False, hang_timeout=0.3)
        assert f.fired == 1
        assert res.exit_code == 0
        assert res.hangs >= 1
        assert res.steps_done == 4
        assert _series_total(
            fresh_registry, "hang_watchdog_fired_total") >= 1
        mgr.close()


# ---------------------------------------------------------------------------
# fault kinds + site labels
# ---------------------------------------------------------------------------

class TestFaultKinds:
    def test_new_kinds_registered(self):
        assert "param_flip" in faults.KINDS
        assert "host_hang" in faults.KINDS

    def test_fire_spec_returns_the_spec(self):
        with faults.inject("param_flip", at_step=5, seed=42) as f:
            assert faults.fire_spec("param_flip", step=4) is None
            spec = faults.fire_spec("param_flip", step=5)
            assert spec is f and spec.seed == 42

    def test_site_label_recorded(self, fresh_registry):
        with faults.inject("host_hang", at_step=0):
            assert faults.fires("host_hang", step=0, site="train_step")
        c = fresh_registry.get("resilience_faults_injected_total")
        assert c.value(kind="host_hang", site="train_step") == 1
