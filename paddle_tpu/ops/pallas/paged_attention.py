"""Paged (block-table) decode attention — XLA gather baseline + Pallas TPU
kernel.

Decode-time attention for the paged KV cache (``inference/kv_cache.py``):
each sequence's keys/values live scattered across fixed-size pool pages and
a per-sequence block table names the pages in order. Dense attention would
need the KV contiguous; here the gather happens through the table.

Two interchangeable paths (numerics asserted identical in tests):

- **XLA gather** (mandatory baseline, any backend): ``pool[tables]``
  advanced indexing → one rectangular (B, S, H, D) view per batch, masked
  to each row's true context length. Also provides the ragged
  *mixed-batch* path (:func:`paged_prefill_attention`) where prefill-chunk
  and decode-step rows share one flattened token axis.
- **Pallas kernel** (decode steps Tq == 1, and the ragged speculative-
  verify path 1 < Tq <= 32): the flash-attention streaming structure —
  grid ``(batch, heads, pages)``, online softmax in VMEM scratch — with
  the KV *block index maps reading the block table from scalar-prefetch
  SMEM* (``PrefetchScalarGridSpec``), so each grid step DMAs exactly one
  page and fully-masked pages are skipped. Page-tail masking reuses
  flash's ``kv_lens`` column-mask idiom (finite ``NEG_INF`` plus explicit
  ``p`` zeroing so fully-masked rows yield 0, not NaN). The kernel
  returns *unnormalized* (acc, m, l) running stats; the chunk tokens'
  self-attention term — a single score for a decode step, a causal
  (Tq, Tq) block for a verify chunk — is folded in a tiny jnp epilogue,
  so the new K/V never has to be scattered into the pool before
  attention reads it. The kernel body is row-wise: the verify path packs
  the Tq distinct queries into the sublane rows the decode path
  broadcasts one query across (``_paged_verify_kernel`` is the same body
  under its own name so the tuner/lint keying can tell the shapes apart).

Config (``q_pad`` — sublane rows holding the query/queries, 8 for f32
tiles / 16 for the bf16 tile shape; the verify path needs
``q_pad >= bucket(Tq)``) resolves through the tuning DB under kernel name
``"paged_attention"``; interpret-validated seeds ship in
``tuning_db.json`` — verify shapes carry an extra ``sq`` dim in the key.

Public API:
    paged_decode_attention(q, k_pool, v_pool, block_tables, context_lens,
                           k_new=None, v_new=None, ...)
    paged_prefill_attention(q, k_new, v_new, row_id, positions, valid,
                            k_pool, v_pool, block_tables, context_lens)
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, STAT_LANES, LANES

DEFAULT_Q_PAD = 8  # sublane rows the single decode query is broadcast to
MAX_VERIFY_TQ = 32  # widest speculative-verify chunk the kernel packs


def verify_rows(tq: int) -> int:
    """Sublane rows a Tq-query verify chunk occupies: Tq rounded up to
    the next power of two, floored at the f32 tile height. This — not
    the config's ``q_pad`` — keys the tuning DB for verify shapes, so
    the key is recoverable from the traced kernel's padded query shape."""
    from .tuner import shape_bucket
    return shape_bucket(int(tq), floor=8)


def paged_dims(d: int, page_size: int, num_pages: int, tq: int = 1) -> dict:
    """Tuning-DB dims for a paged decode call: head_dim and page size
    exact (hardware tiles), max context bucketed (one entry serves every
    block-table width whose capacity lands in the bucket). Verify calls
    (tq > 1) add the padded query-row bucket as ``sq``; decode keys stay
    exactly as the shipped seeds spell them."""
    from .tuner import shape_bucket
    dims = {"d": int(d), "ps": int(page_size),
            "sk": shape_bucket(int(page_size) * int(num_pages))}
    if int(tq) > 1:
        dims["sq"] = verify_rows(tq)
    return dims


def paged_decode_supported(q, k_pool, interpret: bool = False) -> bool:
    """Gate for the Pallas paged-decode/verify kernel: 1..MAX_VERIFY_TQ
    query tokens per row, tileable head_dim, sublane-aligned page size.
    Interpret mode lifts the backend requirement (CPU tests)."""
    return ((interpret or jax.default_backend() == "tpu") and
            q.ndim == 4 and 1 <= q.shape[1] <= MAX_VERIFY_TQ and
            q.shape[-1] in (32, 64, 128, 256) and
            k_pool.shape[1] % 8 == 0)


# ---------------------------------------------------------------------------
# XLA gather baseline
# ---------------------------------------------------------------------------

def _gather_ctx(pool, tables):
    """(P, ps, H, D) pool + (B, n) tables → (B, n*ps, H, D) context."""
    b, n = tables.shape
    p, ps, h, d = pool.shape
    return pool[tables].reshape(b, n * ps, h, d)


def _xla_paged_decode(q, k_pool, v_pool, tables, lens, k_new, v_new,
                      sm_scale):
    b, tq, h, d = q.shape
    kc = _gather_ctx(k_pool, tables).astype(jnp.float32)   # (B, S, H, D)
    vc = _gather_ctx(v_pool, tables).astype(jnp.float32)
    s_len = kc.shape[1]
    qf = q.astype(jnp.float32) * sm_scale
    s_ctx = jnp.einsum("bqhd,bshd->bhqs", qf, kc)          # (B, H, Tq, S)
    cols = jnp.arange(s_len, dtype=jnp.int32)
    ctx_mask = cols[None, None, None, :] < \
        lens.astype(jnp.int32)[:, None, None, None]
    s_ctx = jnp.where(ctx_mask, s_ctx, NEG_INF)
    if k_new is not None:
        knf = k_new.astype(jnp.float32)
        s_new = jnp.einsum("bqhd,buhd->bhqu", qf, knf)     # (B, H, Tq, Tq)
        rows = jnp.arange(tq, dtype=jnp.int32)
        causal = rows[None, None, :, None] >= rows[None, None, None, :]
        s_new = jnp.where(causal, s_new, NEG_INF)
        s = jnp.concatenate([s_ctx, s_new], axis=-1)
    else:
        s = s_ctx
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m) * (s > NEG_INF * 0.5)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhqs,bshd->bqhd", p[..., :s_len], vc)
    if v_new is not None:
        out = out + jnp.einsum("bhqu,buhd->bqhd", p[..., s_len:],
                               v_new.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas decode kernel (Tq == 1)
# ---------------------------------------------------------------------------

def _paged_decode_kernel(tables_ref, lens_ref,      # scalar prefetch (SMEM)
                         q_ref,                     # (1, 1, q_pad, D)
                         k_ref, v_ref,              # (1, ps, 1, D)
                         o_ref,                     # (1, 1, q_pad, D) f32
                         m_ref, l_ref,              # (1, 1, q_pad, STAT)
                         m_scr, l_scr, acc_scr,     # VMEM running stats
                         *, sm_scale, page_size, num_pages):
    del tables_ref  # consumed by the index maps
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(i * page_size < lens_ref[b])
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale     # (q_pad, D)
        k = k_ref[0, :, 0, :].astype(jnp.float32)          # (ps, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        cols = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(cols < lens_ref[b], s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = p * (s > NEG_INF * 0.5)      # fully-masked rows stay at l == 0
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(i == num_pages - 1)
    def _finalize():
        # UNnormalized acc + (m, l): the wrapper epilogue folds the new
        # token's self-attention term before dividing
        o_ref[0, 0] = acc_scr[:].astype(o_ref.dtype)
        m_ref[0, 0] = jnp.broadcast_to(m_scr[:, :1],
                                       (m_scr.shape[0], STAT_LANES))
        l_ref[0, 0] = jnp.broadcast_to(l_scr[:, :1],
                                       (l_scr.shape[0], STAT_LANES))


def _paged_verify_kernel(*args, **kwargs):
    """Same body as :func:`_paged_decode_kernel`, under its own name:
    the sublane rows hold Tq DISTINCT queries (speculative verify)
    instead of one broadcast query, and the tuner/lint keying
    (``entry_for_traced_call``) recovers the ``sq`` dim from the traced
    query-row count only for this kernel name."""
    return _paged_decode_kernel(*args, **kwargs)


def _run_paged_kernel(kernel_fn, qhp, k_pool, v_pool, tables, lens,
                      sm_scale, interpret):
    """pallas_call plumbing shared by the decode and verify wrappers:
    qhp is (B, H, q_pad, D); returns unnormalized (acc, m, l)."""
    b, h, q_pad, d = qhp.shape
    num_pool_pages, ps, _, _ = k_pool.shape
    npages = tables.shape[1]
    # masked-out table slots may hold sentinel ids: the index map fetches
    # even skipped pages, so clamp every slot into the pool
    tables = jnp.clip(tables.astype(jnp.int32), 0, num_pool_pages - 1)
    lens = jnp.minimum(lens.astype(jnp.int32), npages * ps).reshape(b)

    kernel = functools.partial(
        kernel_fn, sm_scale=sm_scale, page_size=ps,
        num_pages=npages)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, npages),
        in_specs=[
            pl.BlockSpec((1, 1, q_pad, d),
                         lambda bi, hi, i, tables, lens: (bi, hi, 0, 0)),
            pl.BlockSpec((1, ps, 1, d),
                         lambda bi, hi, i, tables, lens:
                         (tables[bi, i], 0, hi, 0)),
            pl.BlockSpec((1, ps, 1, d),
                         lambda bi, hi, i, tables, lens:
                         (tables[bi, i], 0, hi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, q_pad, d),
                         lambda bi, hi, i, tables, lens: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, q_pad, STAT_LANES),
                         lambda bi, hi, i, tables, lens: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, q_pad, STAT_LANES),
                         lambda bi, hi, i, tables, lens: (bi, hi, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((q_pad, LANES), jnp.float32),
            pltpu.VMEM((q_pad, LANES), jnp.float32),
            pltpu.VMEM((q_pad, d), jnp.float32),
        ],
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, h, q_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, q_pad, STAT_LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, h, q_pad, STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(tables, lens, qhp, k_pool, v_pool)
    return acc, m, l


def _pallas_paged_decode(q, k_pool, v_pool, tables, lens, k_new, v_new,
                         sm_scale, q_pad, interpret):
    b, tq, h, d = q.shape
    # (B, 1, H, D) → (B, H, q_pad, D): broadcast the single query row
    # across the sublane tile (all rows compute identical stats)
    qhp = jnp.broadcast_to(jnp.transpose(q, (0, 2, 1, 3)),
                           (b, h, q_pad, d))
    acc, m, l = _run_paged_kernel(_paged_decode_kernel, qhp, k_pool,
                                  v_pool, tables, lens, sm_scale,
                                  interpret)
    acc = acc[:, :, 0, :]                                   # (B, H, D)
    m = m[:, :, 0, 0]                                       # (B, H)
    l = l[:, :, 0, 0]
    if k_new is None:
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc / l_safe[..., None]
    else:
        # fold the new token's self-attention term (score vs itself is
        # always unmasked: a decode step attends to its own position)
        qf = q[:, 0].astype(jnp.float32)                    # (B, H, D)
        s_self = jnp.sum(qf * k_new[:, 0].astype(jnp.float32),
                         axis=-1) * sm_scale                # (B, H)
        m2 = jnp.maximum(m, s_self)
        alpha = jnp.exp(m - m2)       # finite NEG_INF → underflows to 0
        w_self = jnp.exp(s_self - m2)
        l2 = l * alpha + w_self
        out = (acc * alpha[..., None] +
               w_self[..., None] * v_new[:, 0].astype(jnp.float32)) / \
            l2[..., None]
    return out[:, None].astype(q.dtype)                     # (B, 1, H, D)


def _pallas_paged_verify(q, k_pool, v_pool, tables, lens, k_new, v_new,
                         sm_scale, q_pad, interpret):
    """Speculative-verify path (1 < Tq <= MAX_VERIFY_TQ): the Tq chunk
    queries ride the sublane rows the decode path broadcasts across —
    the kernel body is already row-wise, so each row streams the SAME
    cached context (every chunk token attends to the full prefix) with
    per-row online-softmax stats. The causal (Tq, Tq) self block over
    the chunk's own new K/V folds in the epilogue."""
    b, tq, h, d = q.shape
    rows = max(int(q_pad), verify_rows(tq))
    qt = jnp.transpose(q, (0, 2, 1, 3))                     # (B, H, Tq, D)
    qhp = jnp.pad(qt, ((0, 0), (0, 0), (0, rows - tq), (0, 0)))
    acc, m, l = _run_paged_kernel(_paged_verify_kernel, qhp, k_pool,
                                  v_pool, tables, lens, sm_scale,
                                  interpret)
    acc = acc[:, :, :tq, :]                                 # (B, H, Tq, D)
    m = m[:, :, :tq, 0]                                     # (B, H, Tq)
    l = l[:, :, :tq, 0]
    if k_new is None:
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc / l_safe[..., None]
    else:
        qf = q.astype(jnp.float32) * sm_scale
        s_self = jnp.einsum("bqhd,buhd->bhqu", qf,
                            k_new.astype(jnp.float32))      # (B,H,Tq,Tq)
        rng = jnp.arange(tq, dtype=jnp.int32)
        causal = rng[None, None, :, None] >= rng[None, None, None, :]
        s_self = jnp.where(causal, s_self, NEG_INF)
        m2 = jnp.maximum(m, jnp.max(s_self, axis=-1))
        alpha = jnp.exp(m - m2)       # finite NEG_INF → underflows to 0
        p_self = jnp.exp(s_self - m2[..., None]) * (s_self > NEG_INF * 0.5)
        l2 = l * alpha + jnp.sum(p_self, axis=-1)
        out = (acc * alpha[..., None] +
               jnp.einsum("bhqu,buhd->bhqd", p_self,
                          v_new.astype(jnp.float32))) / l2[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # (B,Tq,H,D)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def paged_decode_attention(q, k_pool, v_pool, block_tables, context_lens,
                           k_new=None, v_new=None, sm_scale=None,
                           kernel="auto", q_pad=None, interpret=False):
    """Decode attention through a block table.

    q: (B, Tq, H, D) new-token queries (Tq == 1 for pure decode;
    Tq = 1 + K for a speculative-verify chunk — every query attends the
    full cached context plus the chunk's earlier tokens causally).
    k_pool/v_pool: (P, page_size, H, D) page pools.
    block_tables: (B, n_pages) int32 page ids per row (padded slots may
    hold any value; only the first ceil(len/page_size) are read).
    context_lens: (B,) int32 valid cached tokens per row.
    k_new/v_new: optional (B, Tq, H, D) K/V of the query tokens
    themselves (not yet written to the pool); query i additionally
    attends causally to new tokens j <= i.

    kernel: "auto" (Pallas where supported, else XLA), "xla", "pallas".
    q_pad: Pallas sublane padding; ``None`` resolves from the tuning DB
    (kernel name ``"paged_attention"``).
    """
    b, tq, h, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    sm_scale = float(sm_scale)
    use_pallas = kernel == "pallas" or (
        kernel == "auto" and
        paged_decode_supported(q, k_pool, interpret=interpret))
    if kernel == "pallas" and \
            not paged_decode_supported(q, k_pool, interpret=interpret):
        raise ValueError("pallas paged decode unsupported for this "
                         f"shape/backend: q={q.shape} pool={k_pool.shape}")
    if use_pallas:
        from .tuner import resolve
        if q_pad is None:
            cfg, _ = resolve("paged_attention", q.dtype,
                             paged_dims(d, k_pool.shape[1],
                                        block_tables.shape[1], tq=tq),
                             {"q_pad": (DEFAULT_Q_PAD if tq == 1
                                        else verify_rows(tq))})
            q_pad = cfg["q_pad"]
        impl = _pallas_paged_decode if tq == 1 else _pallas_paged_verify
        return impl(q, k_pool, v_pool, block_tables,
                    context_lens, k_new, v_new, sm_scale,
                    int(q_pad), interpret)
    if kernel == "auto":
        from .tuner import record_fallback
        record_fallback("paged_attention")
    return _xla_paged_decode(q, k_pool, v_pool, block_tables, context_lens,
                             k_new, v_new, sm_scale)


def paged_prefill_attention(q, k_new, v_new, row_id, positions, valid,
                            k_pool, v_pool, block_tables, context_lens,
                            sm_scale=None):
    """Ragged mixed-batch attention (XLA): prefill chunks and decode
    steps flattened onto one token axis.

    q/k_new/v_new: (T, H, D) — chunk tokens of ALL rows concatenated.
    row_id: (T,) which batch row each token belongs to; positions: (T,)
    absolute position of each token in its sequence; valid: (T,) 1 for
    real tokens, 0 for padding. block_tables: (R, n_pages);
    context_lens: (R,) cached tokens per row. Each token attends to its
    row's cached context plus same-row chunk tokens at positions <= its
    own. Returns (T, H, D).
    """
    t, h, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    row_id = row_id.astype(jnp.int32)
    kc = _gather_ctx(k_pool, block_tables).astype(jnp.float32)  # (R,S,H,D)
    vc = _gather_ctx(v_pool, block_tables).astype(jnp.float32)
    s_len = kc.shape[1]
    kct = jnp.take(kc, row_id, axis=0)                      # (T, S, H, D)
    vct = jnp.take(vc, row_id, axis=0)
    qf = q.astype(jnp.float32) * sm_scale
    s_ctx = jnp.einsum("thd,tshd->ths", qf, kct)            # (T, H, S)
    cols = jnp.arange(s_len, dtype=jnp.int32)
    ctx_len_t = jnp.take(context_lens.astype(jnp.int32), row_id)
    s_ctx = jnp.where(cols[None, None, :] < ctx_len_t[:, None, None],
                      s_ctx, NEG_INF)
    s_new = jnp.einsum("thd,uhd->thu", qf,
                       k_new.astype(jnp.float32))           # (T, H, T)
    same_row = row_id[:, None] == row_id[None, :]
    causal = positions[None, :].astype(jnp.int32) <= \
        positions[:, None].astype(jnp.int32)
    ok = same_row & causal & (valid[None, :] > 0)
    s_new = jnp.where(ok[:, None, :], s_new, NEG_INF)
    s = jnp.concatenate([s_ctx, s_new], axis=-1)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m) * (s > NEG_INF * 0.5)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("ths,tshd->thd", p[..., :s_len], vct) + \
        jnp.einsum("thu,uhd->thd", p[..., s_len:],
                   v_new.astype(jnp.float32))
    return out.astype(q.dtype)
