"""Device-mesh management.

TPU-native replacement for the reference's 4-D communicator topology
(reference: fleet/base/topology.py:36 CommunicateTopology, :117
HybridCommunicateGroup, axis order ["data", "pipe", "sharding", "model"]).
Instead of NCCL rings per axis-subgroup (collective_helper.h), we build ONE
jax.sharding.Mesh whose named axes are the hybrid-parallel axes; XLA derives
every subgroup collective from shardings/axis names.

An extra "sep" (sequence/context-parallel) axis is supported beyond the
reference — used by ring attention (SURVEY.md §5 long-context gap).

Axis link types: every mesh axis rides one of two physical link classes —
"ici" (intra-slice torus, ~100s of GB/s per chip) or "dcn" (the
data-center network between slices, ~10s of Gb/s per host). The
compressed gradient exchange (distributed/compressed.py) gates its
quantization per axis on this map: quantize overhead LOSES on ICI hops
and wins on DCN. ``axis_links`` infers the map from the devices' slice
structure (``slice_index`` on multi-slice TPU, ``process_index``
otherwise); ``set_axis_links`` / ``build_mesh(axis_links=...)`` override
it explicitly (the only way a forced-host CPU test mesh can model a
multi-slice topology).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: F401

AXES_ORDER = ("data", "pipe", "sharding", "sep", "model")

LINK_TYPES = ("ici", "dcn")


class _MeshState(threading.local):
    def __init__(self):
        self.mesh: Optional[Mesh] = None
        # _mesh_key(mesh) -> {axis: "ici"|"dcn"} explicit overrides
        self.links: Dict[tuple, Dict[str, str]] = {}


_state = _MeshState()


def _mesh_key(mesh: Mesh) -> tuple:
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
            tuple(int(getattr(d, "id", i))
                  for i, d in enumerate(mesh.devices.flat)))


def build_mesh(degrees: Dict[str, int], devices: Optional[Sequence] = None,
               axis_links: Optional[Dict[str, str]] = None) -> Mesh:
    """Create (and set current) a named mesh from per-axis degrees.

    Axis order follows the reference's topology.py ordering so that
    neighboring ranks in the fastest-varying axis ("model") are
    ICI-adjacent — TP traffic rides the fastest links, DP the slowest, the
    same locality reasoning as the reference's ring assignment.

    ``axis_links`` optionally pins each axis's link type ("ici"/"dcn")
    instead of inferring it from the devices' slice structure.
    """
    devices = list(devices if devices is not None else jax.devices())
    shape = [int(degrees.get(a, 1)) for a in AXES_ORDER]
    total = int(np.prod(shape))
    if total != len(devices):
        if total < len(devices):
            devices = devices[:total]
        else:
            raise ValueError(f"mesh degrees {degrees} need {total} devices, "
                             f"have {len(devices)}")
    arr = np.asarray(devices).reshape(shape)
    mesh = Mesh(arr, AXES_ORDER)
    _state.mesh = mesh
    if axis_links is not None:
        set_axis_links(axis_links, mesh=mesh)
    return mesh


def set_mesh(mesh: Mesh):
    _state.mesh = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _state.mesh


def require_mesh() -> Mesh:
    if _state.mesh is None:
        # default: pure data-parallel over all local devices
        return build_mesh({"data": len(jax.devices())})
    return _state.mesh


def named_sharding(*spec) -> NamedSharding:
    return NamedSharding(require_mesh(), P(*spec))


def axis_size(axis: str) -> int:
    mesh = get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


# ---------------------------------------------------------------------------
# mesh-axis -> link-type map (ICI vs DCN)
# ---------------------------------------------------------------------------

def set_axis_links(links: Dict[str, str], mesh: Optional[Mesh] = None
                   ) -> Dict[str, str]:
    """Explicitly declare each axis's link type for ``mesh`` (current mesh
    when None). Unlisted axes default to "ici" at query time. Overrides
    inference — the knob for CPU test meshes modeling multi-slice
    topologies and for operators who know better than the heuristic."""
    mesh = mesh if mesh is not None else require_mesh()
    links = {str(k): str(v) for k, v in links.items()}
    for ax, link in links.items():
        if link not in LINK_TYPES:
            raise ValueError(f"link type for axis {ax!r} must be one of "
                             f"{LINK_TYPES}, got {link!r}")
        if ax not in mesh.axis_names:
            raise ValueError(f"axis {ax!r} not in mesh axes "
                             f"{tuple(mesh.axis_names)}")
    _state.links[_mesh_key(mesh)] = links
    return links


def explicit_axis_links(mesh: Optional[Mesh] = None
                        ) -> Optional[Dict[str, str]]:
    """The explicitly-set link map for ``mesh``, or None if none was set
    (callers that must not guess — e.g. the link-mismatch lint on
    single-slice hosts — check this before falling back to inference)."""
    mesh = mesh if mesh is not None else get_mesh()
    if mesh is None:
        return None
    links = _state.links.get(_mesh_key(mesh))
    return dict(links) if links is not None else None


def _slice_id(device) -> int:
    """The failure/link domain a device lives in: TPU slice when the
    platform exposes it, else the owning process (multi-host CPU/GPU)."""
    for attr in ("slice_index",):
        v = getattr(device, attr, None)
        if v is not None:
            return int(v)
    return int(getattr(device, "process_index", 0))


def infer_axis_links(mesh: Optional[Mesh] = None) -> Dict[str, str]:
    """Infer each axis's link type from the device array: an axis is
    "dcn" iff stepping along it ever crosses a slice (or process)
    boundary — those hops leave the ICI torus. Size-1 axes are "ici"
    (they move no data)."""
    mesh = mesh if mesh is not None else require_mesh()
    devs = mesh.devices
    ids = np.empty(devs.shape, dtype=np.int64)
    for idx, d in np.ndenumerate(devs):
        ids[idx] = _slice_id(d)
    links = {}
    for i, ax in enumerate(mesh.axis_names):
        if devs.shape[i] <= 1:
            links[ax] = "ici"
            continue
        lo = np.take(ids, range(devs.shape[i] - 1), axis=i)
        hi = np.take(ids, range(1, devs.shape[i]), axis=i)
        links[ax] = "dcn" if bool((lo != hi).any()) else "ici"
    return links


def axis_links(mesh: Optional[Mesh] = None) -> Dict[str, str]:
    """The axis -> link-type map: explicit override when set, else
    inferred from slice structure."""
    mesh = mesh if mesh is not None else require_mesh()
    explicit = explicit_axis_links(mesh)
    if explicit is not None:
        out = {ax: "ici" for ax in mesh.axis_names}
        out.update(explicit)
        return out
    return infer_axis_links(mesh)


def axis_link(axis: str, mesh: Optional[Mesh] = None) -> str:
    """Link type of one axis ("ici" when the axis is unknown)."""
    return axis_links(mesh).get(axis, "ici")


# ---------------------------------------------------------------------------
# link bandwidth constants (the overlap model's wire-time denominators)
# ---------------------------------------------------------------------------

# Per-chip effective bandwidth in bytes/sec for each link class. The ICI
# figure is one v5e-class torus link pair (~90 GB/s); DCN is a 50 Gb/s
# per-host share (~6.25 GB/s). These feed analysis/cost.py's collective
# time estimates (time = ring wire bytes / bandwidth) — they rank
# schedules and size overlap windows, they are not a profiler. Override
# per deployment with PADDLE_TPU_ICI_BPS / PADDLE_TPU_DCN_BPS.
LINK_BANDWIDTHS: Dict[str, float] = {"ici": 9.0e10, "dcn": 6.25e9}

_LINK_BW_ENV = {"ici": "PADDLE_TPU_ICI_BPS", "dcn": "PADDLE_TPU_DCN_BPS"}
_LINK_LAT_ENV = {"ici": "PADDLE_TPU_ICI_LAT_S", "dcn": "PADDLE_TPU_DCN_LAT_S"}


def _calibration():
    # lazy + fail-soft: mesh must import before telemetry and still price
    # links if the calibration layer is somehow unavailable
    try:
        from paddle_tpu.telemetry import calibration
        return calibration
    except Exception:  # pragma: no cover
        return None


def link_bandwidth(link: str) -> float:
    """Bytes/sec of one link class.

    Precedence: env override > calibration-DB fitted constant
    (``telemetry.calibration``, written by ``bench_collectives --suite
    calibrate``) > the shipped :data:`LINK_BANDWIDTHS` figure. This is
    the one choke point every wire-time consumer (cost.overlap_summary,
    the sharding pass, auto.resharding_cost) prices through.
    """
    import os
    env = os.environ.get(_LINK_BW_ENV.get(link, ""), "")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    cal = _calibration()
    if cal is not None:
        fitted = cal.link_bandwidth_override(link)
        if fitted is not None:
            return fitted
    return LINK_BANDWIDTHS.get(link, LINK_BANDWIDTHS["ici"])


def link_latency(link: str) -> float:
    """Fixed per-collective latency (seconds) of one link class.

    Same precedence as :func:`link_bandwidth` (env > calibration DB),
    except the shipped default is 0.0 — the pure-bandwidth wire model —
    so un-calibrated behavior is exactly what it was before this term
    existed.
    """
    import os
    env = os.environ.get(_LINK_LAT_ENV.get(link, ""), "")
    if env:
        try:
            return max(0.0, float(env))
        except ValueError:
            pass
    cal = _calibration()
    if cal is not None:
        fitted = cal.link_latency_override(link)
        if fitted is not None:
            return fitted
    return 0.0


class CommunicateTopology:
    """reference: fleet/base/topology.py:36 — coordinate math over the mesh."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = None
        shape = tuple(dims)
        self._world = int(np.prod(shape))
        self._coords = {}
        for rank in range(self._world):
            self._coords[rank] = tuple(
                int(x) for x in np.unravel_index(rank, shape))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coords = tuple(kwargs[n] for n in self._parallel_names)
        return int(np.ravel_multi_index(coords, tuple(self._dims)))

    def get_coord(self, rank):
        return self._coords[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in self._coords.items() if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All subgroups along `axis_name` (list of rank lists)."""
        axis = self._parallel_names.index(axis_name)
        groups = {}
        for r, c in self._coords.items():
            key = tuple(v for i, v in enumerate(c) if i != axis)
            groups.setdefault(key, []).append(r)
        return [sorted(v) for _, v in sorted(groups.items())]


class HybridCommunicateGroup:
    """reference: fleet/base/topology.py:117 — per-rank view of the 4-D mesh.

    On TPU the "groups" are mesh axes; this object provides the reference's
    rank/degree queries for code (pipeline schedules, TP layers) that needs
    explicit coordinates.
    """

    def __init__(self, topology: CommunicateTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._mp_degree = topology.get_dim("model")
        coord = topology.get_coord(global_rank)
        names = topology.get_hybrid_group_names()
        self._coord = dict(zip(names, coord))

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_rank(self):
        return self._coord["data"]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_rank(self):
        return self._coord["model"]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_stage_id(self):
        return self._coord["pipe"]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_rank(self):
        return self._coord["sharding"]

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_next_rank(self):
        names = self._topo.get_hybrid_group_names()
        coords = dict(self._coord)
        coords["pipe"] = (coords["pipe"] + 1) % self._pp_degree
        return self._topo.get_rank(**coords)

    def get_p2p_prev_rank(self):
        coords = dict(self._coord)
        coords["pipe"] = (coords["pipe"] - 1) % self._pp_degree
        return self._topo.get_rank(**coords)
