"""End-to-end tracing + flight recorder (ISSUE 15): the span model and
explicit cross-thread handoff, tail-sampling keep/drop, the
decode-failover span-tree walk (one kept trace covering admission wait,
both dispatch attempts across the replica respawn, KV events, and every
decode re-entry), the zero-allocation disabled path, flight-dump
triggers (SIGUSR2, SLO shed burn rate), and the rank-0 merge of
per-host dumps."""
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from paddle_tpu import telemetry
from paddle_tpu.resilience import faults
from paddle_tpu.telemetry import flight, slo, tracing
from paddle_tpu.telemetry.export import chrome_trace
from paddle_tpu.telemetry.metrics import Registry
from paddle_tpu.telemetry.slo import SloMonitor, SloRule
from paddle_tpu.telemetry.tracing import KeepPolicy, Tracer


@pytest.fixture(autouse=True)
def _fresh_trace_state():
    tracing.disable()
    tracing.reset()
    flight.reset()
    slo.reset()
    yield
    faults.reset()
    tracing.disable()
    tracing.reset()
    flight.reset()
    slo.reset()


# ---------------------------------------------------------------------------
# span model


class TestSpanModel:
    def test_span_tree_parent_ids(self):
        tr = Tracer(policy=KeepPolicy(keep_all=True))
        t = tr.start_trace("work", job=7)
        a = t.span("phase_a")
        b = t.span("inner", parent=a)
        b.end("ok")
        a.end("ok")
        t.close("completed")
        [kept] = tr.snapshot_kept()
        by_id = {s["span_id"]: s for s in kept["spans"]}
        root = [s for s in kept["spans"] if s["parent_id"] is None]
        assert len(root) == 1 and root[0]["name"] == "work"
        assert root[0]["attrs"]["job"] == 7
        sa = next(s for s in kept["spans"] if s["name"] == "phase_a")
        sb = next(s for s in kept["spans"] if s["name"] == "inner")
        assert sa["parent_id"] == root[0]["span_id"]
        assert sb["parent_id"] == sa["span_id"]
        assert all(s["t1_ns"] >= s["t0_ns"] for s in by_id.values())

    def test_cross_thread_handoff_records_end_thread(self):
        # the explicit handoff contract: the Span object is carried to
        # another thread, which ends it — recording both identities
        tr = Tracer(policy=KeepPolicy(keep_all=True))
        t = tr.start_trace("ckpt_save")
        sp = t.span("commit")

        def _finish():
            sp.end("committed")
            t.close("committed")

        th = threading.Thread(target=_finish, name="committer-sim")
        th.start()
        th.join()
        [kept] = tr.snapshot_kept()
        commit = next(s for s in kept["spans"] if s["name"] == "commit")
        assert commit["thread"] == threading.current_thread().name
        assert commit["attrs"]["end_thread"] == "committer-sim"
        root = next(s for s in kept["spans"] if s["parent_id"] is None)
        assert root["attrs"]["end_thread"] == "committer-sim"

    def test_late_span_counts_dropped_and_accounting_closes(self):
        tr = Tracer(policy=KeepPolicy(keep_all=True))
        t = tr.start_trace("work")
        straggler = t.span("late")
        t.close("completed")
        assert tr.accounting()["recorded"] == 1   # root only, so far
        straggler.end("ok")         # ends after its trace closed
        a = tr.accounting()
        assert a["recorded"] == 2 and a["dropped"] == 1 and a["open"] == 0
        assert a["recorded"] == a["kept"] + a["dropped"]
        assert tr.accounted()
        # the late span never joins the kept trace's tree
        [kept] = tr.snapshot_kept()
        assert all(s["name"] != "late" for s in kept["spans"])

    def test_events_attach_in_order(self):
        tr = Tracer(policy=KeepPolicy(keep_all=True))
        t = tr.start_trace("work")
        sp = t.span("io")
        sp.event("read", bytes=10)
        sp.event("write", bytes=20)
        sp.end("ok")
        t.close("completed")
        [kept] = tr.snapshot_kept()
        io = next(s for s in kept["spans"] if s["name"] == "io")
        assert [e["name"] for e in io["events"]] == ["read", "write"]
        assert io["events"][0]["t_ns"] <= io["events"][1]["t_ns"]


class TestKeepPolicy:
    def test_bad_outcomes_and_failover_kept(self):
        p = KeepPolicy()
        assert p.decide("shed", 0.01, None, False) == "shed"
        assert p.decide("failed", 0.01, None, False) == "failed"
        assert p.decide("completed", 0.01, None, True) == "failover"
        assert p.decide("completed", 0.01, None, False) is None

    def test_deadline_fraction(self):
        p = KeepPolicy(deadline_fraction=0.9)
        assert p.decide("completed", 0.95, 1.0, False) == "deadline"
        assert p.decide("completed", 0.5, 1.0, False) is None

    def test_latency_percentile_needs_priors(self):
        p = KeepPolicy(percentile_min_samples=50)
        for _ in range(60):
            assert p.decide("completed", 0.001, None, False) is None
        assert p.decide("completed", 10.0, None, False) \
            == "latency_percentile"

    def test_keep_none_overrides_everything(self):
        p = KeepPolicy(keep_none=True)
        assert p.decide("failed", 10.0, 1.0, True) is None

    def test_keep_all(self):
        p = KeepPolicy(keep_all=True)
        assert p.decide("completed", 0.0, None, False) == "forced"


class TestDisabledPath:
    def test_everything_noops_and_allocates_no_spans(self):
        assert not tracing.enabled()
        assert tracing.start_trace("x") is None
        assert tracing.child_span("y") is None
        with tracing.use_span(None):
            tracing.add_event("ev", k=1)     # no ambient span: no-op
        a = tracing.accounting()
        assert a["recorded"] == 0 and a["traces_started"] == 0

    def test_enable_disable_roundtrip(self):
        tracing.enable()
        t = tracing.start_trace("x")
        assert t is not None
        t.close("completed")
        tracing.disable()
        assert tracing.start_trace("x") is None
        assert tracing.accounted()


# ---------------------------------------------------------------------------
# flight recorder


class TestFlightRecorder:
    def test_dump_payload_and_filename(self, tmp_path):
        flight.configure(str(tmp_path), process_index=3)
        flight.record({"name": "s1", "t0_ns": 1, "t1_ns": 2})
        path = flight.dump("hang_watchdog", step=12,
                           extra={"stalled": "host1"})
        assert path is not None
        assert os.path.basename(path) == "flight_hang_watchdog_12.json"
        with open(path) as f:
            d = json.load(f)
        assert d["reason"] == "hang_watchdog" and d["step"] == 12
        assert d["process_index"] == 3
        assert d["spans"] == [{"name": "s1", "t0_ns": 1, "t1_ns": 2}]
        assert d["extra"] == {"stalled": "host1"}
        assert "metrics" in d and "marks" in d
        assert flight.spans_dumped() == 1

    def test_same_reason_step_twice_never_clobbers(self, tmp_path):
        flight.configure(str(tmp_path))
        p1 = flight.dump("drain", step=0)
        p2 = flight.dump("drain", step=0)
        assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)

    def test_dump_without_destination_is_noop(self):
        assert flight.get_recorder()._resolve_dir() is None
        assert flight.dump("drain") is None
        assert flight.spans_dumped() == 0

    def test_env_var_destination(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "env"))
        path = flight.dump("sigusr2")
        assert path is not None and str(tmp_path / "env") in path

    def test_ring_bounds_memory(self):
        flight.reset(capacity=4)
        for i in range(10):
            flight.record({"i": i})
        rec = flight.get_recorder()
        assert rec.ring_len() == 4

    def test_find_dumps_filters_by_reason(self, tmp_path):
        flight.configure(str(tmp_path / "h0"))
        flight.dump("divergence", step=5)
        flight.dump("drain", step=5)
        root = str(tmp_path)
        assert len(flight.find_dumps(root)) == 2
        div = flight.find_dumps(root, reason="divergence")
        assert len(div) == 1 and "flight_divergence_5" in div[0]

    def test_merge_dumps_tags_process_index(self, tmp_path):
        reg = telemetry.get_registry()
        paths = []
        for pidx in (0, 1):
            flight.reset()
            flight.configure(str(tmp_path / f"h{pidx}"),
                             process_index=pidx)
            flight.record({"name": f"span_h{pidx}", "t0_ns": 1,
                           "t1_ns": 2})
            paths.append(flight.dump("hang_watchdog", step=9))
        out = str(tmp_path / "merged.json")
        merged = flight.merge_dumps(paths, out_path=out)
        assert {s["process_index"] for s in merged["spans"]} == {0, 1}
        assert {m["process_index"] for m in merged["dumps"]} == {0, 1}
        # per-host metric series stay distinct via process_index labels
        flat = json.dumps(merged["metrics"])
        assert "process_index" in flat
        with open(out) as f:
            assert json.load(f)["spans"] == merged["spans"]

    def test_merge_duplicate_process_index_keeps_all_spans(self, tmp_path):
        paths = []
        for k in range(2):   # two dumps claiming the same host index
            flight.reset()
            flight.configure(str(tmp_path / f"d{k}"), process_index=0)
            flight.record({"name": f"s{k}"})
            paths.append(flight.dump("drain"))
        merged = flight.merge_dumps(paths)
        assert len(merged["spans"]) == 2


class TestDumpTriggers:
    def test_sigusr2_dumps_flight_ring(self, tmp_path):
        flight.configure(str(tmp_path))
        flight.record({"name": "pre_signal"})
        prev = signal.getsignal(signal.SIGUSR2)
        try:
            assert flight.install_signal_handler()
            os.kill(os.getpid(), signal.SIGUSR2)
            deadline = time.monotonic() + 5.0
            while (not flight.find_dumps(str(tmp_path), "sigusr2")
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        finally:
            signal.signal(signal.SIGUSR2, prev)
        # >= 1: a handler installed earlier in the process chains through
        # ours and may dump a second time — both land in this dir
        dumps = flight.find_dumps(str(tmp_path), "sigusr2")
        assert len(dumps) >= 1
        with open(dumps[0]) as f:
            d = json.load(f)
        assert any(s.get("name") == "pre_signal" for s in d["spans"])

    def test_install_signal_handler_off_main_thread_refuses(self):
        out = {}

        def _try():
            out["ok"] = flight.install_signal_handler()

        th = threading.Thread(target=_try)
        th.start()
        th.join()
        assert out["ok"] is False

    def test_slo_shed_burn_rate_dump_latches(self, tmp_path):
        flight.configure(str(tmp_path))
        reg = Registry()
        rule = SloRule("shed_burn",
                       numerator="serving_requests_shed_total",
                       denominator="serving_requests_total",
                       threshold=0.3, window_s=5.0, min_denominator=10.0)
        mon = SloMonitor([rule], registry=reg)
        mon.poll(now=0.0)                      # baseline sample
        reg.counter("serving_requests_total").inc(20)
        reg.counter("serving_requests_shed_total").inc(10)
        mon.poll(now=1.0)                      # burn 0.5 > 0.3: fires
        assert rule.alerts == 1 and rule.latched
        dumps = flight.find_dumps(str(tmp_path), "slo_shed_burn")
        assert len(dumps) == 1
        with open(dumps[0]) as f:
            extra = json.load(f)["extra"]
        assert extra["burn_rate"] == pytest.approx(0.5)
        # hysteresis: a sustained breach is ONE alert, not one per poll
        reg.counter("serving_requests_total").inc(20)
        reg.counter("serving_requests_shed_total").inc(10)
        mon.poll(now=2.0)
        assert rule.alerts == 1
        assert len(flight.find_dumps(str(tmp_path), "slo_shed_burn")) == 1
        # recovery below threshold/2 unlatches; a new breach re-alerts
        reg.counter("serving_requests_total").inc(200)
        mon.poll(now=6.5)
        assert not rule.latched
        reg.counter("serving_requests_total").inc(20)
        reg.counter("serving_requests_shed_total").inc(15)
        mon.poll(now=7.5)
        assert rule.alerts == 2

    def test_install_shed_rule_registers_global_monitor(self):
        mon = slo.install_shed_rule(threshold=0.25)
        assert slo.get_monitor() is mon
        slo.maybe_poll()     # must be callable from hot paths, cheap
        slo.reset()
        slo.maybe_poll()     # and a no-op without a monitor


# ---------------------------------------------------------------------------
# chrome export: kept spans + thread_name metadata


def test_chrome_trace_includes_spans_and_thread_names(tmp_path):
    tracing.enable(policy=KeepPolicy(keep_all=True))
    t = tracing.start_trace("unit")
    sp = t.span("work")

    def _end():
        sp.end("ok")

    th = threading.Thread(target=_end, name="worker-thread-x")
    th.start()
    th.join()
    t.close("completed")
    tracing.disable()
    path = str(tmp_path / "trace.json")
    trace = chrome_trace(path)
    evs = trace["traceEvents"]
    span_evs = [e for e in evs if e.get("cat") == "trace"]
    assert {e["name"] for e in span_evs} >= {"unit", "work"}
    assert all(e["ts"] >= 0 for e in evs)
    metas = {e["tid"]: e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    work = next(e for e in span_evs if e["name"] == "work")
    # the span's opening thread is named in the viewer metadata
    assert metas.get(work["tid"]) == threading.current_thread().name


# ---------------------------------------------------------------------------
# decode serving end-to-end: the acceptance span-tree walk


@pytest.mark.slow
class TestDecodeFailoverTrace:
    def _stack(self):
        from paddle_tpu.inference import serving
        from paddle_tpu.inference.decode_model import (init_decode_model,
                                                       make_step_fn)
        from paddle_tpu.inference.kv_cache import PagedKVCache
        params = init_decode_model(vocab=128, num_heads=2, head_dim=32,
                                   seed=7)
        cache = PagedKVCache(64, 4, 2, 32)
        fn = make_step_fn(params, cache)
        cfg = serving.ServingConfig(max_batch=32, batch_wait_s=0.002,
                                    call_timeout_s=1.0,
                                    probation_base_s=0.02,
                                    probation_max_s=0.2, seed=3)
        # ONE replica: the retry must land on the respawned worker, so
        # the generation bump is visible in the kept trace
        srv = serving.DecodeServer(fn, cache, replicas=1, config=cfg,
                                   prefill_chunk=8, max_pages_per_seq=16)
        return srv

    @staticmethod
    def _prompt(i, extra=4):
        rs = np.random.RandomState(11)
        system = [int(t) for t in rs.randint(0, 128, 8)]
        rs = np.random.RandomState(100 + i)
        return system + [int(t) for t in rs.randint(0, 128, extra)]

    def test_single_kept_trace_covers_whole_request(self):
        max_new = 4
        tracing.enable(policy=KeepPolicy())   # the production policy
        srv = self._stack()
        with srv:
            # warm-up completes cleanly -> its trace must be DROPPED
            srv.submit_generate(self._prompt(0), 3).result(timeout=60)
            with faults.inject("replica_stall") as spec:
                r = srv.submit_generate(self._prompt(1), max_new)
                out = r.result(timeout=60)
            assert spec.fired == 1
            assert srv.stats()["failovers"] >= 1
            assert srv.accounted()
        tracing.disable()
        assert len(out[0]) == max_new

        kept = tracing.snapshot_kept()
        assert len(kept) == 1, "exactly the failover request is kept"
        tr = kept[0]
        assert tr["keep_reason"] == "failover"
        assert tr["outcome"] == "completed"

        spans = tr["spans"]
        assert len({s["trace_id"] for s in spans}) == 1
        roots = [s for s in spans if s["parent_id"] is None]
        assert len(roots) == 1 and roots[0]["name"] == "serving_request"
        root = roots[0]
        assert root["attrs"]["attempts"] == 1

        # admission wait, with the KV prefix hit reported ambiently
        waits = [s for s in spans if s["name"] == "admission_wait"]
        assert len(waits) == 1
        assert waits[0]["parent_id"] == root["span_id"]
        assert any(e["name"] == "kv_prefix_hit"
                   for e in waits[0]["events"])

        execs = sorted((s for s in spans if s["name"] == "execute"),
                       key=lambda s: s["t0_ns"])
        assert execs and all(s["parent_id"] == root["span_id"]
                             for s in execs)

        # attempt 0: dispatched to generation 0, ended by the requeue
        failed = [s for s in execs if s["status"] == "failover"]
        assert len(failed) == 1
        assert failed[0]["attrs"]["attempt"] == 0
        assert failed[0]["attrs"]["generation"] == 0

        # attempt 1: every span on the RESPAWNED worker (generation 1)
        retries = [s for s in execs if s["attrs"]["attempt"] == 1]
        assert retries and all(s["attrs"]["generation"] == 1
                               and s["attrs"]["replica"] == 0
                               for s in retries)
        assert all(s["t0_ns"] > failed[0]["t1_ns"] for s in retries)
        assert all(s["status"] in ("ok", "completed") for s in retries)

        # every decode re-entry is its own span, each committing KV
        decodes = [s for s in retries if s["attrs"]["phase"] == "decode"]
        assert len(decodes) >= max_new - 2
        assert all(any(e["name"] == "kv_append" for e in s["events"])
                   for s in retries)

        a = tracing.accounting()
        assert a["traces_closed"] == 2     # warm-up + failover request
        assert tracing.accounted()

    def test_disabled_serving_path_allocates_nothing(self):
        assert not tracing.enabled()
        srv = self._stack()
        with srv:
            r = srv.submit_generate(self._prompt(0), 2)
            r.result(timeout=60)
            assert r._trace is None and r._span_wait is None \
                and r._attempt_span is None
        assert tracing.accounting()["recorded"] == 0


# ---------------------------------------------------------------------------
# async checkpoint: the staged-tuple cross-thread handoff


@pytest.mark.slow
def test_ckpt_trace_commit_ends_on_committer_thread(tmp_path):
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    rng = np.random.RandomState(0)
    state = {"w": rng.randn(16, 4).astype(np.float32),
             "step": np.int64(1)}
    tracing.enable(policy=KeepPolicy(keep_all=True))
    m = CheckpointManager(str(tmp_path), async_commit=True)
    m.save(1, state)
    m.flush()
    m.close()
    tracing.disable()
    kept = [t for t in tracing.snapshot_kept() if t["name"] == "ckpt_save"]
    assert len(kept) == 1 and kept[0]["outcome"] == "committed"
    spans = kept[0]["spans"]
    snap = next(s for s in spans if s["name"] == "snapshot")
    commit = next(s for s in spans if s["name"] == "commit")
    root = next(s for s in spans if s["parent_id"] is None)
    # snapshot runs on the saving thread; the Trace object rides the
    # staged tuple to the committer, which opens the commit span and
    # closes the trace — the root records the cross-thread end
    assert snap["thread"] == threading.current_thread().name
    assert "end_thread" not in snap["attrs"]
    assert commit["thread"] == "ckpt-committer"
    assert root["thread"] == threading.current_thread().name
    assert root["attrs"]["end_thread"] == "ckpt-committer"
    assert commit["t0_ns"] >= snap["t1_ns"]
    assert tracing.accounted()
