"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST, CIFAR,
FashionMNIST; legacy python/paddle/dataset/).

This environment has zero egress, so the download path is gated: datasets
read local files in the reference's formats (IDX for MNIST, pickled batches
for CIFAR) and FakeData provides deterministic synthetic samples for tests
and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io.dataset import Dataset

DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME",
                                              "~/.cache/paddle_tpu/datasets"))


class FakeData(Dataset):
    """Deterministic synthetic dataset for tests/benchmarks."""

    def __init__(self, size=1024, image_shape=(1, 28, 28), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.asarray(rng.randint(0, self.num_classes), dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data


class MNIST(Dataset):
    """reference: python/paddle/vision/datasets/mnist.py. Reads local IDX
    files; pass image_path/label_path or place files under DATA_HOME/mnist."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        base = os.path.join(DATA_HOME, self.NAME)
        stem = "train" if self.mode == "train" else "t10k"
        if image_path is None:
            for suffix in ("-images-idx3-ubyte.gz", "-images-idx3-ubyte"):
                cand = os.path.join(base, stem + suffix)
                if os.path.exists(cand):
                    image_path = cand
                    break
        if label_path is None:
            for suffix in ("-labels-idx1-ubyte.gz", "-labels-idx1-ubyte"):
                cand = os.path.join(base, stem + suffix)
                if os.path.exists(cand):
                    label_path = cand
                    break
        if image_path is None or label_path is None:
            raise FileNotFoundError(
                f"MNIST files not found under {base}; this environment has no "
                f"network egress — place IDX files there or use "
                f"paddle_tpu.vision.datasets.FakeData for tests")
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :] / 255.0
        label = np.asarray(self.labels[idx], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference: python/paddle/vision/datasets/cifar.py — reads the original
    python-pickle tar from a local path."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        mode = mode.lower()
        data_file = data_file or os.path.join(DATA_HOME, "cifar",
                                              "cifar-10-python.tar.gz")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{data_file} not found; no network egress — place the CIFAR "
                f"archive locally or use FakeData")
        names = ([f"data_batch_{i}" for i in range(1, 6)] if mode == "train"
                 else ["test_batch"])
        xs, ys = [], []
        with tarfile.open(data_file, "r:*") as tar:
            for member in tar.getmembers():
                if any(member.name.endswith(n) for n in names):
                    batch = pickle.load(tar.extractfile(member), encoding="bytes")
                    xs.append(batch[b"data"])
                    ys.extend(batch.get(b"labels", batch.get(b"fine_labels")))
        self.data = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        data_file = data_file or os.path.join(DATA_HOME, "cifar",
                                              "cifar-100-python.tar.gz")
        super().__init__(data_file, mode, transform, download, backend)


_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                   ".tiff", ".webp")


def _pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        return Image.open(f).convert("RGB")


class DatasetFolder(Dataset):
    """Class-per-subdirectory image dataset (reference:
    python/paddle/vision/datasets/folder.py DatasetFolder): root/cls_x/a.jpg
    -> (sample, class_index). Samples sorted for determinism."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _pil_loader
        if extensions is None and is_valid_file is None:
            extensions = _IMG_EXTENSIONS
        if extensions is not None and is_valid_file is not None:
            raise ValueError(
                "pass either extensions or is_valid_file, not both")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise FileNotFoundError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        valid = (is_valid_file if is_valid_file is not None
                 else (lambda p: p.lower().endswith(tuple(extensions))))
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    p = os.path.join(dirpath, fn)
                    if valid(p):
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise FileNotFoundError(f"no valid files under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat (label-free) image dataset (reference folder.py ImageFolder):
    every valid file under root, recursively, sorted."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _pil_loader
        if extensions is None and is_valid_file is None:
            extensions = _IMG_EXTENSIONS
        valid = (is_valid_file if is_valid_file is not None
                 else (lambda p: p.lower().endswith(tuple(extensions))))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                p = os.path.join(dirpath, fn)
                if valid(p):
                    self.samples.append(p)
        if not self.samples:
            raise FileNotFoundError(f"no valid files under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Oxford 102 Flowers (reference vision/datasets/flowers.py). Reads the
    local 102flowers.tgz + imagelabels.mat + setid.mat (no egress)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        import scipy.io as sio
        base = os.path.join(DATA_HOME, "flowers")
        data_file = data_file or os.path.join(base, "102flowers.tgz")
        label_file = label_file or os.path.join(base, "imagelabels.mat")
        setid_file = setid_file or os.path.join(base, "setid.mat")
        for f in (data_file, label_file, setid_file):
            if not os.path.exists(f):
                raise FileNotFoundError(
                    f"{f} not found; no network egress — place the Flowers "
                    f"archive/mat files under {base}")
        self.transform = transform
        labels = sio.loadmat(label_file)["labels"].ravel()
        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[
            mode.lower()]
        self.indexes = setid[key].ravel()
        self.labels = labels
        self._data_file = data_file
        self._pid = None
        self._names = {os.path.basename(m.name): m
                       for m in self._open().getmembers() if m.isfile()}

    def _open(self):
        # one TarFile PER PROCESS: a fork-inherited handle shares its file
        # offset across DataLoader workers (interleaved seeks corrupt reads)
        if self._pid != os.getpid():
            self._tar = tarfile.open(self._data_file, "r:*")
            self._pid = os.getpid()
        return self._tar

    def __getitem__(self, idx):
        from PIL import Image
        flower_id = int(self.indexes[idx])
        member = self._names[f"image_{flower_id:05d}.jpg"]
        img = Image.open(self._open().extractfile(member)).convert("RGB")
        label = np.asarray(self.labels[flower_id - 1] - 1, dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (reference vision/datasets/voc2012.py).
    Reads the local VOCtrainval tar (no egress): returns (image, label
    mask) pairs from ImageSets/Segmentation/{train,val,trainval}.txt."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        data_file = data_file or os.path.join(DATA_HOME, "voc2012",
                                              "VOCtrainval_11-May-2012.tar")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{data_file} not found; no network egress — place the "
                f"VOC2012 tar locally")
        self.transform = transform
        self._data_file = data_file
        self._pid = None
        members = {m.name: m for m in self._open().getmembers()}
        mode = {"train": "train", "valid": "val", "test": "val",
                "trainval": "trainval"}[mode.lower()]
        listname = next(n for n in members
                        if n.endswith(f"ImageSets/Segmentation/{mode}.txt"))
        ids = self._open().extractfile(members[listname]).read() \
            .decode().split()
        prefix = listname.split("ImageSets")[0]
        self._pairs = [(members[f"{prefix}JPEGImages/{i}.jpg"],
                        members[f"{prefix}SegmentationClass/{i}.png"])
                       for i in ids]

    def _open(self):
        # per-process TarFile — see Flowers._open
        if self._pid != os.getpid():
            self._tar = tarfile.open(self._data_file, "r:*")
            self._pid = os.getpid()
        return self._tar

    def __getitem__(self, idx):
        from PIL import Image
        im_m, lb_m = self._pairs[idx]
        img = np.asarray(Image.open(self._open().extractfile(im_m))
                         .convert("RGB"))
        label = np.asarray(Image.open(self._open().extractfile(lb_m)))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self._pairs)
