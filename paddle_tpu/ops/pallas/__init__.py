from . import tuner  # noqa: F401
from .flash_attention import flash_attention, flash_supported  # noqa: F401
from .fused_ce import fused_ce_supported, fused_lm_ce  # noqa: F401
