"""Elastic manager, auto-checkpoint resume, group_sharded API (reference:
fleet/elastic.py, incubate/checkpoint/auto_checkpoint.py,
distributed sharding surface)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet import ElasticManager, ElasticStatus


def test_elastic_disabled_without_server():
    em = ElasticManager(elastic_server=None, np=2)
    assert not em.enable
    assert em.watch(proc_alive=lambda: True) == ElasticStatus.HOLD
    assert em.watch(proc_alive=lambda: False) == ElasticStatus.COMPLETED


def test_elastic_membership_lifecycle(tmp_path):
    kv = str(tmp_path / "kv")
    a = ElasticManager(elastic_server=kv, job_id="j1", np=2, host="hostA")
    b = ElasticManager(elastic_server=kv, job_id="j1", np=2, host="hostB")
    assert a.enable and b.enable
    a.register()
    assert a.watch() == ElasticStatus.RESTART  # 1 < np_min
    b.register()
    assert sorted(a.hosts()) == ["hostA", "hostB"]
    assert a.wait(max_wait=2)
    assert a.watch() == ElasticStatus.HOLD
    b.deregister()
    assert a.watch() == ElasticStatus.RESTART
    a.deregister()
    assert a.watch() == ElasticStatus.EXIT


def test_elastic_np_range(tmp_path):
    kv = str(tmp_path / "kv")
    ms = [ElasticManager(elastic_server=kv, job_id="j2", np="1:2",
                         host=f"h{i}") for i in range(3)]
    for m in ms:
        m.register()
    assert ms[0].watch() == ElasticStatus.RESTART  # 3 > max
    ms[2].deregister()
    assert ms[0].watch() == ElasticStatus.HOLD


def test_auto_checkpoint_resume(tmp_path):
    from paddle_tpu.incubate.checkpoint.auto_checkpoint import (
        train_epoch_range)
    ckdir = str(tmp_path / "auto")
    state = {"w": np.zeros(4, np.float32)}
    seen = []
    r = train_epoch_range(3, save_checkpoint_inter=0, checkpoint_dir=ckdir)
    r.add_state(lambda: dict(state), lambda s: state.update(s))
    for epoch in r:
        state["w"] = state["w"] + 1.0
        seen.append(epoch)
    assert seen == [0, 1, 2]

    # simulate a restart: fresh state restores from the last snapshot
    state2 = {"w": np.zeros(4, np.float32)}
    seen2 = []
    r2 = train_epoch_range(3, save_checkpoint_inter=0, checkpoint_dir=ckdir)
    r2.add_state(lambda: dict(state2), lambda s: state2.update(s))
    for epoch in r2:
        seen2.append(epoch)
    assert seen2 == []  # all epochs already done
    assert r2.restored_from == 2
    np.testing.assert_allclose(np.asarray(state2["w"]), 3.0)


def test_auto_checkpoint_partial_resume(tmp_path):
    from paddle_tpu.incubate.checkpoint.auto_checkpoint import (
        train_epoch_range)
    ckdir = str(tmp_path / "auto2")
    state = {"n": np.zeros((), np.int32)}
    r = train_epoch_range(5, save_checkpoint_inter=0, checkpoint_dir=ckdir)
    r.add_state(lambda: dict(state), lambda s: state.update(s))
    for epoch in r:
        state["n"] = state["n"] + 1
        if epoch == 2:
            break  # "preemption" after saving epoch 0..2? (save happens post-yield)
    # epochs 0,1 were saved post-yield; epoch 2 body ran but generator
    # stopped before its save → resume from epoch 2
    state2 = {"n": np.zeros((), np.int32)}
    seen = []
    r2 = train_epoch_range(5, save_checkpoint_inter=0, checkpoint_dir=ckdir)
    r2.add_state(lambda: dict(state2), lambda s: state2.update(s))
    for epoch in r2:
        seen.append(epoch)
        state2["n"] = state2["n"] + 1
    assert seen[0] >= 2 and seen[-1] == 4


def test_group_sharded_parallel_api():
    from paddle_tpu import nn
    from paddle_tpu.distributed.sharding import (
        get_group_sharded_stage, group_sharded_parallel)

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            return self.fc(x)

    m = M()
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    m2, opt2, scaler = group_sharded_parallel(m, opt, "os_g")
    assert get_group_sharded_stage(m2) == 2
    assert get_group_sharded_stage(opt2) == 2
    with pytest.raises(ValueError):
        group_sharded_parallel(m, opt, "bogus")
    with pytest.raises(NotImplementedError):
        group_sharded_parallel(m, opt, "os", offload=True)


def test_save_group_sharded_model(tmp_path):
    from paddle_tpu import nn
    from paddle_tpu.distributed.sharding import save_group_sharded_model

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            return self.fc(x)

    m = M()
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    out = str(tmp_path / "gss")
    save_group_sharded_model(m, out, opt)
    assert os.path.exists(out)
