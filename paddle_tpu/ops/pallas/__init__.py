from .flash_attention import flash_attention  # noqa: F401
