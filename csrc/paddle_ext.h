// Custom-op extension ABI for paddle_tpu
// (capability parity with the reference's out-of-tree custom-op API:
// paddle/fluid/extension/include/ext_op_meta_info.h PD_BUILD_OP and
// framework/custom_operator.cc — re-designed for the JAX runtime: the C++
// kernel runs on host through jax.pure_callback; gradients plug into
// jax.custom_vjp. NOT a port: registration is a plain inline registry, no
// OpMetaInfo/pybind machinery.)
//
// Convention (v1): float32 elementwise-style ops.
//   forward:  void fwd(const float** ins, int n_ins, float* out, int64_t n)
//             n = element count; out has the shape of ins[0].
//   backward: void bwd(const float** ins, int n_ins, const float* grad_out,
//                      float** grad_ins, int64_t n)   // may be nullptr
//
// A user .cc includes this header ONCE and registers ops:
//   PD_EXT_REGISTER(relu6, &relu6_fwd, &relu6_bwd, 1);
// Build + load from Python with paddle_tpu.utils.cpp_extension.load().

#ifndef PADDLE_TPU_CSRC_PADDLE_EXT_H_
#define PADDLE_TPU_CSRC_PADDLE_EXT_H_

#include <cstdint>
#include <vector>

typedef void (*pd_ext_fwd_fn)(const float**, int, float*, int64_t);
typedef void (*pd_ext_bwd_fn)(const float**, int, const float*, float**,
                              int64_t);

struct PdExtOp {
  const char* name;
  pd_ext_fwd_fn fwd;
  pd_ext_bwd_fn bwd;  // nullptr -> op is non-differentiable
  int n_inputs;
};

inline std::vector<PdExtOp>& pd_ext_registry() {
  static std::vector<PdExtOp> r;
  return r;
}

struct PdExtRegistrar {
  PdExtRegistrar(const char* name, pd_ext_fwd_fn fwd, pd_ext_bwd_fn bwd,
                 int n_inputs) {
    pd_ext_registry().push_back(PdExtOp{name, fwd, bwd, n_inputs});
  }
};

#define PD_EXT_REGISTER(opname, fwd, bwd, n_inputs) \
  static PdExtRegistrar pd_ext_reg_##opname(#opname, fwd, bwd, n_inputs)

// -- C ABI queried by ctypes (defined here; the user source is a single
// translation unit, so plain external definitions are safe) ----------------
extern "C" {

int pd_ext_num_ops() { return static_cast<int>(pd_ext_registry().size()); }

const char* pd_ext_op_name(int i) { return pd_ext_registry()[i].name; }

int pd_ext_op_n_inputs(int i) { return pd_ext_registry()[i].n_inputs; }

int pd_ext_op_has_grad(int i) {
  return pd_ext_registry()[i].bwd != nullptr ? 1 : 0;
}

void pd_ext_op_forward(int i, const float** ins, int n_ins, float* out,
                       int64_t n) {
  pd_ext_registry()[i].fwd(ins, n_ins, out, n);
}

void pd_ext_op_backward(int i, const float** ins, int n_ins,
                        const float* grad_out, float** grad_ins, int64_t n) {
  pd_ext_registry()[i].bwd(ins, n_ins, grad_out, grad_ins, n);
}

}  // extern "C"

#endif  // PADDLE_TPU_CSRC_PADDLE_EXT_H_
