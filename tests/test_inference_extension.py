"""Inference predictor + custom-op cpp_extension (reference:
paddle/fluid/inference/api/analysis_predictor.*, python/paddle/utils/
cpp_extension/)."""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


@pytest.fixture(scope="module")
def exported_model(tmp_path_factory):
    from paddle_tpu.static import InputSpec

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 3)

        def forward(self, x):
            return nn.functional.softmax(self.fc(x))

    paddle.seed(7)
    m = M()
    prefix = str(tmp_path_factory.mktemp("inf") / "model")
    paddle.jit.save(m, prefix, input_spec=[InputSpec([2, 4], "float32")])
    return m, prefix


def test_predictor_run_positional(exported_model):
    m, prefix = exported_model
    cfg = paddle.inference.Config(prefix)
    pred = paddle.inference.create_predictor(cfg)
    x = np.random.RandomState(0).rand(2, 4).astype("float32")
    outs = pred.run([x])
    np.testing.assert_allclose(outs[0], np.asarray(m(x)), rtol=1e-5)


def test_predictor_handle_api(exported_model):
    m, prefix = exported_model
    pred = paddle.inference.create_predictor(paddle.inference.Config(prefix))
    names = pred.get_input_names()
    assert names == ["x0"]
    x = np.random.RandomState(1).rand(2, 4).astype("float32")
    pred.get_input_handle("x0").copy_from_cpu(x)
    pred.run()
    out_name = pred.get_output_names()[0]
    out = pred.get_output_handle(out_name).copy_to_cpu()
    np.testing.assert_allclose(out, np.asarray(m(x)), rtol=1e-5)
    pred.clear_intermediate_tensor()


def test_config_accepts_reference_toggles(exported_model):
    import warnings
    _, prefix = exported_model
    cfg = paddle.inference.Config(prefix + ".stablehlo")
    cfg.disable_gpu()
    # inert toggles are accepted but must SAY they do nothing (once per
    # process per toggle, so ported reference configs aren't silently lied
    # to — VERDICT r3 item 8)
    paddle.inference.Config._warned_toggles.clear()
    with pytest.warns(UserWarning, match="no effect on TPU"):
        cfg.switch_ir_optim(True)
    with pytest.warns(UserWarning, match="no effect on TPU"):
        cfg.enable_memory_optim()
    with pytest.warns(UserWarning, match="no effect on TPU"):
        cfg.enable_mkldnn()
    with pytest.warns(UserWarning, match="no effect on TPU"):
        cfg.set_cpu_math_library_num_threads(4)
    with warnings.catch_warnings():  # second call: already warned
        warnings.simplefilter("error")
        cfg.enable_memory_optim()
    assert cfg.prog_file().endswith(".stablehlo")
    assert not cfg.use_gpu()
    pred = paddle.inference.create_predictor(cfg)
    assert pred.get_input_names()


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    d = tmp_path_factory.mktemp("ext")
    src = d / "myops.cc"
    src.write_text(textwrap.dedent("""
        #include "paddle_ext.h"
        #include <algorithm>
        #include <cmath>

        static void relu6_fwd(const float** ins, int, float* out, int64_t n) {
          for (int64_t i = 0; i < n; ++i)
            out[i] = std::min(std::max(ins[0][i], 0.0f), 6.0f);
        }
        static void relu6_bwd(const float** ins, int, const float* gout,
                              float** gins, int64_t n) {
          for (int64_t i = 0; i < n; ++i)
            gins[0][i] = (ins[0][i] > 0.0f && ins[0][i] < 6.0f)
                             ? gout[i] : 0.0f;
        }
        PD_EXT_REGISTER(relu6, &relu6_fwd, &relu6_bwd, 1);

        static void scaled_add_fwd(const float** ins, int, float* out,
                                   int64_t n) {
          for (int64_t i = 0; i < n; ++i)
            out[i] = ins[0][i] + 2.0f * ins[1][i];
        }
        PD_EXT_REGISTER(scaled_add, &scaled_add_fwd, nullptr, 2);
    """))
    from paddle_tpu.utils import cpp_extension
    return cpp_extension.load("myops_test", [str(src)],
                              build_directory=str(d / "build"))


def test_custom_op_forward(ext):
    x = np.array([-2.0, 1.0, 9.0], dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(ext.relu6(x)), [0.0, 1.0, 6.0])


def test_custom_op_grad(ext):
    import jax
    x = np.array([-2.0, 1.0, 9.0], dtype=np.float32)
    g = jax.grad(lambda v: ext.relu6(v).sum())(x)
    np.testing.assert_array_equal(np.asarray(g), [0.0, 1.0, 0.0])


def test_custom_op_under_jit(ext):
    import jax
    x = np.arange(8, dtype=np.float32) - 4
    np.testing.assert_allclose(np.asarray(jax.jit(ext.relu6)(x)),
                               np.clip(x, 0, 6))


def test_custom_op_two_inputs_no_grad(ext):
    x = np.ones(4, dtype=np.float32)
    y = np.full(4, 3.0, dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(ext.scaled_add(x, y)),
                                  np.full(4, 7.0, np.float32))
    assert "scaled_add" in ext.op_names()
    assert not ext._ops["scaled_add"].has_grad


def test_setup_builds_extension(tmp_path):
    src = tmp_path / "one.cc"
    src.write_text(
        '#include "paddle_ext.h"\n'
        "static void neg_fwd(const float** ins, int, float* out, int64_t n)"
        " { for (int64_t i = 0; i < n; ++i) out[i] = -ins[0][i]; }\n"
        "PD_EXT_REGISTER(neg, &neg_fwd, nullptr, 1);\n")
    from paddle_tpu.utils import cpp_extension
    mod = cpp_extension.setup(
        "one_test", cpp_extension.CppExtension([str(src)], name="one_test"))
    x = np.array([1.0, -2.0], dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(mod.neg(x)), [-1.0, 2.0])


def test_inference_type_tags_and_pool(tmp_path):
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn
    from paddle_tpu.jit import InputSpec

    assert inference.get_num_bytes_of_data_type(
        inference.DataType.FLOAT32) == 4
    assert inference.get_num_bytes_of_data_type(
        inference.DataType.INT64) == 8
    with pytest.raises(ValueError):
        inference.get_num_bytes_of_data_type("float128")
    assert "paddle_tpu" in inference.get_version()

    net = nn.Linear(4, 2)
    net.eval()
    path = str(tmp_path / "m" / "model")
    paddle.jit.save(net, path, input_spec=[InputSpec([1, 4])])
    cfg = inference.Config(path)
    pool = inference.PredictorPool(cfg, 2)
    assert len(pool) == 2
    x = np.ones((1, 4), "float32")
    outs = []
    for i in range(2):
        p = pool.retrieve(i)
        inp = p.get_input_handle(p.get_input_names()[0])
        inp.copy_from_cpu(x)
        p.run()
        outs.append(p.get_output_handle(
            p.get_output_names()[0]).copy_to_cpu())
    np.testing.assert_allclose(outs[0], outs[1])
    with pytest.raises(ValueError):
        inference.PredictorPool(cfg, 0)
