"""Version shims for the installed jax.

The codebase targets the current jax surface (``jax.shard_map`` with the
``check_vma`` kwarg); the pinned runtime here is jax 0.4.37, where shard_map
still lives in ``jax.experimental.shard_map`` and the replication checker
kwarg is named ``check_rep``. Installing the alias once at package import
keeps every call site (and the tests, which call ``jax.shard_map``
directly) on the one modern spelling.
"""
from __future__ import annotations

import jax


def _shard_map_compat(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, check_rep=None, **kw):
    from jax.experimental.shard_map import shard_map as _sm
    if check_rep is None:
        check_rep = True if check_vma is None else bool(check_vma)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_rep, **kw)


def _axis_size_compat(axis_name):
    # psum of a python scalar folds statically at trace time, so this is
    # the pre-0.5 spelling of lax.axis_size (tuple axis names included)
    from jax import lax
    return lax.psum(1, axis_name)


def install():
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_compat
    from jax import lax
    if not hasattr(lax, "axis_size"):
        lax.axis_size = _axis_size_compat
