"""paddle.save / paddle.load equivalents (reference:
python/paddle/framework/io.py:550 save, :766 load).

Format: a pickle of the object tree with jax/numpy arrays converted to
numpy (portable, no jax needed to read). For sharded/async checkpoints of
large distributed models use paddle_tpu.distributed.checkpoint (orbax-style);
this path covers the reference's single-file state_dict workflow.
"""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np


def _to_saveable(obj):
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if hasattr(obj, "value") and hasattr(obj, "trainable"):  # Parameter
        return np.asarray(obj.value)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj) if type(obj) in (list, tuple) else list
        return t(_to_saveable(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return pickle.load(f)
