"""Vision transforms on numpy CHW arrays (reference:
python/paddle/vision/transforms/transforms.py — host-side preprocessing, so
numpy, not jax: it runs in DataLoader workers)."""
from __future__ import annotations

import numbers
import random

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


def _chw(img):
    return img if img.ndim == 3 else img[None]


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        if img.ndim == 2:
            img = img[None]
        elif img.ndim == 3 and img.shape[-1] in (1, 3, 4) and self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        return img.astype(np.float32)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, dtype=np.float32)
        c = img.shape[0] if self.data_format == "CHW" else img.shape[-1]
        mean = self.mean[:c]
        std = self.std[:c]
        if self.data_format == "CHW":
            return (img - mean[:, None, None]) / std[:, None, None]
        return (img - mean) / std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        if chw:
            h_ax, w_ax = 1, 2
        else:
            h_ax, w_ax = 0, 1
        new_shape = list(img.shape)
        new_shape[h_ax], new_shape[w_ax] = self.size
        out = jax.image.resize(jnp.asarray(img), tuple(new_shape), method="linear")
        return np.asarray(out)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h, w = (img.shape[1], img.shape[2]) if chw else (img.shape[0], img.shape[1])
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        if chw:
            return img[:, i:i + th, j:j + tw]
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else [self.padding] * 4
            pad_cfg = [(0, 0), (p[1], p[3]), (p[0], p[2])] if chw \
                else [(p[1], p[3]), (p[0], p[2])] + ([(0, 0)] if img.ndim == 3 else [])
            img = np.pad(img, pad_cfg)
        h, w = (img.shape[1], img.shape[2]) if chw else (img.shape[0], img.shape[1])
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        if chw:
            return img[:, i:i + th, j:j + tw]
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
            return img[:, :, ::-1].copy() if chw else img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
            return img[:, ::-1, :].copy() if chw else img[::-1].copy()
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(img, self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        factor = 1.0 + random.uniform(-self.value, self.value)
        return np.clip(img * factor, 0, 1.0 if img.dtype != np.uint8 else 255)


class ContrastTransform(BaseTransform):
    """Random contrast jitter in [max(0,1-value), 1+value] (reference
    transforms.py ContrastTransform)."""

    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        from . import functional as Fv
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return Fv.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError("saturation value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        from . import functional as Fv
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return Fv.adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        from . import functional as Fv
        if self.value == 0:
            return img
        return Fv.adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Randomly jitter brightness/contrast/saturation/hue in random order
    (reference transforms.py ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        order = list(self.transforms)
        random.shuffle(order)
        for t in order:
            img = t(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        from . import functional as Fv
        return Fv.to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        from . import functional as Fv
        return Fv.pad(img, self.padding, self.fill, self.padding_mode)


class RandomResizedCrop(BaseTransform):
    """Crop a random area/aspect patch then resize (reference transforms.py
    RandomResizedCrop: scale=(0.08,1), ratio=(3/4,4/3), 10 attempts then
    center fallback)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _get_param(self, h, w):
        import math
        area = h * w
        for _ in range(10):
            target_area = area * random.uniform(*self.scale)
            log_ratio = (math.log(self.ratio[0]), math.log(self.ratio[1]))
            aspect = math.exp(random.uniform(*log_ratio))
            tw = int(round(math.sqrt(target_area * aspect)))
            th = int(round(math.sqrt(target_area / aspect)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                return i, j, th, tw
        # fallback: center crop at clamped aspect
        in_ratio = w / h
        if in_ratio < self.ratio[0]:
            tw, th = w, int(round(w / self.ratio[0]))
        elif in_ratio > self.ratio[1]:
            th, tw = h, int(round(h * self.ratio[1]))
        else:
            tw, th = w, h
        return (h - th) // 2, (w - tw) // 2, th, tw

    def _apply_image(self, img):
        from . import functional as Fv
        arr = img if not hasattr(img, "size") or isinstance(img, np.ndarray) \
            else None
        if arr is None:  # PIL
            w, h = img.size
        else:
            h, w = np.asarray(img).shape[:2]
        i, j, th, tw = self._get_param(h, w)
        out = Fv.crop(img, i, j, th, tw)
        return Fv.resize(out, self.size, self.interpolation)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            self.degrees = (-degrees, degrees)
        else:
            self.degrees = tuple(degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        from . import functional as Fv
        angle = random.uniform(*self.degrees)
        return Fv.rotate(img, angle, self.interpolation, self.expand,
                         self.center, self.fill)
