"""paddle_tpu.optimizer (reference: python/paddle/optimizer/__init__.py)."""
from . import lr  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .optimizer import (  # noqa: F401
    SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, DGCMomentum, Lamb, Lars,
    LarsMomentum, Momentum, Optimizer, RMSProp)


class L2Decay:
    """Weight-decay spec (reference: python/paddle/regularizer.py L2Decay)."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
