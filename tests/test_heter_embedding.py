"""HeterEmbedding (device-resident hot tier over host PS) + the
alltoall sharded-lookup op.

Reference capability: framework/fleet/heter_ps/hashtable.h:47,
heter_comm.h:50 (GPU-resident embedding tier with device optimizer and
inter-device row exchange; CPU PS as the full store). The equality
tests pin the contract: the two-tier path must produce the SAME
training trajectory as the host-only PS path.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.engine import ParallelTrainer
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.ps import (DistributedEmbedding,
                                       HeterEmbedding, SparseTable)


class TestTierExchange:
    def test_export_import_roundtrip_with_slots(self):
        t = SparseTable(4, optimizer="adagrad", init_range=0.0)
        keys = np.asarray([3, 99, 12345], np.int64)
        t.push(keys, np.ones((3, 4), np.float32), lr=0.1)
        rows = t.export_rows(keys)
        assert rows.shape == (3, t.row_width) and t.row_width == 8
        # adagrad slot column = accumulated g^2 = 1.0 after one push
        np.testing.assert_allclose(rows[:, 4:], 1.0)
        rows2 = rows.copy()
        rows2[:, :4] = 7.0
        t.import_rows(keys, rows2)
        np.testing.assert_allclose(t.pull(keys), 7.0)
        # slot column preserved
        np.testing.assert_allclose(t.export_rows(keys)[:, 4:], 1.0)

    def test_export_missing_zero_or_init(self):
        t = SparseTable(4, optimizer="sgd", init_range=0.0)
        rows = t.export_rows(np.asarray([5], np.int64),
                             create_missing=False)
        np.testing.assert_allclose(rows, 0.0)
        assert len(t) == 0


def _make_pooled_model(emb):
    """score = sum over pooled embedding -> scalar-per-row logit."""
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = emb
            self.head = nn.Linear(emb.dim, 1)

        def forward(self, ids):
            return self.head(self.emb(ids))[..., 0, 0]

    return M()


def _mse(o, y):
    return jnp.mean(jnp.square(o - y))


def _run_host_path(ids_seq, y_seq, dim, lr, optimizer):
    build_mesh({"data": 1})
    paddle.seed(0)
    emb = DistributedEmbedding(dim, optimizer=optimizer, lr=lr,
                               init_range=0.0)
    m = _make_pooled_model(emb)
    opt = (paddle.optimizer.SGD(lr, parameters=m.parameters())
           if optimizer == "sgd" else
           paddle.optimizer.Adagrad(lr, epsilon=1e-8,
                                    parameters=m.parameters()))
    tr = ParallelTrainer(m, opt, _mse)
    return [float(tr.train_step(i, y)) for i, y in zip(ids_seq, y_seq)]


def _run_heter_path(ids_seq, y_seq, dim, lr, optimizer, capacity,
                    shard_axis=None, mesh_degrees=None):
    build_mesh(mesh_degrees or {"data": 1})
    paddle.seed(0)
    emb = HeterEmbedding(dim, capacity=capacity, optimizer=optimizer,
                         init_range=0.0, shard_axis=shard_axis)
    m = _make_pooled_model(emb)
    opt = (paddle.optimizer.SGD(lr, parameters=m.parameters())
           if optimizer == "sgd" else
           paddle.optimizer.Adagrad(lr, epsilon=1e-8,
                                    parameters=m.parameters()))
    tr = ParallelTrainer(m, opt, _mse)  # auto-binds the hot tier
    losses = []
    for ids, y in zip(ids_seq, y_seq):
        slots = emb.prepare(ids)
        losses.append(float(tr.train_step(slots, y)))
    return losses, emb


def _batches(n_steps, batch, width, vocab, seed=0, distinct=True):
    rs = np.random.RandomState(seed)
    ids_seq, y_seq = [], []
    for _ in range(n_steps):
        if distinct:
            ids = rs.choice(vocab, size=(batch * width,), replace=False)
        else:
            ids = rs.randint(0, vocab, size=(batch * width,))
        ids_seq.append(ids.reshape(batch, width).astype(np.int64))
        y_seq.append(rs.randn(batch).astype(np.float32))
    return ids_seq, y_seq


class TestHeterVsHost:
    """The device-tier trajectory must equal the host-PS trajectory."""

    def test_sgd_no_eviction(self):
        ids_seq, y_seq = _batches(6, 4, 3, vocab=40)
        host = _run_host_path(ids_seq, y_seq, 8, 0.1, "sgd")
        het, emb = _run_heter_path(ids_seq, y_seq, 8, 0.1, "sgd",
                                   capacity=64)
        np.testing.assert_allclose(host, het, rtol=2e-4)
        assert emb.stats["evicts"] == 0

    def test_sgd_with_evictions(self):
        # capacity 16 but 12 distinct keys per batch from a 60-key space
        # -> heavy churn; SGD handoff is exact (no slot columns)
        ids_seq, y_seq = _batches(8, 4, 3, vocab=60, seed=3)
        host = _run_host_path(ids_seq, y_seq, 8, 0.1, "sgd")
        het, emb = _run_heter_path(ids_seq, y_seq, 8, 0.1, "sgd",
                                   capacity=16)
        np.testing.assert_allclose(host, het, rtol=2e-4)
        assert emb.stats["evicts"] > 0

    def test_adagrad_slot_handoff_across_eviction(self):
        # repeated keys across batches + eviction churn: the adagrad
        # accumulator must migrate device->PS->device intact
        ids_seq, y_seq = _batches(10, 4, 3, vocab=40, seed=5)
        host = _run_host_path(ids_seq, y_seq, 8, 0.1, "adagrad")
        het, emb = _run_heter_path(ids_seq, y_seq, 8, 0.1, "adagrad",
                                   capacity=16)
        np.testing.assert_allclose(host, het, rtol=5e-4)
        assert emb.stats["evicts"] > 0

    def test_padding_ids(self):
        build_mesh({"data": 1})
        paddle.seed(0)
        emb = HeterEmbedding(4, capacity=8, optimizer="sgd",
                             init_range=0.01)
        slots = emb.prepare(np.asarray([[1, -1], [2, 1]], np.int64))
        assert slots[0, 1] == -1
        out = emb(jnp.asarray(slots))
        np.testing.assert_allclose(np.asarray(out[0, 1]), 0.0)

    def test_save_load_roundtrip(self, tmp_path):
        build_mesh({"data": 1})
        paddle.seed(0)
        emb = HeterEmbedding(4, capacity=8, optimizer="sgd",
                             init_range=0.05)
        slots = emb.prepare(np.asarray([3, 7, 11], np.int64))
        vals = np.asarray(emb(jnp.asarray(slots)))
        p = str(tmp_path / "heter_table")
        emb.save(p)
        emb2 = HeterEmbedding(4, capacity=8, optimizer="sgd",
                              init_range=0.05)
        emb2.load(p)
        slots2 = emb2.prepare(np.asarray([3, 7, 11], np.int64))
        np.testing.assert_allclose(
            np.asarray(emb2(jnp.asarray(slots2))), vals, rtol=1e-6)


class TestShardedHotTier:
    def test_model_sharded_matches_replicated(self):
        ids_seq, y_seq = _batches(5, 4, 3, vocab=30, seed=7)
        ref, _ = _run_heter_path(ids_seq, y_seq, 8, 0.1, "sgd",
                                 capacity=64)
        shd, emb = _run_heter_path(ids_seq, y_seq, 8, 0.1, "sgd",
                                   capacity=64, shard_axis="model",
                                   mesh_degrees={"data": 2, "model": 4})
        np.testing.assert_allclose(ref, shd, rtol=2e-4)


class TestPadPow2:
    def test_sizes_and_idempotent_roundtrip(self):
        """Exchange batches pad to the next power of two by repeating
        the last (slot, key) pair — sizes exact, and a flush/promote
        round-trip with a non-pow2 batch equals the unpadded rows (the
        duplicate-write idempotency the padding relies on)."""
        from paddle_tpu.distributed.ps.heter import HeterEmbedding
        for n, want in ((1, 1), (2, 2), (3, 4), (5, 8), (8, 8), (9, 16)):
            s, k = HeterEmbedding._pad_pow2(
                np.arange(n, dtype=np.int64), np.arange(n, dtype=np.int64))
            assert s.shape == (want,) == k.shape, (n, s.shape)
            np.testing.assert_array_equal(s[:n], np.arange(n))
            np.testing.assert_array_equal(s[n:], np.full(want - n, n - 1))
        build_mesh({"data": 1})
        paddle.seed(0)
        emb = HeterEmbedding(4, capacity=8, optimizer="sgd",
                             init_range=0.05)
        keys = np.asarray([3, 7, 11], np.int64)      # non-pow2 batch
        slots = emb.prepare(keys)                    # promote (padded)
        vals = np.asarray(emb(jnp.asarray(slots)))
        emb.flush_all()                              # flush (padded)
        rows = emb.table.pull(keys)
        np.testing.assert_allclose(rows, vals, rtol=1e-6)


class TestShardCapacityValidation:
    def test_indivisible_capacity_raises_with_named_numbers(self):
        """An indivisible hot-tier capacity must fail with the numbers
        named, not as an opaque GSPMD sharding error later (ADVICE r4)."""
        from paddle_tpu.distributed.ps.heter import HeterEmbedding
        mesh = build_mesh({"data": 2, "model": 4})
        with mesh:
            with pytest.raises(ValueError, match=r"66.*divisible.*'model'"):
                HeterEmbedding(8, capacity=66, shard_axis="model")
            HeterEmbedding(8, capacity=64, shard_axis="model")  # ok


class TestWideDeepHeter:
    def test_e2e_trains_and_matches_host_path(self):
        from paddle_tpu.rec import WideDeep
        fields = [50] * 4
        rs = np.random.RandomState(0)
        batches = [(rs.randint(0, 50, (8, 4)).astype(np.int64),
                    rs.randn(8, 5).astype(np.float32),
                    rs.randint(0, 2, 8).astype(np.float32))
                   for _ in range(6)]

        def bce(logit, y):
            return jnp.mean(jnp.maximum(logit, 0) - logit * y
                            + jnp.log1p(jnp.exp(-jnp.abs(logit))))

        def run(mode):
            build_mesh({"data": 1})
            paddle.seed(0)
            m = WideDeep(fields, dense_dim=5, embedding_dim=4,
                         hidden_sizes=(16,), sparse=mode,
                         heter_capacity=64)  # < 200 keys -> evictions
            opt = paddle.optimizer.Adagrad(
                0.05, epsilon=1e-8, parameters=m.parameters())
            tr = ParallelTrainer(m, opt, bce)
            losses = []
            for ids, dense, y in batches:
                if mode == "heter":
                    ids = m.prepare_batch(ids)
                losses.append(float(tr.train_step((ids, dense), y)))
            return losses, m

        def run_overlapped():
            build_mesh({"data": 1})
            paddle.seed(0)
            m = WideDeep(fields, dense_dim=5, embedding_dim=4,
                         hidden_sizes=(16,), sparse="heter",
                         heter_capacity=64)
            opt = paddle.optimizer.Adagrad(
                0.05, epsilon=1e-8, parameters=m.parameters())
            tr = ParallelTrainer(m, opt, bce)
            # double-buffered: prepare(k+1) on the tier's worker thread
            # while the device executes step k; submit AFTER dispatch
            # (donated buffers) — same pattern as the bench tool
            losses = []
            fut = m.prepare_batch_async(batches[0][0])
            for i, (ids, dense, y) in enumerate(batches):
                slots = fut.result()
                losses.append(float(tr.train_step((slots, dense), y)))
                if i + 1 < len(batches):
                    fut = m.prepare_batch_async(batches[i + 1][0])
            return losses, m

        host, _ = run(True)
        het, m = run("heter")
        assert het[-1] < het[0]          # it trains
        # same math through both tiers. The paths are not bit-identical:
        # the host tier applies per-OCCURRENCE adagrad updates while the
        # device tier scatter-adds duplicate ids then updates once (the
        # random batches repeat ids within a field), so compare loosely.
        np.testing.assert_allclose(host, het, rtol=0.15)
        assert m.ctr_table.stats["evicts"] > 0
        assert m.ctr_table.stats["prepare_s"] > 0.0  # latency tracked
        # overlapped (prepare_async) trajectory is EXACTLY the serial
        # heter trajectory — overlap changes timing, never the math
        ovl, m2 = run_overlapped()
        np.testing.assert_allclose(het, ovl, rtol=1e-6)
        assert m2.ctr_table.stats["evicts"] == m.ctr_table.stats["evicts"]


class TestAlltoallLookup:
    """ops/sharded_embedding.alltoall_lookup: batch-sharded ids over a
    row-sharded table (the heter_comm.h id-exchange pattern)."""

    def _setup(self):
        from paddle_tpu.ops.sharded_embedding import alltoall_lookup
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
        R, dim, n_local = 64, 5, 16
        rows = jnp.asarray(
            np.random.RandomState(0).randn(R, dim), jnp.float32)
        ids = np.random.RandomState(1).randint(
            -1, R, (n_local * 8,)).astype(np.int32)
        return alltoall_lookup, mesh, R, dim, rows, ids

    @pytest.mark.parametrize("cap", [1, 3, 16])
    def test_forward_matches_dense(self, cap):
        op, mesh, R, dim, rows, ids = self._setup()

        def f(local_rows, ids_l):
            return op(local_rows, ids_l, "data", cap, R // 8)

        out = jax.jit(shard_map(
            f, mesh=mesh, in_specs=(P("data", None), P("data")),
            out_specs=P("data"), check_vma=False))(rows, jnp.asarray(ids))
        ref = np.where((ids >= 0)[:, None],
                       np.asarray(rows)[np.clip(ids, 0, R - 1)], 0.0)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)

    @pytest.mark.parametrize("cap", [pytest.param(1, marks=pytest.mark.slow),
                                     pytest.param(3, marks=pytest.mark.slow),
                                     16])
    def test_grads_match_dense(self, cap):
        op, mesh, R, dim, rows, ids = self._setup()
        N = ids.shape[0]
        coef = (1.0 + jnp.arange(N, dtype=jnp.float32))[:, None]

        def loss_sharded(rows, ids, coef):
            def inner(local_rows, ids_l, coef_l):
                o = op(local_rows, ids_l, "data", cap, R // 8)
                return jax.lax.psum(jnp.sum(o * coef_l), "data")
            return shard_map(
                inner, mesh=mesh,
                in_specs=(P("data", None), P("data"), P("data", None)),
                out_specs=P(), check_vma=False)(rows, ids, coef)

        def loss_dense(rows, ids, coef):
            o = jnp.where((ids >= 0)[:, None],
                          rows[jnp.clip(ids, 0, R - 1)], 0.0)
            return jnp.sum(o * coef)

        g1 = jax.grad(loss_sharded)(rows, jnp.asarray(ids), coef)
        g2 = jax.grad(loss_dense)(rows, jnp.asarray(ids), coef)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-5)
