"""DGC momentum + ASP n:m sparsity (reference:
fluid DGCMomentumOptimizer / dgc_momentum_op.cc and
fluid/contrib/sparsity/asp.py)."""
import numpy as np

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.engine import ParallelTrainer
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.incubate import asp


def _data(rows=16):
    rng = np.random.RandomState(0)
    return (rng.randn(rows, 8).astype("f4"),
            rng.randn(rows, 4).astype("f4"))


def _mse(o, y):
    return jnp.mean((o - y) ** 2)


class TestDGCMomentum:
    def test_local_accumulation_conserves_gradient_mass(self):
        """Unsent coordinates stay in the residual: across enough steps of
        a CONSTANT gradient, total applied update approaches what dense
        momentum would apply (nothing is lost, only delayed)."""
        opt = paddle.optimizer.DGCMomentum(
            1.0, momentum=0.0, rampup_begin_step=0, sparsity=(0.75,))
        p = {"w": jnp.zeros((16,))}
        g = {"w": jnp.asarray(np.linspace(0.1, 1.6, 16), jnp.float32)}
        state = opt.init_state(p)
        steps = 40
        for _ in range(steps):
            p, state = opt.apply_gradients(p, dict(g), state, lr=1.0)
        # dense momentum(0) with lr 1 for 40 steps would apply -40*g per
        # coordinate; with local accumulation every coordinate (even the
        # smallest, which must accumulate ~13 steps to cross the top-k
        # threshold) receives most of its mass — delayed, never lost
        applied = -np.asarray(p["w"]) / np.asarray(g["w"])
        assert applied.min() > steps * 0.5, applied
        # residual bounded by ~the selection threshold (max |g| scale)
        resid = np.abs(np.asarray(state["slots"]["w"]["v"]))
        assert resid.max() <= 2.0 * float(np.max(np.asarray(g["w"])))

    def test_sparsification_sends_topk_only(self):
        opt = paddle.optimizer.DGCMomentum(
            1.0, momentum=0.0, rampup_begin_step=0, sparsity=(0.75,))
        p = {"w": jnp.zeros((16,))}
        g = {"w": jnp.asarray(np.linspace(0.1, 1.6, 16), jnp.float32)}
        state = opt.init_state(p)
        p, state = opt.apply_gradients(p, g, state, lr=1.0)
        moved = np.asarray(p["w"]) != 0.0
        assert moved.sum() <= 5            # ~25% of 16 coordinates
        assert moved[-1] and not moved[0]  # largest sent, smallest held

    def test_rampup_dense_before_begin_step(self):
        opt = paddle.optimizer.DGCMomentum(
            1.0, momentum=0.0, rampup_begin_step=3, sparsity=(0.9,))
        p = {"w": jnp.zeros((8,))}
        g = {"w": jnp.ones((8,), jnp.float32)}
        state = opt.init_state(p)
        p, state = opt.apply_gradients(p, g, state, lr=1.0)
        assert (np.asarray(p["w"]) != 0).all()  # step 1 <= begin: dense

    def test_trains_a_model(self):
        build_mesh({"data": 1})
        paddle.seed(0)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.DGCMomentum(
            0.05, momentum=0.9, rampup_begin_step=2, sparsity=(0.5,),
            parameters=net.parameters())
        tr = ParallelTrainer(net, opt, _mse)
        x, y = _data()
        losses = [float(tr.train_step(x, y)) for _ in range(15)]
        assert losses[-1] < losses[0]


class TestASP:
    def test_mask_is_2_of_4(self):
        w = np.random.RandomState(0).randn(8, 16).astype("f4")
        mask = np.asarray(asp.compute_nm_mask(w))
        groups = mask.reshape(-1, 4)
        assert (groups.sum(axis=-1) == 2).all()
        # kept entries are the two largest magnitudes per group
        wg = np.abs(w.reshape(-1, 4))
        for row_m, row_w in zip(groups, wg):
            kept = row_w[row_m == 1]
            dropped = row_w[row_m == 0]
            assert kept.min() >= dropped.max() - 1e-7

    def test_prune_and_check(self):
        paddle.seed(0)
        net = nn.Linear(8, 16)
        asp.prune_model(net)
        assert asp.check_sparsity(net.weight.value)

    def test_sparsity_maintained_through_training(self):
        build_mesh({"data": 1})
        paddle.seed(1)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
        asp.prune_model(net)
        opt = asp.decorate(
            paddle.optimizer.Momentum(0.05, parameters=net.parameters()),
            net)
        tr = ParallelTrainer(net, opt, _mse)
        x, y = _data()
        losses = [float(tr.train_step(x, y)) for _ in range(10)]
        assert losses[-1] < losses[0]
        w = np.asarray(tr.state["params"]["0.weight"])
        assert asp.check_sparsity(w)       # still 2:4 after real training
        w2 = np.asarray(tr.state["params"]["2.weight"])
        assert asp.check_sparsity(w2)

    def test_decorate_before_prune_order(self):
        """Reference call order (decorate THEN prune_model) must also
        keep masks applied (masks are looked up at step time)."""
        build_mesh({"data": 1})
        paddle.seed(2)
        net = nn.Linear(8, 16)
        opt = asp.decorate(
            paddle.optimizer.SGD(0.1, parameters=net.parameters()), net)
        asp.prune_model(net)
        tr = ParallelTrainer(net, opt, _mse)
        rng = np.random.RandomState(0)
        x = rng.randn(8, 8).astype("f4")
        y = rng.randn(8, 16).astype("f4")
        for _ in range(3):
            tr.train_step(x, y)
        assert asp.check_sparsity(np.asarray(tr.state["params"]["weight"]))

    def test_prune_mid_training_inside_compiled_step(self):
        """Pruning AFTER the first (already traced+compiled) step must
        still take effect: decorate derives masks from runtime weight
        values, not from Python state baked in at trace time."""
        build_mesh({"data": 1})
        paddle.seed(4)
        net = nn.Linear(8, 16)
        opt = asp.decorate(
            paddle.optimizer.SGD(0.1, parameters=net.parameters()), net)
        tr = ParallelTrainer(net, opt, _mse)
        rng = np.random.RandomState(1)
        x = rng.randn(8, 8).astype("f4")
        y = rng.randn(8, 16).astype("f4")
        for _ in range(2):
            tr.train_step(x, y)        # traces with DENSE weights
        assert not asp.check_sparsity(
            np.asarray(tr.state["params"]["weight"]))
        tr.state["params"], masks = asp.prune_params(tr.state["params"])
        assert "weight" in masks
        for _ in range(3):
            tr.train_step(x, y)        # same compiled fn, masks now bind
        w = np.asarray(tr.state["params"]["weight"])
        assert asp.check_sparsity(w)
        # and it actually trained (non-masked entries moved)
        assert np.abs(w).sum() > 0

    def test_custom_group_size(self):
        paddle.seed(3)
        net = nn.Linear(8, 6)      # last dim 6: prunable only for m=2
        masks = asp.prune_model(net, n=1, m=2)
        assert "weight" in masks
        assert asp.check_sparsity(net.weight.value, n=1, m=2)
