"""IMDB sentiment dataset (reference: python/paddle/text/datasets/imdb.py:33
— aclImdb tarball, word-frequency dict with cutoff, pos label 0 / neg 1).
"""
from __future__ import annotations

import collections
import re
import string
import tarfile

import numpy as np

from ...io.dataset import Dataset
from ...utils.download import DATA_HOME, get_path_from_url

URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"

_PUNCT = str.maketrans("", "", string.punctuation)


class Imdb(Dataset):
    """Samples: (np.array(word_ids), np.array([label])) with label 0=pos,
    1=neg (matches reference imdb.py:139)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        if data_file is None:
            assert download, "data_file not set and download disabled"
            data_file = get_path_from_url(URL, DATA_HOME + "/imdb",
                                          decompress=False)
        self.data_file = data_file
        self.word_idx = self._build_word_dict(cutoff)
        self._load(self.word_idx)

    def _tokenize(self, pattern):
        docs = []
        with tarfile.open(self.data_file) as tf:
            for member in tf:
                if pattern.match(member.name):
                    text = tf.extractfile(member).read().decode(
                        "utf-8", "ignore")
                    docs.append(
                        text.rstrip("\n\r").translate(_PUNCT).lower().split())
        return docs

    def _build_word_dict(self, cutoff):
        pattern = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        freq = collections.Counter()
        for doc in self._tokenize(pattern):
            freq.update(doc)
        freq.pop("<unk>", None)
        kept = [(w, c) for w, c in freq.items() if c > cutoff]
        kept.sort(key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load(self, word_idx):
        unk = word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, sub in ((0, "pos"), (1, "neg")):
            pattern = re.compile(
                rf"aclImdb/{self.mode}/{sub}/.*\.txt$")
            for doc in self._tokenize(pattern):
                self.docs.append([word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)
