"""Sparse-feature admission policies for the PS embedding tier.

Reference: python/paddle/distributed/entry_attr.py (ProbabilityEntry:59,
CountFilterEntry:100) — with a parameter server, new feature ids are not
admitted into the sparse table unconditionally: ProbabilityEntry admits a
new id with probability p; CountFilterEntry admits an id only once it has
been seen >= n times. Non-admitted ids read as zero rows and their
gradients are dropped (the reference's common_sparse_table entry filter).

TPU division of labor: admission is a HOST-side concern (the table lives
host-side; the device only sees dense pulled rows), so the filter wraps the
table's pull/push — the jitted compute is unchanged.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

__all__ = ["EntryAttr", "ProbabilityEntry", "CountFilterEntry"]


class EntryAttr:
    """Base admission policy (reference entry_attr.py EntryAttr)."""

    _name = "entry_attr"

    def _to_attr(self) -> str:
        raise NotImplementedError("EntryAttr is base class")

    # -- filter protocol used by _AdmissionTable -------------------------
    def accumulate_and_admit(self, keys: np.ndarray) -> np.ndarray:
        """Observe one occurrence of each element of ``keys`` (duplicates
        count) and return a bool mask: True = admitted (row may be
        created)."""
        raise NotImplementedError

    def is_admitted(self, keys: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class ProbabilityEntry(EntryAttr):
    """Admit a new id with fixed probability (reference entry_attr.py:59).
    Deterministic per key (stable 64-bit hash vs threshold), so every
    trainer makes the same admission decision without coordination."""

    def __init__(self, probability: float):
        if not isinstance(probability, float) or not 0 < probability <= 1:
            raise ValueError(
                "probability must be a float in (0, 1], got "
                f"{probability!r}")
        self._name = "probability_entry"
        self._probability = probability

    def _to_attr(self) -> str:
        return f"{self._name}:{self._probability}"

    def _hash01(self, keys: np.ndarray) -> np.ndarray:
        h = keys.astype(np.uint64, copy=True)
        h ^= h >> np.uint64(33)
        h *= np.uint64(0xFF51AFD7ED558CCD)
        h ^= h >> np.uint64(33)
        return (h % np.uint64(1 << 24)).astype(np.float64) / float(1 << 24)

    def accumulate_and_admit(self, keys: np.ndarray) -> np.ndarray:
        return self._hash01(np.asarray(keys, np.int64)) < self._probability

    is_admitted = accumulate_and_admit


class CountFilterEntry(EntryAttr):
    """Admit an id once it has been seen >= ``count_filter`` times
    (reference entry_attr.py:100). Counts are per-process (each trainer
    sees its own shard of the stream, like the reference's per-shard
    counters)."""

    def __init__(self, count_filter: int):
        if not isinstance(count_filter, int) or count_filter < 0:
            raise ValueError(
                "count_filter must be a non-negative integer, got "
                f"{count_filter!r}")
        self._name = "count_filter_entry"
        self._count = count_filter
        self._seen: Dict[int, int] = {}

    def _to_attr(self) -> str:
        return f"{self._name}:{self._count}"

    def accumulate_and_admit(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.int64)
        uniq, counts = np.unique(keys, return_counts=True)
        for k, c in zip(uniq.tolist(), counts.tolist()):
            self._seen[k] = self._seen.get(k, 0) + c
        return self.is_admitted(keys)

    def is_admitted(self, keys: np.ndarray) -> np.ndarray:
        n = self._count
        return np.fromiter((self._seen.get(int(k), 0) >= n
                            for k in np.asarray(keys).ravel()),
                           dtype=bool,
                           count=int(np.asarray(keys).size))


class _AdmissionTable:
    """pull/push view of a sparse table with an EntryAttr gate: rows are
    only created for admitted keys; pushes for non-admitted keys are
    dropped; existing rows (e.g. from load()) always read."""

    def __init__(self, table, entry: EntryAttr):
        self._table = table
        self.entry = entry

    @property
    def dim(self):
        return self._table.dim

    def pull(self, keys, create_missing: bool = True) -> np.ndarray:
        keys = np.asarray(keys, np.int64)
        shape = keys.shape
        flat = keys.reshape(-1)
        if not create_missing:
            return self._table.pull(keys, create_missing=False)
        admitted = self.entry.accumulate_and_admit(flat)
        out = np.zeros((flat.size, self.dim), np.float32)
        if admitted.any():
            out[admitted] = self._table.pull(flat[admitted],
                                             create_missing=True) \
                .reshape(-1, self.dim)
        rest = ~admitted
        if rest.any():  # not admitted, but may pre-exist via load()
            out[rest] = self._table.pull(flat[rest],
                                         create_missing=False) \
                .reshape(-1, self.dim)
        return out.reshape(shape + (self.dim,))

    def push(self, keys, grads, lr: float):
        keys = np.asarray(keys, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(keys.size, -1)
        m = self.entry.is_admitted(keys)
        if m.any():
            self._table.push(keys[m], grads[m], lr)

    def __len__(self):
        return len(self._table)

    def __getattr__(self, name):  # save/load/flush/... delegate
        return getattr(self._table, name)
