"""paddle.distributed.spawn equivalent (reference: distributed/spawn.py).

On TPU, one process drives all local chips (single-controller JAX), so
spawn-per-device is unnecessary; this spawns one process per *host group*
for multi-process simulation/testing (the SURVEY.md §4 TestDistBase pattern),
setting PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM env vars.
"""
from __future__ import annotations

import multiprocessing as mp
import os


def _worker(func, rank, nprocs, args, env):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    func(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    ctx = mp.get_context("spawn")
    procs = []
    env = {k: v for k, v in os.environ.items()}
    for rank in range(nprocs):
        p = ctx.Process(target=_worker, args=(func, rank, nprocs, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(f"spawned process failed with {p.exitcode}")
    return procs
