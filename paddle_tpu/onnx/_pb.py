"""Minimal protobuf wire-format encoder/decoder for ONNX serialization.

The reference delegates ONNX export to the external paddle2onnx package
(python/paddle/onnx/export.py). This image ships neither `onnx` nor a
converter stack, so emission is done directly at the protobuf wire level —
the format is stable and simple (varint tags + length-delimited
submessages). Field numbers below follow onnx/onnx.proto (IR version 8,
default opset). The decoder exists so round-trip tests can validate the
emitted bytes without the onnx package.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple, Union

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def varint(n: int) -> bytes:
    """Unsigned LEB128. int64 fields with negative values take the 10-byte
    two's-complement form."""
    if n < 0:
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field: int, wire: int) -> bytes:
    return varint((field << 3) | wire)


def f_varint(field: int, n: int) -> bytes:
    return tag(field, _VARINT) + varint(n)


def f_bytes(field: int, data: bytes) -> bytes:
    return tag(field, _LEN) + varint(len(data)) + data


def f_str(field: int, s: str) -> bytes:
    return f_bytes(field, s.encode("utf-8"))


def f_packed_i64(field: int, vals) -> bytes:
    body = b"".join(varint(int(v)) for v in vals)
    return f_bytes(field, body)


def f_packed_f32(field: int, vals) -> bytes:
    return f_bytes(field, struct.pack(f"<{len(vals)}f", *vals))


# -- decoder (for tests) ------------------------------------------------------

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def decode(buf: bytes) -> Dict[int, List[Union[int, bytes]]]:
    """Decode one message level: field number -> list of raw values
    (ints for varint fields, bytes for length-delimited). Nested messages
    are decoded by calling decode() again on the bytes value."""
    out: Dict[int, List[Union[int, bytes]]] = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == _VARINT:
            val, pos = _read_varint(buf, pos)
        elif wire == _LEN:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == _I64:
            val = struct.unpack_from("<q", buf, pos)[0]
            pos += 8
        elif wire == _I32:
            val = struct.unpack_from("<i", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"bad wire type {wire}")
        out.setdefault(field, []).append(val)
    return out
