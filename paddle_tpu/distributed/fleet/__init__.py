"""Fleet API (reference: python/paddle/distributed/fleet/base/fleet_base.py:72
Fleet, :139 init, :836 distributed_model; DistributedStrategy
fleet/base/distributed_strategy.py:105).

TPU-native: ``init`` builds the hybrid mesh (data/pipe/sharding/sep/model)
from DistributedStrategy.hybrid_configs; ``distributed_model`` wraps the
network per the active degrees (DataParallel / TensorParallel /
PipelineParallel / ShardingParallel); ``distributed_optimizer`` attaches
mesh-wide grad clip + DP grad averaging. The 18 static meta-optimizers of
the reference collapse into sharding annotations + jit options here (AMP →
amp.auto_cast, Recompute → jax.checkpoint, GradientMerge → accumulate steps,
DGC/LocalSGD → documented non-goals of XLA SPMD).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from .. import env as _env
from ..mesh import (AXES_ORDER, CommunicateTopology, HybridCommunicateGroup,
                    build_mesh, get_mesh)


@dataclasses.dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1


class DistributedStrategy:
    """reference: fleet/base/distributed_strategy.py:105 (wrapping
    distributed_strategy.proto). Plain dataclass-style config; serializable
    via __dict__."""

    def __init__(self):
        self.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                               "sharding_degree": 1, "sep_degree": 1}
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "use_pure_fp16": False,
                            "use_bf16": True}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {"stage": 1, "offload": False}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.find_unused_parameters = False
        # reference meta_optimizers/fp16_allreduce_optimizer.py: compress
        # grads for the allreduce. TPU form: cast fp32 grads to bf16 for
        # the pmean collectives (halves ICI bytes), accumulate back in fp32.
        self.fp16_allreduce = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1

    def to_dict(self):
        return dict(self.__dict__)


class Fleet:
    def __init__(self):
        self._is_initialized = False
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._topology: Optional[CommunicateTopology] = None
        self._user_defined_strategy = None

    # -- init --------------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        degrees = {
            "data": hc.get("dp_degree", 1),
            "pipe": hc.get("pp_degree", 1),
            "sharding": hc.get("sharding_degree", 1),
            "sep": hc.get("sep_degree", 1),
            "model": hc.get("mp_degree", 1),
        }
        import numpy as np
        import jax
        total = int(np.prod(list(degrees.values())))
        n_dev = len(jax.devices())
        if total == 1 and n_dev > 1:
            degrees["data"] = n_dev  # default: DP over all devices
        build_mesh(degrees)
        dims = [degrees[a] for a in ("data", "pipe", "sharding", "model")]
        self._topology = CommunicateTopology(
            ("data", "pipe", "sharding", "model"), dims)
        self._hcg = HybridCommunicateGroup(self._topology,
                                           global_rank=_env.get_rank() %
                                           max(self._topology.world_size(), 1))
        self._is_initialized = True
        return self

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        return self._hcg

    # -- info --------------------------------------------------------------
    def is_first_worker(self):
        return _env.get_rank() == 0

    def worker_index(self):
        return _env.get_rank()

    def worker_num(self):
        return _env.get_world_size()

    def is_worker(self):
        return True

    def worker_endpoints(self, to_string=False):
        eps = _env.ParallelEnv().trainer_endpoints
        return ",".join(eps) if to_string else eps

    def server_num(self):
        import os
        eps = os.environ.get("PADDLE_PSERVER_ENDPOINTS", "")
        return len([e for e in eps.split(",") if e.strip()])

    def is_server(self):
        import os
        return os.environ.get("TRAINING_ROLE", "").upper() == "PSERVER"

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    # -- parameter-server lifecycle (reference fleet_base.py:533-632) ------
    # The PS tier this drives is distributed/ps: native sparse tables
    # behind the csrc/ps TCP RPC service, key-hash-routed clients, and
    # the Hogwild/Downpour trainer runtime.
    def init_server(self, dim: int = None, optimizer: str = "adagrad",
                    port: int = None, **table_kwargs):
        """Start this rank's PS shard (reference init_server + the brpc
        server setup). The listening port comes from this rank's entry in
        PADDLE_PSERVER_ENDPOINTS unless given. Returns the PsServer (its
        ``.table`` is checkpointable)."""
        import os
        import threading
        from ..ps.service import PsServer
        if dim is None:
            raise ValueError("init_server needs the embedding dim "
                             "(the PS table schema)")
        if port is None:
            eps = [e for e in os.environ.get(
                "PADDLE_PSERVER_ENDPOINTS", "").split(",") if e.strip()]
            # this rank's index among the SERVERS — PADDLE_PSERVER_ID
            # (launch.py sets it for the server role); PADDLE_TRAINER_ID
            # numbers a different role and only coincides by accident
            idx = int(os.environ.get(
                "PADDLE_PSERVER_ID", os.environ.get("PADDLE_TRAINER_ID",
                                                    "0")))
            port = int(eps[idx].rsplit(":", 1)[1]) if idx < len(eps) else 0
        self._ps_server = PsServer(dim, optimizer, port=port, **table_kwargs)
        self._ps_stop = threading.Event()
        return self._ps_server

    def run_server(self):
        """Serve until stop_server() (reference run_server blocks in brpc).
        The RPC threads run in the background; this parks the main
        thread."""
        if getattr(self, "_ps_server", None) is None:
            raise RuntimeError("call fleet.init_server(...) first")
        self._ps_stop.wait()
        self._ps_server.stop()

    def stop_server(self):
        if getattr(self, "_ps_stop", None) is not None:
            self._ps_stop.set()

    def init_worker(self, async_mode: bool = False):
        """Connect this trainer to all PS shards (reference init_worker:
        brpc client + communicator). Returns the key-hash-routed
        DistributedSparseTable; async_mode enables geo-style buffered
        pushes."""
        import os
        from ..ps.service import DistributedSparseTable
        eps = [e for e in os.environ.get(
            "PADDLE_PSERVER_ENDPOINTS", "").split(",") if e.strip()]
        if not eps:
            raise RuntimeError("PADDLE_PSERVER_ENDPOINTS is empty — "
                               "no parameter servers to connect to")
        self._ps_client = DistributedSparseTable(eps, async_mode=async_mode)
        return self._ps_client

    def stop_worker(self):
        if getattr(self, "_ps_client", None) is not None:
            self._ps_client.flush()
            self._ps_client.close()
            self._ps_client = None

    # -- model/optimizer wrapping -----------------------------------------
    def distributed_model(self, model):
        """reference: fleet_base.py:836 — dispatch on hybrid degrees."""
        from ..parallel import DataParallel
        from ..meta_parallel import (PipelineParallel, ShardingParallel,
                                     TensorParallel)
        hcg = self._hcg
        if hcg is None:
            self.init()
            hcg = self._hcg
        if hcg.get_pipe_parallel_world_size() > 1:
            return PipelineParallel(model, hcg, self._strategy)
        if hcg.get_model_parallel_world_size() > 1:
            return TensorParallel(model, hcg, self._strategy)
        if hcg.get_sharding_parallel_world_size() > 1:
            return ShardingParallel(model, hcg, self._strategy)
        return DataParallel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        from ..meta_parallel.hybrid_optimizer import HybridParallelOptimizer
        if strategy is not None:
            self._user_defined_strategy = strategy
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       self._strategy or DistributedStrategy())

    # -- checkpoint passthrough -------------------------------------------
    def save(self, dirname, feed=(), fetch=(), model=None, input_spec=None,
             **configs):
        """reference fleet_base.py:654 save — persistables when no
        feed/fetch are given, else an inference artifact. Here the
        inference artifact is the StableHLO export (jit.save) of
        ``model`` traced at ``input_spec``."""
        if not feed and not fetch and input_spec is None:
            return self.save_persistables(dirname=dirname, model=model)
        if model is None:
            raise ValueError("fleet.save(inference) needs model= (a Layer) "
                             "and input_spec=[InputSpec...]")
        import os
        from ... import jit as _jit
        path = os.path.join(dirname, "model")
        _jit.save(model, path, input_spec=list(input_spec or ()))
        return path

    def save_inference_model(self, executor=None, dirname=None,
                             feeded_var_names=None, target_vars=None,
                             main_program=None, export_for_deployment=True,
                             model=None, input_spec=None):
        """reference fleet_base.py:697 (deprecated alias of save)."""
        if model is not None and not input_spec:
            raise ValueError(
                "fleet.save_inference_model needs input_spec=[InputSpec...] "
                "to trace the model (an empty spec would export a 0-input "
                "graph)")
        return self.save(dirname, feed=feeded_var_names or ("x",),
                         fetch=target_vars or ("out",), model=model,
                         input_spec=input_spec)

    def save_persistables(self, executor=None, dirname=None,
                          main_program=None, model=None):
        """reference fleet_base.py save_persistables: persist trainable
        state. Here the persistable state is (a) a Layer's state_dict
        when ``model`` is given, else (b) the static global scope
        (programs hold params in the Scope), plus (c) this rank's PS
        shard if one is hosted (fleet.init_server)."""
        import os

        import numpy as np

        if dirname is None:
            raise ValueError("save_persistables needs dirname")
        os.makedirs(dirname, exist_ok=True)
        if model is not None:
            from ... import save as _save
            _save(model.state_dict(), os.path.join(dirname, "model.pdparams"))
        else:
            from ...static import global_scope
            scope = global_scope()
            state = {n: np.asarray(scope.find_var(n))
                     for n in scope.local_var_names()
                     if scope.find_var(n) is not None}
            from ... import save as _save
            _save(state, os.path.join(dirname, "scope.pdparams"))
        if getattr(self, "_ps_server", None) is not None:
            self._ps_server.table.save(
                os.path.join(dirname, "sparse_shard.bin"))
        return dirname


from . import utils  # noqa: F401,E402

fleet = Fleet()

# Module-level API mirroring `from paddle.distributed import fleet`
init = fleet.init
is_first_worker = fleet.is_first_worker
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_worker = fleet.is_worker
worker_endpoints = fleet.worker_endpoints
server_num = fleet.server_num
is_server = fleet.is_server
barrier_worker = fleet.barrier_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_server = fleet.stop_server
init_worker = fleet.init_worker
stop_worker = fleet.stop_worker
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
from . import elastic  # noqa: F401,E402
from . import fs  # noqa: F401
from .elastic import ElasticManager, ElasticStatus  # noqa: F401,E402
