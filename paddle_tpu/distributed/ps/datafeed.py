"""MultiSlot dataset over the native parallel parser.

Capability map (reference): framework/data_feed.h:208 DataFeed /
:757 MultiSlotDataFeed (multi-threaded text ingest), data_set.h:43 Dataset
(:101 LoadIntoMemory, global shuffle) and the python paddle.distributed
InMemoryDataset wrappers. Slots are declared up front; each line holds, per
slot, a count followed by that many int64 ids (sparse) or floats (dense).
"""
from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .native import lib


class _Slot:
    def __init__(self, name: str, dense: bool):
        self.name = name
        self.dense = dense
        self.offsets = np.zeros((1,), np.int64)   # CSR over examples
        self.values = np.zeros((0,), np.float32 if dense else np.int64)


class InMemoryDataset:
    """reference: data_set.h:43 / python InMemoryDataset. load_into_memory
    parses files with the native multi-threaded parser; global_shuffle
    permutes examples; batches come out padded (sparse) or stacked (dense).
    """

    def __init__(self, slot_names: Sequence[str],
                 dense_slots: Sequence[str] = ()):
        self.slot_names = list(slot_names)
        self._slots = [_Slot(n, n in set(dense_slots)) for n in slot_names]
        self._order: Optional[np.ndarray] = None
        self._n = 0

    def __len__(self):
        return self._n

    def load_into_memory(self, filelist: Sequence[str], nthreads: int = 8):
        l = lib()
        kinds = np.array([1 if s.dense else 0 for s in self._slots],
                         dtype=np.int32)
        kp = kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_int))
        # per-file pieces accumulated in lists, concatenated once at the end
        # (a rolling per-file concatenate would copy O(files^2) bytes)
        piece_offs: List[List[np.ndarray]] = [[] for _ in self._slots]
        piece_vals: List[List[np.ndarray]] = [[] for _ in self._slots]
        bases = [s.values.size for s in self._slots]
        for path in filelist:
            h = l.ps_datafeed_parse(path.encode(), len(self._slots), kp,
                                    nthreads)
            if not h:
                raise IOError(f"cannot parse {path}")
            try:
                n = int(l.ps_datafeed_num_lines(h))
                for i, s in enumerate(self._slots):
                    offs = np.empty((n + 1,), np.int64)
                    l.ps_datafeed_slot_offsets(
                        h, i, offs.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_int64)))
                    total = int(l.ps_datafeed_slot_total(h, i))
                    if s.dense:
                        vals = np.empty((total,), np.float32)
                        l.ps_datafeed_slot_vals(
                            h, i, vals.ctypes.data_as(
                                ctypes.POINTER(ctypes.c_float)))
                    else:
                        vals = np.empty((total,), np.int64)
                        l.ps_datafeed_slot_ids(
                            h, i, vals.ctypes.data_as(
                                ctypes.POINTER(ctypes.c_int64)))
                    piece_offs[i].append(offs[1:] + bases[i])
                    piece_vals[i].append(vals)
                    bases[i] += vals.size
            finally:
                l.ps_datafeed_destroy(h)
        for i, s in enumerate(self._slots):
            s.offsets = np.concatenate([s.offsets] + piece_offs[i])
            s.values = np.concatenate([s.values] + piece_vals[i])
        self._n = self._slots[0].offsets.size - 1
        for s in self._slots:
            if s.dense and s.offsets.size > 1:
                widths = np.diff(s.offsets)
                if widths.min() != widths.max():
                    bad = int(np.argmax(widths != widths[0]))
                    raise ValueError(
                        f"dense slot {s.name!r} has ragged widths: example "
                        f"{bad} has {int(widths[bad])} floats, expected "
                        f"{int(widths[0])} — check the input files")
        self._order = np.arange(self._n)

    def global_shuffle(self, seed: int = 0, rank: Optional[int] = None,
                       nprocs: Optional[int] = None,
                       exchange_dir: Optional[str] = None,
                       timeout: float = 120.0):
        """reference: data_set.h:157 global shuffle. Single-host form
        (rank None): permute the example order. Multi-PROCESS form: every
        example is routed to a uniformly random destination trainer and
        physically EXCHANGED (the reference ships examples through the PS;
        here through a shared filesystem ``exchange_dir`` — each rank
        writes per-destination shards, barriers on done-markers, then
        ingests the shards addressed to it), followed by a local
        permutation."""
        if rank is None or not nprocs or nprocs == 1:
            rng = np.random.RandomState(seed)
            self._order = rng.permutation(self._n)
            return
        assert exchange_dir, "multi-process global_shuffle needs a shared " \
                             "exchange_dir"
        import os
        import pickle
        import time
        os.makedirs(exchange_dir, exist_ok=True)
        # shards/markers are keyed by seed so each shuffle ROUND is its own
        # namespace — reusing (exchange_dir, seed) would let a rank sail
        # through the barrier on the previous round's stale markers and
        # read old shards (duplicating/losing examples). Fail loudly.
        done_mine = os.path.join(exchange_dir, f"done.{seed}.{rank}")
        if os.path.exists(done_mine):
            raise ValueError(
                f"global_shuffle(seed={seed}) was already run in "
                f"{exchange_dir!r}; use a fresh seed (e.g. the epoch "
                "number) or a fresh exchange_dir per shuffle round")
        rng = np.random.RandomState(seed * 100003 + rank)
        dest = rng.randint(0, nprocs, size=self._n)
        for d in range(nprocs):
            idxs = np.nonzero(dest == d)[0]
            payload = {"n": int(idxs.size),
                       "slots": [(s.name, s.dense) + self._gather(s, idxs)
                                 for s in self._slots]}
            tmp = os.path.join(exchange_dir, f".ex.{seed}.{rank}.{d}.tmp")
            with open(tmp, "wb") as f:
                pickle.dump(payload, f)
            os.replace(tmp, os.path.join(exchange_dir,
                                         f"ex.{seed}.{rank}.{d}.pkl"))
        open(done_mine, "w").close()
        deadline = time.time() + timeout
        while not all(os.path.exists(os.path.join(exchange_dir,
                                                  f"done.{seed}.{r}"))
                      for r in range(nprocs)):
            if time.time() > deadline:
                raise TimeoutError("global_shuffle barrier timed out")
            time.sleep(0.05)
        parts = []
        for src in range(nprocs):
            with open(os.path.join(
                    exchange_dir, f"ex.{seed}.{src}.{rank}.pkl"),
                    "rb") as f:
                parts.append(pickle.load(f))
        # rebuild slots from the shards addressed to this rank
        self._n = sum(p["n"] for p in parts)
        for si, s in enumerate(self._slots):
            offs = [np.zeros((1,), np.int64)]
            vals = []
            base = 0
            for p in parts:
                _, _, po, pv = p["slots"][si]
                offs.append(po[1:] + base)
                vals.append(pv)
                base += pv.size
            s.offsets = np.concatenate(offs)
            s.values = (np.concatenate(vals) if vals else
                        np.zeros((0,), s.values.dtype))
        self._order = np.random.RandomState(
            seed * 7919 + rank).permutation(self._n)

    @staticmethod
    def _gather(s: "_Slot", idxs: np.ndarray):
        """(offsets, values) of the examples `idxs` as a packed pair.
        Vectorized: one fancy-index instead of a per-example slice loop
        (this runs once per destination rank per slot in global_shuffle)."""
        starts = s.offsets[idxs]
        lens = s.offsets[idxs + 1] - starts
        offsets = np.concatenate([np.zeros((1,), np.int64),
                                  np.cumsum(lens)])
        if not idxs.size or offsets[-1] == 0:
            return offsets, np.zeros((0,), s.values.dtype)
        # flat source index for every value: repeat each start, then add
        # the within-example ramp (global ramp minus repeated segment base)
        seg_base = np.repeat(offsets[:-1], lens)
        flat = np.repeat(starts, lens) + (np.arange(offsets[-1]) - seg_base)
        return offsets, s.values[flat]

    def _example_slice(self, s: _Slot, idx: int):
        a, b = s.offsets[idx], s.offsets[idx + 1]
        return s.values[a:b]

    def batch(self, start: int, size: int,
              pad: int = -1) -> Dict[str, np.ndarray]:
        """Examples [start, start+size) in the (possibly shuffled) order.
        Sparse slots pad to the longest example with ``pad`` (=-1, the
        DistributedEmbedding padding id); dense slots stack."""
        idxs = self._order[start:start + size]
        out: Dict[str, np.ndarray] = {}
        for s in self._slots:
            rows = [self._example_slice(s, int(i)) for i in idxs]
            if s.dense:
                out[s.name] = np.stack([r.astype(np.float32) for r in rows])
            else:
                L = max((r.size for r in rows), default=1) or 1
                m = np.full((len(rows), L), pad, dtype=np.int64)
                for j, r in enumerate(rows):
                    m[j, :r.size] = r
                out[s.name] = m
        return out

    def batches(self, batch_size: int, drop_last: bool = True):
        n = (self._n // batch_size) * batch_size if drop_last else self._n
        for st in range(0, n, batch_size):
            yield self.batch(st, min(batch_size, n - st))


# QueueDataset-style streaming is one pass over batches()
QueueDataset = InMemoryDataset
