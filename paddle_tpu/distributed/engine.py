"""Hybrid-parallel training engine.

The TPU-native replacement for the reference's entire multi-device execution
stack: ParallelExecutor + SSA graph (framework/parallel_executor.cc),
meta-optimizer program rewriting (fleet/meta_optimizers/*), and the
Trainer/SectionWorker runtime (framework/trainer.h) collapse into ONE jitted
step built here:

    loss/grads  — shard_map over the ("data","pipe","sharding","sep","model")
                  mesh: DP = batch split over data(+sharding) with pmean'd
                  grads; TP = explicit collectives inside mp_layers;
                  PP = GPipe/ppermute schedule (pipeline_parallel.py);
                  SP = ring attention over "sep" (ops/ring_attention.py).
    update      — GSPMD region: optimizer slots carry NamedShardings; ZeRO
                  stage-1/2 fall out of sharding the slots over "sharding"
                  (sharding_parallel.py), XLA inserts the gather/scatter that
                  sharding_optimizer.py:43 hand-writes.

One compiled XLA program per step: collectives are scheduled/overlapped by
XLA's latency-hiding scheduler (replacing reducer.cc:798's manual overlap).
"""
from __future__ import annotations

import time
from collections import OrderedDict
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

shard_map = jax.shard_map

from .. import profiler as _profiler
from .. import telemetry as _telemetry
from ..telemetry import tracing as _tracing
from ..framework.random import get_rng_key
from ..jit.functionalization import functional_call, state_of
from ..resilience.guard import all_finite
from .compressed import (QUANTIZED_POLICIES, compressed_psum_scatter,
                         compressed_tree_mean, normalize_axis_policies)
from .mesh import axis_links, require_mesh
from .meta_parallel.pipeline_parallel import PipelineParallel
from .meta_parallel.sharding_parallel import shard_spec_for

DATA_AXES = ("data", "sharding")  # batch is split over both (ZeRO ⊂ DP)

# XLA flags that make the TPU compiler schedule collectives asynchronously
# and hide them under compute — the hardware half of the bucketed
# backward-overlapped exchange (the jaxpr half is the per-bucket
# custom_vjp hooks in grads_fn). Must be in the environment BEFORE the
# TPU backend initializes; enable_latency_hiding_scheduler() is the
# idempotent setter.
LATENCY_HIDING_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)


def enable_latency_hiding_scheduler(env=None) -> bool:
    """Append :data:`LATENCY_HIDING_FLAGS` to ``LIBTPU_INIT_ARGS``,
    skipping any flag already present so operator overrides win. Returns
    True when the environment changed. libtpu reads these at backend
    initialization, so call this before the first jax device query (the
    trainer calls it best-effort when ``grad_sync_buckets > 1``; a late
    call is a no-op for the already-initialized process but still fixes
    child processes). Deliberately NOT mirrored into ``XLA_FLAGS``:
    CPU/GPU jaxlib builds hard-fail on unknown ``--xla_tpu_*`` flags
    there, which would poison every subprocess forked after a bucketed
    trainer is built."""
    import os
    env = os.environ if env is None else env
    cur = env.get("LIBTPU_INIT_ARGS", "")
    missing = [f for f in LATENCY_HIDING_FLAGS
               if f.split("=")[0] not in cur]
    if not missing:
        return False
    env["LIBTPU_INIT_ARGS"] = (cur + " " + " ".join(missing)).strip()
    return True


def partition_reverse_buckets(items, k: int):
    """Partition ``items`` ([(key, nbytes)] in FORWARD layer order) into
    at most ``k`` byte-balanced buckets in REVERSE order: bucket 0 holds
    the last layers — whose grads materialize first during backward — so
    its exchange is issued earliest and gets the longest compute shadow.
    Returns a list of non-empty key lists."""
    items = list(items)
    k = max(1, min(int(k), len(items)))
    total = float(sum(b for _, b in items)) or 1.0
    target = total / k
    buckets, cur, acc = [], [], 0.0
    rev = list(reversed(items))
    for i, (key, b) in enumerate(rev):
        cur.append(key)
        acc += float(b)
        left = len(rev) - i - 1
        need = k - 1 - len(buckets)  # buckets still owed after this one
        if need > 0 and (left == need or (acc >= target and left >= need)):
            buckets.append(cur)
            cur, acc = [], 0.0
    if cur:
        buckets.append(cur)
    return buckets


def _spec_has_axis(spec, axis: str) -> bool:
    return any(ax == axis or (isinstance(ax, tuple) and axis in ax)
               for ax in spec)


def _rank(x) -> int:
    """Array rank; works for arrays, scalars AND ShapeDtypeStructs (which
    jnp.shape rejects) so staging can run on abstract batches."""
    shape = getattr(x, "shape", None)
    return len(shape) if shape is not None else len(jnp.shape(x))


class ParallelTrainer:
    """Builds and runs the sharded jitted train step.

    model: Layer (possibly a meta_parallel wrapper). optimizer: Optimizer or
    HybridParallelOptimizer. loss_fn(outputs, labels) -> scalar (mean over
    the local microbatch).
    """

    def __init__(self, model, optimizer, loss_fn: Callable, mesh=None,
                 micro_batches: int = 1, remat: bool = False,
                 zero_stage: int = 0, accumulate_steps: int = 1,
                 fp16_allreduce: bool = False,
                 grad_sync: Optional[str] = None,
                 grad_sync_block: Optional[int] = None,
                 grad_sync_bucket_bytes: int = 4 << 20,
                 grad_sync_buckets: int = 1,
                 grad_sync_dcn_only: Optional[bool] = None,
                 nan_guard: bool = True,
                 integrity_check_every: int = 0,
                 scaler=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or require_mesh()
        self.micro_batches = micro_batches
        self.remat = remat
        self.zero_stage = zero_stage
        # In-step NaN/Inf guard (reference: check_finite_and_unscale +
        # update_loss_scaling IN the graph): one fused all-finite reduction
        # over the exchanged grads decides — via jnp.where, no host sync,
        # no recompile — whether this step's param/opt/comm_err update
        # applies or is skipped, bumping the skipped-steps counter carried
        # in state["guard"].
        self.nan_guard = nan_guard
        # Optional amp.GradScaler: its functional scale state rides in
        # state["guard"]["amp"]; the traced step scales the loss, unscales
        # the grads, and applies the dynamic incr/decr policy off the same
        # finite flag the guard uses.
        self.scaler = scaler if (scaler is not None
                                 and scaler.is_enable()) else None
        # gradient-exchange policy (distributed/compressed.py): the DP grad
        # sync is a bucketed flat exchange — "fp32" exact, "bf16" half the
        # wire bytes (reference fp16_allreduce_optimizer.py), "int8" the
        # EQuARX-style two-phase block-scaled exchange with error feedback
        # (~4x fewer bytes), "int4" its nibble-packed sibling (~7x).
        # grad_sync_dcn_only gates the quantized policy to the mesh axes
        # whose link type is "dcn" (mesh.axis_links): ICI hops stay fp32.
        # Resolution: explicit arg > the wrapper model's grad_sync
        # attribute (DataParallel / ShardingParallel strategy) > the
        # legacy fp16_allreduce flag.
        if grad_sync is None:
            grad_sync = getattr(model, "grad_sync", None)
            if grad_sync is not None:
                grad_sync_block = getattr(model, "grad_sync_block",
                                          grad_sync_block)
                grad_sync_bucket_bytes = getattr(
                    model, "grad_sync_bucket_bytes", grad_sync_bucket_bytes)
        if grad_sync_dcn_only is None:
            grad_sync_dcn_only = bool(getattr(model, "grad_sync_dcn_only",
                                              False))
        if grad_sync is None:
            grad_sync = "bf16" if fp16_allreduce else "fp32"
        self.grad_sync = grad_sync
        self.grad_sync_block = grad_sync_block
        self.grad_sync_bucket_bytes = grad_sync_bucket_bytes
        # K reverse-layer-order exchange buckets: K=1 is the monolithic
        # post-backward exchange; K>=2 splits the plain trainable leaves
        # into byte-balanced buckets and issues each bucket's exchange
        # INSIDE the backward (per-bucket custom_vjp hooks in grads_fn)
        # as soon as its layers' grads exist, so collective time hides
        # under the remaining backward compute.
        self.grad_sync_buckets = max(
            1, int(getattr(model, "grad_sync_buckets", grad_sync_buckets)))
        if self.grad_sync_buckets > 1:
            enable_latency_hiding_scheduler()
        self.grad_sync_dcn_only = grad_sync_dcn_only
        self.fp16_allreduce = fp16_allreduce or grad_sync == "bf16"
        # GradientMerge (reference: fleet/meta_optimizers
        # gradient_merge_optimizer + DistributedStrategy.gradient_merge):
        # split each batch into k chunks, accumulate grads, one optimizer step
        self.accumulate_steps = accumulate_steps
        # Silent-corruption defense (resilience/integrity.py): every
        # `integrity_check_every` steps the jitted step additionally
        # fingerprints params/opt/comm_err in-graph and compares the
        # data-replicated leaves across ranks with pmin/pmax. Two cached
        # programs (like LocalSGD's sync/non-sync split): the non-check
        # step carries ZERO fingerprint collectives and no recompiles
        # happen after the first check step. 0 disables the feature.
        self.integrity_check_every = max(0, int(getattr(
            model, "integrity_check_every", integrity_check_every)))
        self._steps_run = 0
        self.last_divergence: list = []
        self.state = None
        self._init_state()
        self._build()
        # layers that manage live training state between steps (the
        # HeterPS hot tier) bind themselves here — forgetting a manual
        # attach() would silently train on the stale eager parameters
        if hasattr(model, "named_sublayers"):
            for _, sub in model.named_sublayers(include_self=True):
                hook = getattr(sub, "_on_trainer_built", None)
                if hook is not None:
                    hook(self)

    @classmethod
    def from_plan(cls, plan, model, optimizer, loss_fn: Callable,
                  mesh=None, **overrides) -> "ParallelTrainer":
        """Build a trainer from an auto-parallel :class:`~.auto.Plan`.

        The plan's searched knobs (mesh degrees, grad_sync policy /
        dcn-gating / bucket count, remat, zero_stage, microbatch or
        accumulate count) become constructor kwargs via ``plan.apply()``;
        explicit ``overrides`` win over the plan, and an explicit
        ``mesh`` suppresses building (and installing) the plan's own."""
        kw = plan.apply(mesh=mesh, build_mesh=mesh is None)
        kw.update(overrides)
        return cls(model, optimizer, loss_fn, **kw)

    # -- state -------------------------------------------------------------
    def _param_spec(self, name, p):
        return p.pspec if p.pspec is not None else P()

    def _init_state(self):
        params, buffers = state_of(self.model)
        boxes = OrderedDict(self.model.named_parameters())
        self.param_specs = OrderedDict(
            (n, self._param_spec(n, boxes[n])) for n in params)
        # buffers default replicated; models may pin specific buffers to a
        # mesh axis (pipe-stacked stage buffers, pp_layers.buffer_pspecs)
        bspecs = (self.model.named_buffer_pspecs()
                  if hasattr(self.model, "named_buffer_pspecs") else {})
        self.buffer_specs = OrderedDict(
            (n, bspecs.get(n, P())) for n in buffers)
        self.trainable = OrderedDict((n, boxes[n].trainable) for n in params)
        tparams = OrderedDict((k, v) for k, v in params.items()
                              if self.trainable[k])
        opt_state = self.optimizer.init_state(tparams)
        # place params/opt on the mesh. Copy: the step donates these buffers,
        # and the Layer's Parameters (or another trainer) may alias them.
        def put(v, spec):
            return jax.device_put(jnp.array(v, copy=True),
                                  NamedSharding(self.mesh, spec))

        n_shard = self.mesh.shape.get("sharding", 1)
        # ZeRO-3: shard parameter STORAGE over the "sharding" axis. Inside
        # the step each shard is all-gathered right before use (and the
        # gather's transpose reduce-scatters the grad), so per-device param
        # memory is 1/n_shard — the sharding_optimizer.py:43 capability done
        # the GSPMD way. Params already sharded by ShardingParallel stage 3
        # (pspec on the sharding axis) are honored too.
        if self.zero_stage >= 3 and n_shard > 1:
            for k in list(self.param_specs):
                if self.trainable[k] and self.param_specs[k] == P():
                    self.param_specs[k] = shard_spec_for(params[k], n_shards=n_shard)
        self.zero3_dims = {}
        if n_shard > 1:
            for k, spec in self.param_specs.items():
                for d, ax in enumerate(spec):
                    if ax == "sharding" or (isinstance(ax, tuple)
                                            and "sharding" in ax):
                        self.zero3_dims[k] = d
        # ZeRO-2: gradients leave the step SHARDED over the sharding axis
        # (reduce-scatter instead of all-reduce), so the grad buffers held
        # across gradient-merge accumulation are 1/n_shard per device
        # (reference sharding_optimizer stage os_g). zero-3 leaves are
        # already sharded; this covers the remaining trainable params.
        self.zero2_dims = {}
        if self.zero_stage >= 2 and n_shard > 1:
            for k in self.param_specs:
                if not self.trainable[k] or k in self.zero3_dims:
                    continue
                # only fully-replicated params are eligible: a TP-sharded
                # param (e.g. P(None, "model")) must keep its axis — naively
                # overwriting a dim with "sharding" would declare the grad
                # replicated over "model" while ranks hold different slices
                cur = self.param_specs[k]
                if any(ax is not None for ax in cur):
                    continue
                spec = shard_spec_for(params[k], n_shards=n_shard)
                for d, ax in enumerate(spec):
                    if ax == "sharding":
                        self.zero2_dims[k] = d
        params = OrderedDict((k, put(v, self.param_specs[k]))
                             for k, v in params.items())
        buffers = OrderedDict((k, put(v, self.buffer_specs[k]))
                              for k, v in buffers.items())
        self.opt_specs = self._slot_specs(opt_state, params, n_shard)
        opt_state = jax.tree_util.tree_map(
            lambda v, s: put(v, s), opt_state, self.opt_specs)
        # int8 grad sync: per-RANK error-feedback residuals, stored
        # replica-major — leading dim = product of the grad-reduce axes,
        # sharded over them so each rank owns exactly its own residual
        # (the DGC local-accumulation slot, kept as engine state because
        # the exchange happens inside the shard_map region)
        sep = self.mesh.shape.get("sep", 1) > 1
        axes = DATA_AXES + ("sep",) if sep else DATA_AXES
        # hand-built meshes may omit axes (build_mesh always has all five)
        self.reduce_axes = tuple(ax for ax in axes if ax in self.mesh.shape)
        # per-axis exchange policy: under DCN gating the quantized policy
        # rides only the axes whose link type is "dcn" (explicit
        # mesh.set_axis_links override, else inferred from slice
        # structure); ICI axes pre-reduce losslessly in fp32.
        if self.grad_sync_dcn_only and self.grad_sync in QUANTIZED_POLICIES:
            links = axis_links(self.mesh)
            self._axis_policy = {
                ax: (self.grad_sync if links.get(ax) == "dcn" else "fp32")
                for ax in self.reduce_axes}
            self._any_quantized = any(
                p in QUANTIZED_POLICIES for p in self._axis_policy.values())
        else:
            self._axis_policy = self.grad_sync
            self._any_quantized = self.grad_sync in QUANTIZED_POLICIES
        self.comm_err_specs = {}
        comm_err = {}
        # the sharded-grad (ZeRO-2/3) leaves exchange per-tensor through
        # compressed_psum_scatter over the "sharding" axis; when that
        # axis's policy quantizes they carry their own per-rank residual
        # (full-tensor shape: each rank's quantization error spans the
        # whole gradient, not just its scattered chunk)
        rs_pol = (self._axis_policy.get("sharding", "fp32")
                  if isinstance(self._axis_policy, dict)
                  else self._axis_policy)
        rs_quant = rs_pol in QUANTIZED_POLICIES
        ppm = self.model if isinstance(self.model, PipelineParallel) \
            else getattr(self.model, "_layers", None)
        is_1f1b = (isinstance(ppm, PipelineParallel)
                   and getattr(ppm, "schedule", "gpipe") == "1f1b")
        if self._any_quantized:
            R = 1
            for ax in self.reduce_axes:
                R *= self.mesh.shape.get(ax, 1)
            for k, v in params.items():
                if not self.trainable[k]:
                    continue
                if k in self.zero2_dims or k in self.zero3_dims:
                    # zero-3 grads only pass through the explicit
                    # (possibly quantized) reduce-scatter on the 1F1B
                    # manual-grad path; the AD path's gather transpose is
                    # a lossless psum_scatter and needs no residual
                    if not rs_quant or (k in self.zero3_dims
                                        and not is_1f1b):
                        continue
                    spec = P(self.reduce_axes)
                    self.comm_err_specs[k] = spec
                    comm_err[k] = put(
                        jnp.zeros((R,) + jnp.shape(v), jnp.float32), spec)
                    continue
                # trailing dims follow the param's own sharding: a TP- or
                # pipe-sharded param's residual differs per shard, so it
                # must be sharded the same way (declaring it replicated
                # would silently keep only one rank's residual)
                spec = P(self.reduce_axes, *self.param_specs[k])
                self.comm_err_specs[k] = spec
                comm_err[k] = put(
                    jnp.zeros((R,) + jnp.shape(v), jnp.float32), spec)
        # guard state: replicated scalars threaded through the jitted step
        # (skipped-step counter; plus the loss-scale state when a scaler is
        # attached) — in state so checkpoints carry it.
        guard = {"skipped": put(jnp.zeros((), jnp.int32), P())}
        if self.scaler is not None:
            guard["amp"] = jax.tree_util.tree_map(
                lambda v: put(v, P()), self.scaler.init_scale_state())
        self.state = {"params": params, "buffers": buffers,
                      "opt": opt_state, "comm_err": comm_err,
                      "guard": guard}

    def _slot_specs(self, opt_state, params, n_shard):
        """Sharding specs for the optimizer state.

        Slots follow their parameter's sharding: a pipe-stacked param
        (P("pipe", ...)) gets pipe-sharded moments — per-device slot memory
        1/pp, matching the reference's per-rank optimizer state under PP.
        With ZeRO (stage>=1) non-pipe params' slots shard over "sharding"
        instead (reference sharding_optimizer.py os segment)."""
        slot_specs = {}
        for k, st in opt_state.get("slots", {}).items():
            pspec = self.param_specs[k]
            has_pipe = _spec_has_axis(pspec, "pipe")
            if self.zero_stage >= 1 and n_shard > 1 and not has_pipe:
                slot_specs[k] = jax.tree_util.tree_map(
                    lambda v: shard_spec_for(v, n_shards=n_shard), st)
            else:
                pshape = jnp.shape(params[k])
                slot_specs[k] = jax.tree_util.tree_map(
                    lambda v: pspec if jnp.shape(v) == pshape else P(), st)
        specs = {kk: jax.tree_util.tree_map(lambda v: P(), vv)
                 for kk, vv in opt_state.items() if kk != "slots"}
        if "slots" in opt_state:
            # wrapper optimizers (LookAhead/ModelAverage) nest the real
            # slots deeper; their whole state replicates (no ZeRO slot
            # sharding through wrappers)
            specs["slots"] = slot_specs
        return specs

    # -- step construction ---------------------------------------------------
    def _build(self):
        mesh = self.mesh
        model = self.model
        loss_fn = self.loss_fn
        M = self.micro_batches
        is_pp = isinstance(model, PipelineParallel) or (
            hasattr(model, "_layers") and isinstance(model._layers, PipelineParallel))
        pp = model if isinstance(model, PipelineParallel) else None
        sep = mesh.shape.get("sep", 1) > 1
        reduce_axes = DATA_AXES + ("sep",) if sep else DATA_AXES

        pp_loss = pp_grads = None
        if pp is not None and sep and pp._uniform_fns() is None:
            raise NotImplementedError(
                "pipeline + context parallelism ('sep' axis) requires a "
                "plan that decomposes into prologue/stacked-body/epilogue "
                "(PipelineLayer.uniform_split): the switch-dispatch "
                "fallback issues ring-attention collectives from "
                "per-device branches, which deadlocks or silently "
                "corrupts the exchange")
        if pp is not None:
            if getattr(pp, "schedule", "gpipe") == "1f1b":
                # 1F1B computes grads itself (manual per-stage VJP inside
                # the tick scan — in-flight microbatches bounded by S)
                pp_grads = pp.build_pipeline_grads_fn(loss_fn, M)
            else:
                pp_loss = pp.build_pipeline_loss_fn(loss_fn, M)

        def local_loss(params, buffers, key, inputs, labels):
            """Runs on each device inside shard_map."""
            if pp_loss is not None:
                return pp_loss(params, buffers, key, inputs, labels)
            fwd = functional_call
            if self.remat:
                def fwd(m, p, b, *a, rng=None):
                    # [0]: keep only the model output — returning the
                    # (out, new_buffers) pair through jax.checkpoint
                    # would hand the tuple to loss_fn as "out"
                    f = jax.checkpoint(
                        lambda pp_, xx: functional_call(
                            m, pp_, b, xx, rng=rng)[0])
                    return f(p, *a), b
            out, _ = fwd(model, params, buffers, inputs, rng=key)
            return loss_fn(out, labels)

        zero3_dims = self.zero3_dims
        zero2_dims = self.zero2_dims
        n_shard = mesh.shape.get("sharding", 1)
        # ZeRO-2/3 sharded-grad leaves: block-quantized reduce-scatter
        # (phase 1 of the exchange, no gather) when the sharding axis's
        # policy quantizes; lossless policies keep the plain psum_scatter.
        rs_policy = (self._axis_policy.get("sharding", "fp32")
                     if isinstance(self._axis_policy, dict)
                     else self._axis_policy)
        if rs_policy not in QUANTIZED_POLICIES:
            rs_policy = None

        def _reduce_scatter(g, d, res=None):
            """Mean reduce-scatter of one sharded-grad leaf. ``res`` opts
            into error feedback (quantized rs_policy only): returns
            ``(mean_shard, new_residual)`` — the residual stays in the
            un-divided SUM domain, matching what the quantizer sees."""
            if rs_policy is not None:
                out = compressed_psum_scatter(
                    g, "sharding", scatter_dim=d, policy=rs_policy,
                    block=self.grad_sync_block, residual=res)
                if res is not None:
                    out, res = out
                    return out / n_shard, res
                return out / n_shard
            out = lax.psum_scatter(g, "sharding", scatter_dimension=d,
                                   tiled=True) / n_shard
            return out if res is None else (out, res)
        pipe_n = mesh.shape.get("pipe", 1)
        # params NOT sharded over the pipe axis (embedding/norm/head under
        # PP, i.e. everything outside the _StackedStage bodies) are
        # replicated over pipe, but each stage computes only its own
        # (partial, often zero) grad contribution — the psum over "pipe"
        # makes the grad genuinely replicated. Without it, cross-stage
        # reads of updated state (checkpoint save, sync_to_model) would be
        # undefined for stages >= 1 (round-1/2 verdict, engine grads).
        pipe_psum_keys = {
            k for k in self.param_specs
            if is_pp and pipe_n > 1 and self.trainable[k]
            and not _spec_has_axis(self.param_specs[k], "pipe")}

        # grad-exchange axes actually present in this mesh (an absent axis
        # name is unbound inside shard_map — naming it in a collective
        # would fail at trace time)
        sync_axes = tuple(ax for ax in reduce_axes if ax in mesh.shape)
        live_axes = tuple(ax for ax in sync_axes
                          if mesh.shape.get(ax, 1) > 1)
        if not self._any_quantized:
            # fp32/bf16: size-1 axes are pure no-ops, skip them; the
            # quantized policies keep the full tuple so the
            # quantize->dequantize (and the residual update) runs
            # identically at any device count
            sync_axes = live_axes

        # -- replica-divergence fingerprints (resilience/integrity.py) --
        # Per-leaf metadata for the check program: leaves whose spec
        # mentions none of the live data axes are REPLICATED across data
        # ranks — their fingerprints must agree bit-exactly, so they
        # join the pmin/pmax divergence compare. Leaves legitimately
        # sharded over those axes (comm_err's per-rank residual rows,
        # ZeRO slots) get a wrapping-psum combined digest instead:
        # recordable and replay-comparable, but excluded from the
        # cross-rank equality decision (their per-rank bytes differ by
        # design). Entry order == tree_leaves order of (params, opt,
        # comm_err) as the check shard_map receives them.
        self.integrity_axes = live_axes
        entries = []
        for part, vals, specs in (
                # plain-dict forms, matching exactly what the check
                # shard_map is called with (flatten order must agree)
                ("params", dict(self.state["params"]),
                 dict(self.param_specs)),
                ("opt", self.state["opt"], self.opt_specs),
                ("comm_err", dict(self.state["comm_err"]),
                 dict(self.comm_err_specs))):
            spec_list = []
            jax.tree_util.tree_map(
                lambda v, s: spec_list.append(s), vals, specs)
            flat, _ = jax.tree_util.tree_flatten_with_path(vals)
            for (path, _v), spec in zip(flat, spec_list):
                leaf_axes = tuple(ax for ax in live_axes
                                  if _spec_has_axis(spec, ax))
                entries.append(
                    (part + jax.tree_util.keystr(path), leaf_axes))
        self._integrity_entries = entries
        self._integrity_cmp_idx = [i for i, (_n, a) in enumerate(entries)
                                   if not a]
        # every size>1 mesh axis: the divergence mask is pmax-spread over
        # all of them so rank 0's copy of the verdict is authoritative
        # even when the diverged leaf is model/pipe-sharded
        self._integrity_all_axes = tuple(
            ax for ax in mesh.axis_names if mesh.shape.get(ax, 1) > 1)

        def integrity_check_fn(params, opt_state, comm_err):
            from ..resilience.integrity import fingerprint_array
            leaves = (jax.tree_util.tree_leaves(params)
                      + jax.tree_util.tree_leaves(opt_state)
                      + jax.tree_util.tree_leaves(comm_err))
            fps = []
            for (_name, leaf_axes), leaf in zip(self._integrity_entries,
                                                leaves):
                fp = fingerprint_array(leaf)
                if leaf_axes:
                    # wrap-add is order-independent: the combined digest
                    # of a sharded leaf is deterministic on any backend
                    fp = lax.psum(fp, leaf_axes)
                fps.append(fp)
            fps = (jnp.stack(fps) if fps
                   else jnp.zeros((0,), jnp.uint32))
            cmp_idx = self._integrity_cmp_idx
            if cmp_idx and self.integrity_axes:
                cmp = fps[jnp.asarray(cmp_idx)]
                div = (lax.pmin(cmp, self.integrity_axes)
                       != lax.pmax(cmp, self.integrity_axes)
                       ).astype(jnp.int32)
                if self._integrity_all_axes:
                    div = lax.pmax(div, self._integrity_all_axes)
            else:
                div = jnp.zeros((len(cmp_idx),), jnp.int32)
            return fps, div

        self._integrity_check_fn = integrity_check_fn

        # loss scaling (scaler attached): the loss is scaled BEFORE the
        # backward pass (underflow protection is in the gradient compute,
        # scaling afterwards would be too late) and grads are unscaled
        # before the exchange so comm_err magnitudes stay policy-stable.
        # The pp schedules compute grads manually and skip the scaling —
        # on bf16 TPU scale=1 anyway; the dynamic scale policy still runs
        # off the guard's finite flag.
        use_amp = self.scaler is not None

        # Backward-overlapped exchange buckets: the plain trainable
        # leaves (the flat-exchange set) in named_parameters — i.e.
        # forward/layer — order, split into byte-balanced REVERSE-order
        # buckets. Bucket 0 holds the last layers, whose grads
        # materialize first in the backward, so its exchange is issued
        # earliest and hides under the longest remaining compute. Only
        # the AD path buckets: 1F1B computes grads manually (no backward
        # to hook into), and a single leaf has nothing to split.
        plain_keys = [k for k in self.param_specs
                      if self.trainable[k] and k not in zero2_dims
                      and k not in zero3_dims]
        use_buckets = (self.grad_sync_buckets > 1 and pp_grads is None
                       and bool(sync_axes) and len(plain_keys) >= 2)
        bucket_keys = []
        if use_buckets:
            bucket_keys = partition_reverse_buckets(
                [(k, self.state["params"][k].nbytes) for k in plain_keys],
                self.grad_sync_buckets)
            use_buckets = len(bucket_keys) >= 2
        self.grad_sync_bucket_keys = ([list(b) for b in bucket_keys]
                                      if use_buckets
                                      else ([list(plain_keys)]
                                            if plain_keys else []))
        self._use_buckets = use_buckets
        bucketed = (frozenset(k for b in bucket_keys for k in b)
                    if use_buckets else frozenset())

        def grads_fn(params, buffers, comm_err, scale, key, inputs, labels):
            tparams = {k: v for k, v in params.items() if self.trainable[k]}
            frozen = {k: v for k, v in params.items() if not self.trainable[k]}

            if pp_grads is not None:
                # 1F1B: manual grads. Each device's gacc is the gradient of
                # ITS local (batch-shard) mean loss — the same per-device
                # quantity the AD path produces before reduction — so the
                # reduction block below applies unchanged, except ZeRO-3
                # grads arrive full-size (no gather transpose) and need an
                # explicit reduce-scatter.
                merged = dict(params)
                for k, d in zero3_dims.items():
                    merged[k] = lax.all_gather(merged[k], "sharding",
                                               axis=d, tiled=True)
                loss, grads = pp_grads(merged, buffers, key, inputs,
                                       labels, tuple(tparams))
                for ax in reduce_axes:
                    if mesh.shape.get(ax, 1) > 1:
                        loss = lax.pmean(loss, ax)
            else:
                # Per-bucket exchange hook: identity on the params in the
                # forward; the backward performs the bucket's whole DP
                # exchange (AMP unscale, pipe psum, compressed flat mean)
                # ON the cotangents at the exact point in the backward
                # where the bucket's grads materialize — XLA sees the
                # collective mid-backward and the latency-hiding
                # scheduler can run it under the remaining compute. The
                # bucket's NEW error-feedback residual leaves the
                # backward as the cotangent of the residual input
                # (value_and_grad argnums=(0, 1) below).
                def _exchange_hook():
                    @jax.custom_vjp
                    def hook(sub, res):
                        return sub

                    def h_fwd(sub, res):
                        return sub, res

                    def h_bwd(res, g):
                        g = dict(g)
                        if use_amp:
                            inv = 1.0 / scale
                            g = {k: v * inv.astype(v.dtype)
                                 for k, v in g.items()}
                        for k in g:
                            if k in pipe_psum_keys:
                                g[k] = lax.psum(g[k], "pipe")
                        mean, new_res = compressed_tree_mean(
                            g, sync_axes, policy=self._axis_policy,
                            block=self.grad_sync_block,
                            bucket_bytes=self.grad_sync_bucket_bytes,
                            residuals=(res if res else None))
                        return mean, (new_res if res else {})

                    hook.defvjp(h_fwd, h_bwd)
                    return hook

                hook = _exchange_hook() if use_buckets else None

                def lf(tp, res_in):
                    tp = dict(tp)
                    if use_buckets:
                        for keys, r in zip(bucket_keys, res_in):
                            tp.update(hook({k: tp[k] for k in keys}, r))
                    merged = dict(frozen)
                    merged.update(tp)
                    # ZeRO-3 storage shards -> full params for this step's
                    # compute; the all_gather transpose reduce-scatters
                    # grads
                    for k, d in zero3_dims.items():
                        merged[k] = lax.all_gather(merged[k], "sharding",
                                                   axis=d, tiled=True)
                    loss = local_loss(merged, buffers, key, inputs, labels)
                    # mean over the data axes (each device saw 1/N of the
                    # batch; under context parallelism also 1/n_sep of the
                    # sequence)
                    for ax in reduce_axes:
                        if mesh.shape.get(ax, 1) > 1:
                            loss = lax.pmean(loss, ax)
                    if use_amp:
                        loss = loss * scale.astype(loss.dtype)
                    return loss

                if use_buckets:
                    res_in = tuple(
                        {k: comm_err[k][0] for k in keys if k in comm_err}
                        for keys in bucket_keys)
                    loss, (grads, gres) = jax.value_and_grad(
                        lf, argnums=(0, 1))(tparams, res_in)
                else:
                    loss, grads = jax.value_and_grad(lf)(tparams, ())
                if use_amp:
                    inv = 1.0 / scale
                    loss = loss * inv.astype(loss.dtype)
                    # bucketed leaves were unscaled inside their hook
                    grads = {k: (g if k in bucketed
                                 else g * inv.astype(g.dtype))
                             for k, g in grads.items()}
            # DP grad averaging over the data axes; 'model'/'pipe' grads
            # are handled by shard_map transposition of the collectives.
            # Pipe-replicated grads are psum'd FIRST: psum/pmean commute
            # for the exact policies, and the int8 path must quantize the
            # full (pipe-summed) grad so every stage computes the same
            # residual — otherwise the pipe-replicated comm_err state
            # would silently diverge across stages.
            for k in pipe_psum_keys:
                if k not in bucketed:
                    grads[k] = lax.psum(grads[k], "pipe")
            new_comm_err = dict(comm_err)

            # ZeRO-2/3 leaves keep per-tensor handling: they LEAVE the
            # exchange sharded over "sharding" (reduce-scatter), which the
            # flat bucketed path cannot express.
            def _pmean(g, ax):
                # fp16_allreduce: fp32 grads cross the wire as bf16
                if self.fp16_allreduce and g.dtype == jnp.float32:
                    return lax.pmean(g.astype(jnp.bfloat16),
                                     ax).astype(jnp.float32)
                return lax.pmean(g, ax)

            for k in grads:
                if k in zero3_dims:
                    # ZeRO-3 grads already carry the SUM over the sharding
                    # axis (all_gather transpose = reduce-scatter): divide
                    # for the mean, pmean over the remaining data axes
                    if pp_grads is not None:
                        # manual grads are wrt the GATHERED param: explicit
                        # reduce-scatter (mean) back onto the storage
                        # shard, threading the leaf's EF residual when the
                        # sharding hop quantizes
                        if k in comm_err:
                            grads[k], r = _reduce_scatter(
                                grads[k], zero3_dims[k], comm_err[k][0])
                            new_comm_err[k] = r[None]
                        else:
                            grads[k] = _reduce_scatter(
                                grads[k], zero3_dims[k])
                    else:
                        grads[k] = grads[k] / n_shard
                    for ax in ("data", "sep"):
                        if ax in reduce_axes and mesh.shape.get(ax, 1) > 1:
                            grads[k] = _pmean(grads[k], ax)
                elif k in zero2_dims:
                    # reduce-scatter (mean) over sharding; pmean over data
                    if k in comm_err:
                        grads[k], r = _reduce_scatter(
                            grads[k], zero2_dims[k], comm_err[k][0])
                        new_comm_err[k] = r[None]
                    else:
                        grads[k] = _reduce_scatter(grads[k], zero2_dims[k])
                    for ax in ("data", "sep"):
                        if ax in reduce_axes and mesh.shape.get(ax, 1) > 1:
                            grads[k] = _pmean(grads[k], ax)

            if use_buckets:
                # the per-bucket exchanges already ran inside the
                # backward; fold each bucket's new residual (the
                # cotangent of its residual input) back into the
                # replica-major comm_err state
                for r in gres:
                    for k, v in r.items():
                        new_comm_err[k] = v[None]
                return loss, grads, new_comm_err

            # plain leaves, monolithic (K=1) mode: ONE bucketed flat
            # exchange (compressed.py) over the data axes instead of one
            # pmean per tensor — the Reducer bucketing, plus bf16/int8
            # wire compression per self.grad_sync. comm_err is the int8
            # error-feedback state, replica-major outside the step; its
            # local view here is (1, *shape).
            plain = {k: grads[k] for k in grads
                     if k not in zero3_dims and k not in zero2_dims}
            if plain and sync_axes:
                res = ({k: comm_err[k][0] for k in plain
                        if k in comm_err} or None)
                mean, res = compressed_tree_mean(
                    plain, sync_axes, policy=self._axis_policy,
                    block=self.grad_sync_block,
                    bucket_bytes=self.grad_sync_bucket_bytes,
                    residuals=res)
                grads.update(mean)
                if res:
                    new_comm_err.update({k: res[k][None] for k in res})
            return loss, grads, new_comm_err

        def _grad_spec(k):
            if k in zero2_dims:
                # grads leave the step sharded on the zero-2 dim
                d = zero2_dims[k]
                spec = list(self.param_specs[k]) + [None] * (
                    d + 1 - len(self.param_specs[k]))
                spec[d] = "sharding"
                return P(*spec)
            return self.param_specs[k]

        tspecs = OrderedDict((k, _grad_spec(k))
                             for k in self.param_specs
                             if self.trainable[k])

        opt = self.optimizer

        K = self.accumulate_steps

        def make_step(input_specs, label_specs, do_check=False):
            """Jitted step for one concrete (inputs, labels) pytree shape.

            ``do_check=True`` builds the integrity variant: after the
            update it fingerprints params/opt/comm_err in-graph and
            pmin/pmax-compares the data-replicated leaves across ranks
            (two scalar collectives per compared leaf). The plain
            program carries none of this — zero fingerprint
            collectives, asserted by chaos_smoke's sdc scenario.

            Data specs are per-LEAF: the batch dim always splits over
            data×sharding; with context parallelism ("sep" axis) rank>=2
            leaves additionally split their SEQUENCE dim (dim 1) over "sep"
            — ring attention (ops/ring_attention.py) rotates K/V chunks
            around that axis inside the model. Rank-1 leaves (e.g. per-row
            labels) carry no sequence dim, so they only batch-split — this
            is why specs cannot be a single P for all leaves.
            """
            # check_vma=False: the vma checker cannot statically prove
            # invariances that hold here by construction — size-1 mesh axes
            # skip their pmean, and the custom-vjp collectives in mp_layers
            # (identity-backward psum) hide the reductions that make TP
            # grads replicated. Satisfying it formally would insert real
            # all-reduces over axes whose values are already equal. The
            # replication this declares IS enforced: grads of
            # pipe-replicated params are psum'd over "pipe" above, and
            # test_pipeline_parallel.py::test_tied_state_stays_replicated_
            # across_pipe checks bit-identical per-device state after real
            # updates (set FLAGS_check_replication for the same check at
            # every step).
            sharded_grads = shard_map(
                grads_fn, mesh=mesh,
                in_specs=(dict(self.param_specs), dict(self.buffer_specs),
                          dict(self.comm_err_specs), P(), P(), input_specs,
                          label_specs),
                out_specs=(P(), dict(tspecs), dict(self.comm_err_specs)),
                check_vma=False)

            nan_guard = self.nan_guard
            scaler = self.scaler

            check_map = None
            if do_check and self._integrity_entries:
                check_map = shard_map(
                    self._integrity_check_fn, mesh=mesh,
                    in_specs=(dict(self.param_specs), self.opt_specs,
                              dict(self.comm_err_specs)),
                    out_specs=(P(), P()), check_vma=False)

            def train_step(params, buffers, opt_state, comm_err, guard,
                           key, lr, taint, inputs, labels):
                # taint: the fault-injection operand (1.0 in normal runs,
                # NaN when faults.py poisons a grad leaf). A traced weak
                # scalar, so flipping it never recompiles.
                comm_err0 = comm_err
                scale = (guard["amp"]["scale"] if use_amp
                         else jnp.float32(1.0))
                if K > 1:
                    # gradient merge: grads averaged over K sequential
                    # chunks (activation memory is 1/K; same numerics as
                    # the big batch). The error-feedback state threads
                    # through the chunks — each chunk's exchange consumes
                    # the residual the previous one left.
                    chunk = jax.tree_util.tree_map(
                        lambda x: jnp.reshape(x, (K, x.shape[0] // K)
                                              + x.shape[1:]),
                        (inputs, labels))
                    keys = jax.random.split(key, K)
                    loss = 0.0
                    grads = None
                    for i in range(K):
                        ins_i, lbs_i = jax.tree_util.tree_map(
                            lambda x: x[i], chunk)
                        l_i, g_i, comm_err = sharded_grads(
                            dict(params), dict(buffers), dict(comm_err),
                            scale, keys[i], ins_i, lbs_i)
                        loss = loss + l_i / K
                        grads = g_i if grads is None else \
                            jax.tree_util.tree_map(
                                lambda a, b: a + b, grads, g_i)
                    grads = jax.tree_util.tree_map(lambda g: g / K, grads)
                else:
                    loss, grads, comm_err = sharded_grads(
                        dict(params), dict(buffers), dict(comm_err),
                        scale, key, inputs, labels)
                if grads:
                    # fault-injection surface: poison ONE grad leaf
                    k0 = next(iter(grads))
                    grads[k0] = grads[k0] * jnp.asarray(
                        taint, grads[k0].dtype)
                tparams = {k: v for k, v in params.items()
                           if self.trainable[k]}
                new_t, new_opt = opt.apply_gradients(tparams, grads,
                                                     opt_state, lr=lr)
                new_params = dict(params)
                new_params.update(new_t)
                # keep optimizer slots on their ZeRO shardings
                new_opt = jax.tree_util.tree_map(
                    lambda v, s: lax.with_sharding_constraint(
                        v, NamedSharding(mesh, s)),
                    new_opt, self.opt_specs)
                new_guard = dict(guard)
                if nan_guard or use_amp:
                    # ONE fused reduction, fully in-graph: no host sync,
                    # and the same flag serves the loss-scale policy
                    finite = all_finite(grads)
                    if nan_guard:
                        def keep(new, old):
                            return jax.tree_util.tree_map(
                                lambda n, o: jnp.where(finite, n, o),
                                new, old)
                        new_params = keep(new_params, dict(params))
                        new_opt = keep(new_opt, opt_state)
                        comm_err = keep(comm_err, comm_err0)
                        new_guard["skipped"] = guard["skipped"] + \
                            (~finite).astype(jnp.int32)
                    if use_amp:
                        new_guard["amp"] = scaler.update_scale_state(
                            guard["amp"], ~finite)
                # integrity fingerprints of the FINAL (possibly
                # guard-reverted) state — exactly what a checkpoint at
                # this step would persist. None on the plain program:
                # an empty pytree output, so both programs unpack alike.
                integ = None
                if check_map is not None:
                    integ = check_map(dict(new_params), new_opt,
                                      dict(comm_err))
                return loss, new_params, new_opt, comm_err, new_guard, \
                    integ

            return jax.jit(train_step, donate_argnums=(0, 2, 3, 4))

        self._make_step = make_step
        self._sep = sep
        self._step_cache = {}
        self._step_costs = {}      # cache_key -> analysis.cost numbers
        self._last_cache_key = None

        # Telemetry wire accounting: logical bytes one train_step's
        # bucketed DP exchange moves per rank, split per (policy, link)
        # exchange group so the Prometheus path shows ICI vs DCN bytes
        # separately. Static per trainer (the exchange is
        # shape-independent of the batch); ZeRO-2/3 leaves go through
        # per-tensor (possibly compressed) psum_scatter and are not
        # counted here.
        n_sync = 1
        for ax in sync_axes:
            n_sync *= mesh.shape.get(ax, 1)
        plain_params = {k: v for k, v in self.state["params"].items()
                        if self.trainable[k] and k not in zero2_dims
                        and k not in zero3_dims}
        # [(policy, link, bucket_label, bytes_per_step)] — bucket "0" is
        # the whole exchange in monolithic mode, else the reverse-order
        # bucket index (bucket 0 = last layers, exchanged first)
        self._wire_parts = []
        self._wire_bytes_per_step = 0.0
        self._wire_fp32_per_step = 0.0
        if plain_params and n_sync > 1:
            from .compressed import tree_wire_bytes
            links = axis_links(mesh)
            for bi, keys in enumerate(self.grad_sync_bucket_keys):
                bparams = {k: plain_params[k] for k in keys
                           if k in plain_params}
                if not bparams:
                    continue
                for axes_g, pol in normalize_axis_policies(
                        sync_axes, self._axis_policy):
                    n_g = 1
                    for ax in axes_g:
                        n_g *= mesh.shape.get(ax, 1)
                    if n_g <= 1:
                        continue
                    link = ("dcn" if any(links.get(ax) == "dcn"
                                         for ax in axes_g) else "ici")
                    b = K * tree_wire_bytes(bparams, n_g, pol,
                                            block=self.grad_sync_block)
                    self._wire_parts.append((pol, link, str(bi), b))
            self._wire_bytes_per_step = sum(p[3] for p in self._wire_parts)
            self._wire_fp32_per_step = K * tree_wire_bytes(
                plain_params, n_sync, "fp32", block=self.grad_sync_block)

    def _leaf_spec(self, x):
        """Per-leaf data PartitionSpec (see make_step docstring)."""
        r = _rank(x)
        if r == 0:
            return P()
        if self._sep and r >= 2:
            return P(DATA_AXES, "sep")
        return P(DATA_AXES)

    def _stage(self, inputs, labels, place: bool = True,
               do_check: bool = False):
        """Normalize a batch and get its jitted step from the cache
        (tracing it on first use). ``place=False`` skips device_put so
        ShapeDtypeStruct batches can stage without materializing data.
        ``do_check`` selects the integrity-check program variant (its
        own cache slot: at steady state both programs are staged once
        and the cadence flips between them with no recompiles).
        Returns (inputs, labels, step)."""
        conv = lambda x: x if isinstance(x, jax.ShapeDtypeStruct) \
            else jnp.asarray(x)  # noqa: E731
        inputs = jax.tree_util.tree_map(conv, inputs)
        labels = jax.tree_util.tree_map(conv, labels)
        in_specs = jax.tree_util.tree_map(self._leaf_spec, inputs)
        lb_specs = jax.tree_util.tree_map(self._leaf_spec, labels)
        if place:
            inputs = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(self.mesh, s)), inputs, in_specs)
            labels = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(self.mesh, s)), labels, lb_specs)
        cache_key = (jax.tree_util.tree_structure((inputs, labels)),
                     tuple(_rank(l) for l in jax.tree_util.tree_leaves(
                         (inputs, labels))),
                     bool(do_check))
        step = self._step_cache.get(cache_key)
        self._last_stage_miss = step is None
        if step is None:
            t0 = time.perf_counter()
            # "stage" rides as a child of the runner's ambient step span
            # (if one is open): staged-program builds show up inside the
            # step that paid for them. The per-bucket exchange plan is
            # recorded as events here — the exchanges themselves run
            # inside the jitted program, invisible to host-side spans.
            sp = _tracing.child_span("stage", check=bool(do_check))
            try:
                step = self._make_step(in_specs, lb_specs, do_check)
            finally:
                if sp is not None:
                    for i, bk in enumerate(
                            getattr(self, "grad_sync_bucket_keys", [])):
                        sp.event(
                            "exchange_bucket", bucket=i, leaves=len(bk),
                            bytes=int(sum(
                                self.state["params"][k].nbytes
                                for k in bk)))
                    sp.end("ok", cache_miss=True)
            self._step_cache[cache_key] = step
            if _telemetry.enabled():
                _telemetry.counter(
                    "recompiles_total",
                    "train-step stagings (cache misses) + jit shape "
                    "recompiles").inc()
                _telemetry.histogram(
                    "stage_time_seconds",
                    "wall time building a step for a new batch "
                    "structure").observe(time.perf_counter() - t0)
                self._step_costs[cache_key] = self._trace_step_cost(
                    step, inputs, labels)
        self._last_cache_key = cache_key
        return inputs, labels, step

    def _trace_step_cost(self, step, inputs, labels):
        """Static per-step cost of the EXACT staged jaxpr — donation mask,
        comm_err plumbing and all — via analysis.cost. Telemetry-only:
        traces with ShapeDtypeStructs (no rng draw, nothing executed) and
        never raises into the training path."""
        try:
            from ..analysis import cost as _cost
            to_struct = lambda x: (jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
                                   if hasattr(x, "shape") and
                                   hasattr(x, "dtype") else x)
            key_aval = jax.eval_shape(lambda: jax.random.key(0))
            args = jax.tree_util.tree_map(to_struct, (
                self.state["params"], self.state["buffers"],
                self.state["opt"], self.state["comm_err"],
                self.state["guard"]))
            lr = float(self.optimizer.get_lr())
            closed = jax.make_jaxpr(lambda *a: step(*a))(
                *args, key_aval, lr, 1.0,
                jax.tree_util.tree_map(to_struct, inputs),
                jax.tree_util.tree_map(to_struct, labels))
            donated = sum(
                getattr(v, "nbytes", 0)
                for part in (self.state["params"], self.state["opt"],
                             self.state["comm_err"], self.state["guard"])
                for v in jax.tree_util.tree_leaves(part))
            out = {"flops": _cost.total_flops(closed),
                   "peak_live_bytes": _cost.peak_live_bytes(closed),
                   "donated_bytes": float(donated)}
            try:
                out["overlap"] = _cost.overlap_summary(closed, self.mesh)
            except Exception:
                out["overlap"] = None
            return out
        except Exception:
            return None

    # -- staging / analysis -------------------------------------------------
    def compile(self, inputs, labels, lr: Optional[float] = None,
                analyze: bool = False, config=None):
        """Stage the jitted train step for this batch shape without
        running it. Returns the step function; with ``analyze=True``
        returns ``(step, Report)`` where the Report comes from tracing
        the EXACT staged step — donation mask, comm_err / compressed
        grad-sync plumbing and all — through paddle_tpu.analysis.

        ``inputs``/``labels`` may be real arrays or ShapeDtypeStructs
        (nothing is materialized or executed either way)."""
        t0 = time.perf_counter()
        inputs, labels, step = self._stage(inputs, labels, place=False)
        if _telemetry.enabled():
            _telemetry.histogram(
                "compile_time_seconds",
                "ParallelTrainer.compile wall time").observe(
                    time.perf_counter() - t0)
        if not analyze:
            return step
        import dataclasses

        from .. import analysis
        # declare the exchange mode to the overlap rule: 0 means the
        # caller didn't say, so inject the trainer's own effective bucket
        # count (an explicit non-zero value in the config wins)
        cfg = config or analysis.AnalysisConfig()
        if getattr(cfg, "grad_sync_buckets", 0) == 0:
            eff = (len(self.grad_sync_bucket_keys)
                   if getattr(self, "_use_buckets", False) else 1)
            cfg = dataclasses.replace(cfg, grad_sync_buckets=eff)
        config = cfg
        closed, donated = self._staged_jaxpr(step, inputs, labels, lr)
        in_specs = None
        try:
            in_specs = self.staged_in_specs(inputs, labels)
            if len(in_specs) != len(closed.jaxpr.invars):
                in_specs = None   # tree drift: better silent than wrong
        except Exception:
            in_specs = None
        report = analysis.analyze_jaxpr(closed, mesh=self.mesh,
                                        donated=donated, config=config,
                                        in_specs=in_specs)
        if _telemetry.enabled():
            ov = getattr(report.cost, "overlap", None) or {}
            if "n_reshard" in ov:
                _telemetry.gauge(
                    "predicted_reshard_collectives",
                    "implicit resharding collectives the sharding pass "
                    "predicts in the staged step").set(ov["n_reshard"])
                _telemetry.gauge(
                    "predicted_reshard_seconds",
                    "modeled per-step wall seconds of implicit "
                    "resharding").set(ov["reshard_time"])
        return step, report

    def staged_in_specs(self, inputs, labels):
        """One PartitionSpec per flat invar of :meth:`staged_jaxpr`'s
        ClosedJaxpr, in tracing order — the seed the static
        sharding-propagation pass (analysis/sharding.py) needs to
        predict implicit resharding from the exact staged step."""
        def flat(part, spec_tree=None):
            leaves, treedef = jax.tree_util.tree_flatten(part)
            if spec_tree is None:
                return [P()] * len(leaves)
            try:
                return list(treedef.flatten_up_to(spec_tree))
            except ValueError:
                # node-type mismatch (dict vs OrderedDict spec trees):
                # align per leaf by key path, in part's own flatten order
                paths = jax.tree_util.tree_flatten_with_path(part)[0]
                out = []
                for path, _ in paths:
                    node = spec_tree
                    for e in path:
                        node = node[e.key if hasattr(e, "key") else e.idx]
                    out.append(node)
                return out
        specs = []
        specs += flat(self.state["params"], self.param_specs)
        specs += flat(self.state["buffers"], self.buffer_specs)
        specs += flat(self.state["opt"], self.opt_specs)
        specs += flat(self.state["comm_err"], self.comm_err_specs)
        specs += flat(self.state["guard"])
        specs += [P(), P(), P()]   # rng key, lr, grad-taint scalar
        specs += flat(inputs, jax.tree_util.tree_map(self._leaf_spec,
                                                     inputs))
        specs += flat(labels, jax.tree_util.tree_map(self._leaf_spec,
                                                     labels))
        return specs

    def _staged_jaxpr(self, step, inputs, labels, lr=None):
        """Trace the staged ``step`` to a ClosedJaxpr with this trainer's
        live state as abstract operands. Returns ``(closed, donated)``
        where ``donated`` is the flat invar index set of jit's
        ``donate_argnums``."""
        from ..framework.random import get_rng_key
        lr = self.optimizer.get_lr() if lr is None else lr
        args = (self.state["params"], self.state["buffers"],
                self.state["opt"], self.state["comm_err"],
                self.state["guard"], get_rng_key(), lr, 1.0, inputs, labels)
        closed = jax.make_jaxpr(lambda *a: step(*a))(*args)
        # flat invar indices of jit's donate_argnums=(0, 2, 3, 4)
        donated, off = set(), 0
        for i, a in enumerate(args):
            n = len(jax.tree_util.tree_leaves(a))
            if i in (0, 2, 3, 4):
                donated.update(range(off, off + n))
            off += n
        return closed, donated

    def staged_jaxpr(self, inputs, labels, lr=None, do_check=False):
        """Public tracing hook for tools: stage the train step for this
        batch shape and return its ClosedJaxpr (nothing executed).
        ``do_check=True`` traces the integrity-check program variant."""
        inputs, labels, step = self._stage(inputs, labels, place=False,
                                           do_check=do_check)
        closed, _ = self._staged_jaxpr(step, inputs, labels, lr)
        return closed

    def program_family(self, inputs, labels, lr=None):
        """The integrity do_check pair as a declared
        :class:`~paddle_tpu.analysis.schedule.ProgramFamily`: train_step
        picks between the plain and fingerprint-check programs with
        ``self._steps_run % integrity_check_every`` — a host-replicated
        step counter, so the selection is rank-invariant by
        construction. The schedule verifier checks both members are
        individually hang-free."""
        from ..analysis.schedule import ProgramFamily
        return ProgramFamily(
            name="trainer-step",
            selector="steps_run % integrity_check_every "
                     "(host-replicated step counter)",
            rank_invariant=True,
            members={
                "step": lambda: self.staged_jaxpr(inputs, labels, lr),
                "step-check": lambda: self.staged_jaxpr(
                    inputs, labels, lr, do_check=True),
            },
            mesh=self.mesh)

    # -- run ----------------------------------------------------------------
    def train_step(self, inputs, labels, lr: Optional[float] = None,
                   grad_taint: Optional[float] = None):
        """One jitted step. ``grad_taint`` is the fault-injection operand:
        a scalar multiplied into one gradient leaf inside the step (NaN
        poisons the step; the in-graph guard must then skip the update).
        Normal callers leave it None."""
        key = get_rng_key()
        lr = self.optimizer.get_lr() if lr is None else lr
        leaves = jax.tree_util.tree_leaves(inputs)
        batch0 = jnp.shape(leaves[0])[0] if leaves and \
            len(jnp.shape(leaves[0])) else None
        if self.accumulate_steps > 1 and batch0 is not None and \
                batch0 % self.accumulate_steps != 0:
            raise ValueError(
                f"batch size {batch0} is not divisible by "
                f"accumulate_steps={self.accumulate_steps}")
        # inputs/labels may be arbitrary pytrees (e.g. (mlm, nsp) labels)
        tel = _telemetry.enabled()
        t_start = time.perf_counter() if tel else 0.0
        # integrity cadence: host-side choice between the two cached
        # programs (no recompile, no in-graph branch on the step count)
        self._steps_run += 1
        ce = self.integrity_check_every
        do_check = bool(ce) and self._steps_run % ce == 0
        inputs, labels, step = self._stage(inputs, labels,
                                           do_check=do_check)
        # Host range for the profiler/chrome trace; the telemetry counter
        # track is aligned against these. Skipped entirely (no object,
        # no named_scope) when the profiler is off.
        ev = (_profiler.RecordEvent("train_step").begin()
              if _profiler.is_profiler_enabled() else None)
        n_compiled0 = self._jit_cache_size(step) if tel else None
        taint = 1.0 if grad_taint is None else float(grad_taint)
        loss, new_params, new_opt, new_comm_err, new_guard, integ = step(
            self.state["params"], self.state["buffers"], self.state["opt"],
            self.state["comm_err"], self.state["guard"], key, lr, taint,
            inputs, labels)
        if tel or ev is not None:
            # the documented telemetry sync point: step wall time includes
            # device execution (loss is the last value the step produces)
            jax.block_until_ready(loss)
        if ev is not None:
            ev.end()
        self.state["params"] = new_params
        self.state["opt"] = new_opt
        self.state["comm_err"] = new_comm_err
        self.state["guard"] = new_guard
        if integ is not None:
            self._record_integrity(integ)
        if tel:
            self._record_step_telemetry(
                time.perf_counter() - t_start, inputs, step, n_compiled0)
        from ..framework import flags as _flags
        if _flags.flag("check_nan_inf"):
            _flags.check_numerics({"loss": loss}, "train_step:")
            _flags.check_numerics(new_params, "params:")
        if _flags.flag("check_replication"):
            self.check_replication()
        if _flags.flag("benchmark"):
            jax.block_until_ready(loss)
        return loss

    def _record_integrity(self, integ):
        """Host side of the check step: pull the tiny divergence mask
        (len == compared leaves, int32) and remember which leaves'
        fingerprints disagreed across data ranks. The fingerprints
        themselves stay on device unless someone asks
        (``last_fingerprints``)."""
        fps, div = integ
        self.last_fingerprints = fps
        mask = np.asarray(jax.device_get(div)).reshape(-1)
        names = [self._integrity_entries[i][0]
                 for i in self._integrity_cmp_idx]
        diverged = [n for n, m in zip(names, mask) if int(m)]
        self.last_divergence = diverged
        if _telemetry.enabled():
            _telemetry.counter(
                "integrity_check_steps_total",
                "train steps that ran the fingerprint check program"
            ).inc()
            if diverged:
                c = _telemetry.counter(
                    "replica_divergence_total",
                    "integrity checks where a leaf's fingerprint "
                    "differed across data-parallel ranks")
                for n in diverged:
                    c.inc(leaf=n)
        return diverged

    def consume_divergence(self) -> list:
        """Divergent leaf names from the most recent check step, cleared
        on read — run_resilient polls this after every step and converts
        a non-empty answer into quarantine + rollback."""
        out, self.last_divergence = self.last_divergence, []
        return out

    @staticmethod
    def _jit_cache_size(step):
        """Compiled-executable count of a jitted step (None if this jax
        doesn't expose it). Lets the recompile counter catch SHAPE misses
        — same batch structure/ranks, so a _step_cache hit, but jit still
        retraces — not just staging misses."""
        try:
            return step._cache_size()
        except Exception:
            return None

    def _record_step_telemetry(self, dt, inputs, step, n_compiled0):
        """Host-side per-step metrics (telemetry enabled only)."""
        _telemetry.histogram(
            "step_time_seconds",
            "train_step wall time incl. device execution").observe(dt)
        if not self._last_stage_miss and n_compiled0 is not None:
            n1 = self._jit_cache_size(step)
            if n1 is not None and n1 > n_compiled0:
                _telemetry.counter(
                    "recompiles_total",
                    "train-step stagings (cache misses) + jit shape "
                    "recompiles").inc()
        tokens = None
        leaves = jax.tree_util.tree_leaves(inputs)
        if leaves:
            shape = jnp.shape(leaves[0])
            if len(shape) >= 2:
                tokens = int(shape[0]) * int(shape[1])
            elif len(shape) == 1:
                tokens = int(shape[0])
        tps = None
        if tokens and dt > 0:
            tps = tokens / dt
            _telemetry.gauge(
                "tokens_per_sec",
                "elements of the lead input's first two dims per "
                "second").set(tps)
        mfu = None
        cost = self._step_costs.get(self._last_cache_key)
        if cost:
            if cost["flops"] and dt > 0:
                mfu = cost["flops"] / dt / _telemetry.peak_flops_per_sec()
                _telemetry.gauge(
                    "mfu", "model FLOPs utilization: analysis.cost FLOPs "
                    "of the staged step / wall time / hardware peak"
                ).set(mfu)
            _telemetry.gauge(
                "peak_live_bytes", "liveness-scan peak working set of the "
                "staged step jaxpr").set(cost["peak_live_bytes"])
            _telemetry.gauge(
                "donated_bytes", "bytes of donated state "
                "(params + opt + comm_err)").set(cost["donated_bytes"])
        if self._wire_bytes_per_step:
            wire = _telemetry.counter(
                "grad_sync_bytes_total",
                "logical wire bytes per rank of the bucketed grad "
                "exchange, per exchange group")
            for pol, link, bucket, b in self._wire_parts:
                if b:
                    wire.inc(b, policy=pol, link=link, bucket=bucket)
            if self._wire_bytes_per_step > 0:
                _telemetry.gauge(
                    "grad_sync_compression_x",
                    "fp32 wire bytes / policy wire bytes").set(
                        self._wire_fp32_per_step /
                        self._wire_bytes_per_step)
        if cost and cost.get("overlap") and \
                cost["overlap"].get("overlap_efficiency") is not None:
            _telemetry.gauge(
                "grad_sync_overlap_efficiency",
                "fraction of the staged step's collective time the "
                "overlap model predicts is hidden under compute").set(
                    cost["overlap"]["overlap_efficiency"])
        if cost and cost.get("overlap") and dt > 0:
            makespan = cost["overlap"].get("makespan")
            if makespan:
                # predicted-vs-measured step time (telemetry.calibration):
                # the overlap model's makespan of the staged step vs this
                # step's wall clock
                _telemetry.calibration.record(
                    "step_time", makespan, dt, step=step)
        res = None
        if self.state["comm_err"]:
            from .compressed import residual_norm
            try:
                res = residual_norm(self.state["comm_err"])
                _telemetry.gauge(
                    "grad_sync_residual_norm",
                    "L2 norm of the int8 error-feedback residual").set(res)
            except Exception:
                res = None
        _telemetry.emit(
            "step", step_time=dt,
            **{k: v for k, v in (("tokens_per_sec", tps), ("mfu", mfu),
                                 ("residual_norm", res)) if v is not None})

    def check_replication(self):
        """Debug aid (FLAGS_check_replication): assert every param whose
        spec declares full replication is bit-identical on all devices —
        the runtime form of the invariant that shard_map's check_vma would
        check statically (see the check_vma note in make_step)."""
        import numpy as np
        for k, spec in self.param_specs.items():
            if any(ax is not None for ax in spec):
                continue
            v = self.state["params"][k]
            shards = v.addressable_shards
            base = np.asarray(shards[0].data)
            for s in shards[1:]:
                if not np.array_equal(base, np.asarray(s.data)):
                    raise AssertionError(
                        f"param {k!r} declared replicated but devices "
                        f"{shards[0].device} and {s.device} disagree")

    def skipped_steps(self) -> int:
        """Steps the in-graph NaN guard skipped so far (ONE host sync —
        call at checkpoint/summary boundaries, not per step)."""
        return int(jax.device_get(self.state["guard"]["skipped"]))

    # -- live-state access (HeterPS hot-tier insert/evict between steps) ----
    def param_name_of(self, box) -> Optional[str]:
        """Full name of a model Parameter by identity (None if absent)."""
        for n, b in self.model.named_parameters():
            if b is box:
                return n
        return None

    def get_param(self, name):
        return self.state["params"][name]

    def set_param(self, name, value):
        """Replace a parameter in live state, preserving its sharding."""
        self.state["params"][name] = jax.device_put(
            value, NamedSharding(self.mesh, self.param_specs[name]))

    def get_opt_slot(self, name, slot):
        """Optimizer slot array for a param (None when the optimizer
        keeps no such slot or nests them — wrapper optimizers)."""
        try:
            v = self.state["opt"]["slots"][name][slot]
            return v if hasattr(v, "shape") else None
        except (KeyError, TypeError):
            return None

    def opt_slot_names(self, name):
        """Names of the flat optimizer slot arrays kept for a param."""
        try:
            d = self.state["opt"]["slots"][name]
            return [k for k, v in d.items() if hasattr(v, "shape")]
        except (KeyError, TypeError):
            return []

    def set_opt_slot(self, name, slot, value):
        spec = self.opt_specs["slots"][name][slot]
        self.state["opt"]["slots"][name][slot] = jax.device_put(
            value, NamedSharding(self.mesh, spec))

    def sync_to_model(self):
        boxes = OrderedDict(self.model.named_parameters())
        for n, v in self.state["params"].items():
            if n in boxes:
                boxes[n].value = v

    # -- checkpoint ---------------------------------------------------------
    def save_checkpoint(self, path: str, use_async: bool = False):
        """Sharded save of {params, buffers, opt} — each shard written from
        the device/host holding it (reference capability: per-rank sharded
        save, dist_sharding_save.py test)."""
        from .checkpoint import save_checkpoint as _save
        return _save(path, self.state, use_async=use_async)

    def load_checkpoint(self, path: str):
        """Restore state with the trainer's own shardings (mesh-keyed)."""
        from .checkpoint import load_checkpoint as _load
        self.state = _load(path, template=self.state)
        return self.state

    # -- elastic remesh -----------------------------------------------------
    def remesh(self, mesh):
        """Rebuild specs, state placement, and the jitted step programs on
        a new mesh (elastic scale-up/down: the healthy host set changed and
        build_mesh produced a different device array). State re-initializes
        FRESH from the model/optimizer — carrying trained state across
        meshes is the caller's job via the sharded checkpoint
        (resilience.elastic.reshard_trainer: save on the old mesh, restore
        on the new one, remap the comm_err residuals whose replica
        dimension follows the mesh)."""
        from .mesh import set_mesh
        self.mesh = mesh
        set_mesh(mesh)
        self._init_state()
        self._build()
        if hasattr(self.model, "named_sublayers"):
            for _, sub in self.model.named_sublayers(include_self=True):
                hook = getattr(sub, "_on_trainer_built", None)
                if hook is not None:
                    hook(self)
        return self
