"""Serving bench: synthetic Poisson open-loop traffic against the
inference serving runtime (paddle_tpu/inference/serving.py).

One server (two Predictor replicas over a jit.save'd MLP, per-prefix
load cache shared) is driven through four phases and ONE JSON line is
printed:

- **warmup** — sequential requests at every batch-bucket size, so the
  compiled-program set is established (``serving_recompiles_total``
  recorded here must NOT grow afterwards — shape buckets closed).
- **baseline** — Poisson arrivals at 0.5x nominal capacity: the
  no-overload goodput / latency reference.
- **overload** — Poisson at 2x capacity: admission control must shed
  (``requests_shed_total > 0``) while the p99 latency of requests that
  completed stays within the deadline, and in-deadline goodput stays
  within a bounded band of the baseline run.
- **failover** — a ``replica_stall`` fault wedges one replica mid-run:
  the per-call deadline fires, the batch requeues to the survivor
  (``replica_failover_total >= 1``), and ZERO admitted-and-feasible
  requests are silently lost — every submitted request terminates as
  completed / shed / expired (``accounted``).
- **decode** — prefix-heavy Poisson generations against the
  DecodeServer + paged KV cache (toy autoregressive LM): every prompt
  shares a 32-token system prefix, so after one warm-up generation the
  prefill tokens actually computed must be <= 0.5x the no-sharing
  baseline at a ``kv_cache_hit_rate`` >= 0.5, the pool is sized so LRU
  eviction fires, and — with every (token-bucket, row-bucket) executor
  shape compiled up front — serving traffic triggers zero fresh
  compiles no matter how decode steps coalesce. Reports decode
  tokens/sec goodput under the deadline contract.
- **fleet** — a 2-member ``ServingFleet`` over the same artifact with
  the persistent compiled-executor warm set pre-seeded: a 1.8x burst
  must autoscale the fleet up, a live hot-swap under traffic must
  canary-promote a republished model generation with zero lost
  requests, and no member ever — bootstrap, autoscaled, canary, or
  rolled — pays a compile cold start (fleet-wide
  ``serving_recompiles_total == 0``). Reports the hot-swap rollout
  wall time.
- **spec-decode** — the same paged-KV stack behind
  ``SpeculativeDecodeServer``: an n-gram drafter proposes K = 4 tokens
  per decode step, verify rides the batcher as a 1 + K-token chunk, and
  decode tokens per target-model step must reach >= 1.5x the
  non-speculative phase (1.0 by construction) with ``dense_generate``
  exactness, a still-closed compiled-shape set, and zero leaked pages
  after drain.

Capacity is made deterministic on any machine by padding each batch
execute with a fixed service time (the model itself is tiny), so
"2x capacity" means the same thing in CI and on a workstation.

Usage::

    python tools/bench_serving.py            # full (longer phases)
    python tools/bench_serving.py --smoke    # CI contract (~20 s)

    {"metric": "serving_overload_goodput_rps", "value": ...,
     "extra": {"requests_shed_total": ..., "replica_failover_total": ...,
               "serving_recompiles_total": {"closed": true, ...}, ...}}
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

from _mesh_setup import ensure_repo_on_path, force_host_devices

ensure_repo_on_path()
force_host_devices(1)

IN_DIM = 32


def build_model(tmp: str):
    """jit.save a tiny MLP with a shape-polymorphic batch dim (the
    serving batcher pads rows to a small bucket set)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit import InputSpec

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(IN_DIM, 64)
            self.fc2 = nn.Linear(64, 8)

        def forward(self, x):
            return self.fc2(nn.functional.relu(self.fc1(x)))

    paddle.seed(0)
    net = MLP()
    net.eval()
    prefix = tmp + "/model"
    paddle.jit.save(net, prefix, input_spec=[InputSpec([None, IN_DIM],
                                                       "float32")])
    return prefix


def make_executor(pred, pad_s: float):
    """Predictor executor with a fixed per-batch service pad, so nominal
    capacity (= replicas * max_batch / pad) is machine-independent."""

    def fn(arrays):
        out = pred.run(list(arrays))
        time.sleep(pad_s)
        return out

    return fn


def make_step_executor(step, pad_s: float):
    """Same fixed service pad around a DecodeServer step executor."""

    def fn(arrays):
        out = step(list(arrays))
        time.sleep(pad_s)
        return out

    return fn


def _diff(before: dict, after: dict) -> dict:
    out = {}
    for k, v in after.items():
        if isinstance(v, (int, float)) and isinstance(before.get(k), (int, float)):
            out[k] = v - before[k]
    for cause in set(after["shed_causes"]) | set(before["shed_causes"]):
        out.setdefault("shed_causes", {})[cause] = (
            after["shed_causes"].get(cause, 0)
            - before["shed_causes"].get(cause, 0))
    return out


def run_phase(server, rate_rps: float, duration_s: float,
              deadline_s: float, rng) -> dict:
    """Open-loop Poisson traffic: arrivals are scheduled independently
    of completions (the defining property of an overload test — a
    closed loop would self-throttle)."""
    before = server.stats()
    reqs = []
    t0 = time.monotonic()
    next_t = t0
    while True:
        next_t += rng.exponential(1.0 / rate_rps)
        if next_t - t0 > duration_s:
            break
        lag = next_t - time.monotonic()
        if lag > 0:
            time.sleep(lag)
        x = rng.rand(1, IN_DIM).astype("float32")
        reqs.append(server.submit([x], deadline_s=deadline_s))
    elapsed = time.monotonic() - t0
    settle = time.monotonic() + deadline_s + 10.0
    for r in reqs:
        r._done.wait(max(0.0, settle - time.monotonic()))
    delta = _diff(before, server.stats())
    lat = sorted(r.latency for r in reqs
                 if r.state == "completed" and r.latency is not None)
    in_deadline = sum(1 for r in reqs if r.state == "completed"
                      and r.latency is not None and r.latency <= deadline_s)

    def pct(p):
        return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else None

    return {
        "offered_rps": round(len(reqs) / elapsed, 1),
        "duration_s": round(elapsed, 3),
        "submitted": len(reqs),
        "completed": delta.get("completed", 0),
        "shed": delta.get("shed", 0),
        "expired": delta.get("expired", 0),
        "failed": delta.get("failed", 0),
        "shed_causes": delta.get("shed_causes", {}),
        "failovers": delta.get("failovers", 0),
        "deadline_s": deadline_s,
        "p50_s": pct(0.50),
        "p99_s": pct(0.99),
        "goodput_rps": round(in_deadline / elapsed, 1),
    }


def _prime_decode_shapes(step, width: int, token_buckets, rows_cap: int):
    """Compile every (token-bucket, row-bucket) shape of ALL executor
    paths — ragged mixed prefill, pure decode, and the rectangular
    uniform-extension (speculative-verify / same-width-prefill) repack —
    before serving starts, so traffic triggers zero fresh compiles
    regardless of how decode steps coalesce into batches."""
    for t_b in token_buckets:
        r_b = min(t_b, rows_cap)
        tables = np.zeros((r_b, width), np.int32)
        # uniform extension shape: one cold row owning every token —
        # for t_b >= 2 this repacks to the (r_b, t_b) verify rectangle
        step([np.zeros(t_b, np.int32), np.zeros(t_b, np.int32),
              np.arange(t_b, dtype=np.int32), np.ones(t_b, np.int32),
              tables, np.zeros(r_b, np.int32), np.zeros(r_b, np.int32)])
        if t_b >= 2:
            # ragged mixed shape: a 1-token decode row sharing the batch
            # with a cold (t_b - 1)-token prefill row — non-uniform
            # counts force the flattened varlen path
            row_id = np.ones(t_b, np.int32)
            row_id[0] = 0
            ctx = np.zeros(r_b, np.int32)
            ctx[0] = 1
            last = np.zeros(r_b, np.int32)
            last[1] = t_b - 1
            step([np.zeros(t_b, np.int32), row_id,
                  np.arange(t_b, dtype=np.int32), np.ones(t_b, np.int32),
                  tables, ctx, last])
        # pure-decode shape: r_b rows with context, one token each
        valid = np.zeros(t_b, np.int32)
        valid[:r_b] = 1
        row_id = np.zeros(t_b, np.int32)
        row_id[:r_b] = np.arange(r_b)
        step([np.zeros(t_b, np.int32), row_id, np.ones(t_b, np.int32),
              valid, tables, np.ones(r_b, np.int32),
              np.arange(r_b, dtype=np.int32)])


def _prime_spec_shapes(step, width: int, rows_cap: int, spec_k: int):
    """Compile the speculative-verify rectangle: a 1 + K chunk lands in
    the bucketed (token, row) shape like any prefill chunk, and the
    executor repacks it to (R, S) — prime that path so spec traffic
    triggers zero fresh compiles."""
    chunk = 1 + spec_k
    t_b = 1 << (chunk - 1).bit_length()
    r_b = min(t_b, rows_cap)
    tables = np.zeros((r_b, width), np.int32)
    valid = np.zeros(t_b, np.int32)
    valid[:chunk] = 1
    ctx = np.zeros(r_b, np.int32)
    ctx[0] = 1
    step([np.zeros(t_b, np.int32), np.zeros(t_b, np.int32),
          np.arange(t_b, dtype=np.int32), valid, tables, ctx,
          np.zeros(r_b, np.int32)])


def run_spec_decode_bench(smoke: bool, seed: int) -> dict:
    """Speculative-decode phase: the same paged-KV serving stack with an
    n-gram drafter proposing K = 4 tokens per decode step, verified in
    one target-model step each. The workload is prefix-heavy AND
    repetitive (the toy LM's greedy stream falls into short cycles), so
    drafting pays; ``dense_generate`` is the exactness oracle — every
    completed generation must match plain greedy token-for-token. The
    acceptance bar is decode tokens per target-model step >= 1.5x the
    non-speculative decode phase (which is 1.0 by construction)."""
    from paddle_tpu.inference import serving, spec_decode
    from paddle_tpu.inference.decode_model import (dense_generate,
                                                   init_decode_model,
                                                   make_step_fn)
    from paddle_tpu.inference.kv_cache import PagedKVCache

    heads, head_dim, page_size = 2, 32, 16
    num_pages, width = 32, 4
    replicas, token_budget, rows_cap = 2, 8, 4
    spec_k, max_new = 4, 24
    pad_s = 0.01
    rate_rps = 6.0 if smoke else 8.0
    duration = 1.5 if smoke else 4.0
    deadline_s = 8.0

    # vocab 32 drives the toy LM's greedy stream into short cycles the
    # n-gram drafter locks onto — the CPU proxy for repetitive text
    params = init_decode_model(vocab=32, num_heads=heads,
                               head_dim=head_dim, seed=5)
    rng = np.random.RandomState(seed + 29)
    system = [int(t) for t in rng.randint(0, 32, 2 * page_size)]
    variants = [system + [int(t) for t in
                          np.random.RandomState(1000 + i).randint(0, 32, 8)]
                for i in range(6)]
    oracle = {i: dense_generate(params, v, max_new)
              for i, v in enumerate(variants)}

    cache = PagedKVCache(num_pages, page_size, heads, head_dim)
    step = make_step_fn(params, cache)
    _prime_decode_shapes(step, width, (1, 2, 4, 8), rows_cap)
    _prime_spec_shapes(step, width, rows_cap, spec_k)
    jits_primed = sum(f._cache_size() for f in step.jit_fns)

    cfg = serving.ServingConfig(
        max_queue=8, max_batch=token_budget, batch_wait_s=0.002,
        call_timeout_s=3.0, admission_safety=1.3, seed=seed)
    server = spec_decode.SpeculativeDecodeServer(
        make_step_executor(step, pad_s), cache,
        drafter=spec_decode.NGramDrafter(), spec_k=spec_k,
        replicas=replicas, config=cfg, prefill_chunk=8,
        max_pages_per_seq=width, max_batch_rows=rows_cap)

    with server:
        server.submit_generate(variants[0], max_new,
                               deadline_s=60.0).result(timeout=120)

        reqs = []
        t0 = time.monotonic()
        next_t, i = t0, 0
        while True:
            next_t += rng.exponential(1.0 / rate_rps)
            if next_t - t0 > duration:
                break
            lag = next_t - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            i += 1
            reqs.append((i % len(variants),
                         server.submit_generate(variants[i % len(variants)],
                                                max_new,
                                                deadline_s=deadline_s)))
        elapsed = time.monotonic() - t0
        settle = time.monotonic() + deadline_s + 20.0
        for _, r in reqs:
            r._done.wait(max(0.0, settle - time.monotonic()))
        stats = server.stats()
        accounted = server.accounted()
        server.shutdown(drain=True)

    jits_final = sum(f._cache_size() for f in step.jit_fns)
    cstats = cache.stats()
    spec = stats["spec_decode"]
    completed = [(v, r) for v, r in reqs if r.state == "completed"]
    exact = all([int(t) for t in r.outputs[0]] == oracle[v]
                for v, r in completed)
    by_state = {}
    for _, r in reqs:
        by_state[r.state] = by_state.get(r.state, 0) + 1
    checks = {
        "spec_exact_vs_dense": exact and len(completed) > 0,
        "spec_tokens_per_step": spec["tokens_per_target_step"] >= 1.5,
        "spec_accepts_drafts": spec["accepted_tokens"] > 0,
        "spec_compiled_set_closed": jits_final == jits_primed,
        "spec_zero_lost": (accounted and by_state.get("failed", 0) == 0
                           and stats["failed"] == 0),
        # nothing pinned after drain: every used page is evictable
        # prefix registry — the same baseline state the warm generation
        # left behind (no leaked sequence or draft-fork references)
        "spec_pages_drained": cstats["pages_used"] == cstats["evictable"],
    }
    return {
        "tokens_per_target_step": round(spec["tokens_per_target_step"], 3),
        "nonspec_tokens_per_target_step": 1.0,
        "accept_rate": round(spec["accept_rate"], 4),
        "draft_tokens": spec["draft_tokens"],
        "accepted_tokens": spec["accepted_tokens"],
        "verify_steps": spec["verify_steps"],
        "spec_k": spec_k,
        "offered_rps": round(len(reqs) / elapsed, 1),
        "duration_s": round(elapsed, 3),
        "submitted": len(reqs),
        "completed": by_state.get("completed", 0),
        "shed": by_state.get("shed", 0),
        "expired": by_state.get("expired", 0),
        "failed": by_state.get("failed", 0),
        "decode_tokens": stats["decode_tokens"],
        "jit_shapes": {"primed": jits_primed, "final": jits_final},
        "recompiles": stats["recompiles"],
        "kv_cache": cstats,
        "checks": checks,
    }


def run_decode_bench(smoke: bool, seed: int) -> dict:
    """Prefix-heavy decode phase: Poisson generations whose prompts
    share a 2-page system prefix. Returns the decode section of the
    bench record, with its own ``checks`` sub-dict."""
    from paddle_tpu.inference import serving
    from paddle_tpu.inference.decode_model import (init_decode_model,
                                                   make_step_fn)
    from paddle_tpu.inference.kv_cache import PagedKVCache

    heads, head_dim, page_size = 2, 32, 16
    num_pages, width = 20, 4           # width = max pages per sequence
    replicas, token_budget, rows_cap = 2, 8, 4
    pad_s = 0.01
    # prompt = 32 shared + 8 unique; 8 written decode tokens close the
    # 3rd page exactly, so each generation leaves ONE fresh registered
    # page behind — completed traffic fills the pool and forces eviction
    max_new = 9
    rate_rps = 16.0 if smoke else 20.0
    duration = 1.5 if smoke else 4.0
    deadline_s = 3.0

    rng = np.random.RandomState(seed + 17)
    system = [int(t) for t in rng.randint(0, 128, 2 * page_size)]

    def prompt(i):
        rs = np.random.RandomState(1000 + i)
        return system + [int(t) for t in rs.randint(0, 128, 8)]

    params = init_decode_model(vocab=128, num_heads=heads,
                               head_dim=head_dim, seed=1)
    cache = PagedKVCache(num_pages, page_size, heads, head_dim)
    step = make_step_fn(params, cache)
    _prime_decode_shapes(step, width, (1, 2, 4, 8), rows_cap)
    jits_primed = sum(f._cache_size() for f in step.jit_fns)

    cfg = serving.ServingConfig(
        max_queue=6, max_batch=token_budget, batch_wait_s=0.002,
        call_timeout_s=2.0, admission_safety=1.3, seed=seed)
    server = serving.DecodeServer(
        make_step_executor(step, pad_s), cache, replicas=replicas,
        config=cfg, prefill_chunk=8, max_pages_per_seq=width,
        max_batch_rows=rows_cap)

    with server:
        # warm-up: registers the shared system-prompt pages + the EWMA
        server.submit_generate(prompt(0), max_new,
                               deadline_s=30.0).result(timeout=120)
        ev0 = cache.evictions
        hits0 = cache.prefix_hit_tokens
        tok0 = server.stats()["decode_tokens"]

        reqs = []
        t0 = time.monotonic()
        next_t, i = t0, 0
        while True:
            next_t += rng.exponential(1.0 / rate_rps)
            if next_t - t0 > duration:
                break
            lag = next_t - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            i += 1
            reqs.append(server.submit_generate(prompt(i), max_new,
                                               deadline_s=deadline_s))
        elapsed = time.monotonic() - t0
        settle = time.monotonic() + deadline_s + 20.0
        for r in reqs:
            r._done.wait(max(0.0, settle - time.monotonic()))
        stats = server.stats()
        accounted = server.accounted()
        server.shutdown(drain=True)

    jits_final = sum(f._cache_size() for f in step.jit_fns)
    admitted = [r for r in reqs if r.seq is not None]
    prompt_tokens = sum(len(r.prompt) for r in admitted)
    hit_tokens = sum(r.seq.cached_tokens for r in admitted)
    prefill_computed = prompt_tokens - hit_tokens
    hit_rate = hit_tokens / max(1, prompt_tokens)
    in_deadline = [r for r in reqs if r.state == "completed"
                   and r.latency is not None and r.latency <= deadline_s]
    goodput_tps = sum(r.max_new for r in in_deadline) / elapsed
    lat = sorted(r.latency for r in reqs
                 if r.state == "completed" and r.latency is not None)

    def pct(p):
        return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else None

    by_state = {}
    for r in reqs:
        by_state[r.state] = by_state.get(r.state, 0) + 1
    checks = {
        "decode_goodput_positive": goodput_tps > 0,
        # acceptance: hit-rate >= 0.5 with prefill computed <= 0.5x the
        # no-sharing baseline (= every admitted prompt fully recomputed)
        "decode_hit_rate": hit_rate >= 0.5,
        "decode_prefill_halved": prefill_computed <= 0.5 * prompt_tokens,
        "decode_compiled_set_closed": jits_final == jits_primed,
        "decode_hit_accounting": (
            hit_tokens == cache.prefix_hit_tokens - hits0),
        "decode_evictions_exercised": cache.evictions - ev0 >= 1,
        "decode_zero_lost": (accounted and by_state.get("failed", 0) == 0
                             and stats["failed"] == 0),
    }
    return {
        "decode_goodput_tokens_per_s": round(goodput_tps, 1),
        "kv_cache_hit_rate": round(hit_rate, 4),
        "prefill_tokens_computed": prefill_computed,
        "prefill_tokens_no_sharing": prompt_tokens,
        "prefix_hit_tokens": hit_tokens,
        "offered_rps": round(len(reqs) / elapsed, 1),
        "duration_s": round(elapsed, 3),
        "submitted": len(reqs),
        "admitted": len(admitted),
        "completed": by_state.get("completed", 0),
        "shed": by_state.get("shed", 0),
        "expired": by_state.get("expired", 0),
        "failed": by_state.get("failed", 0),
        "decode_tokens": stats["decode_tokens"] - tok0,
        "evictions": cache.evictions - ev0,
        "deadline_s": deadline_s,
        "p50_s": pct(0.50),
        "p99_s": pct(0.99),
        "jit_shapes": {"primed": jits_primed, "final": jits_final},
        "recompiles": stats["recompiles"],
        "kv_cache": stats["kv_cache"],
        "checks": checks,
    }


def run_fleet_bench(smoke: bool, seed: int) -> dict:
    """Fleet phase: a 2-member ``ServingFleet`` over the same artifact,
    with the persistent compiled-executor warm set pre-seeded so every
    member — bootstrap and autoscaled alike — pays zero compile cold
    starts. A 1.8x burst must autoscale the fleet up (modeled wait /
    queue depth, via the background control thread), and a live
    hot-swap under traffic must promote a new model generation through
    the canary with zero lost requests; the rollout wall time is the
    reported metric."""
    import threading

    from paddle_tpu.inference import executor_cache as ec
    from paddle_tpu.inference import fleet as fleet_mod
    from paddle_tpu.inference import serving

    pad_s, max_batch, deadline_s = 0.02, 4, 0.4
    duration = 1.5 if smoke else 4.0
    capacity = 2 * max_batch / pad_s          # bootstrap nominal rows/s

    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    prefix = build_model(tmp)
    cache = ec.ExecutorCache(path=os.path.join(tmp, "exec_cache.json"))
    sig = (((IN_DIM,), "<f4"),)
    for bucket in (1, 2, 4):
        cache.record(ec.artifact_key(prefix, None), sig, bucket)

    def pad_wrap(fn):
        def wrapped(arrays):
            out = fn(arrays)
            time.sleep(pad_s)
            return out
        return wrapped

    scfg = serving.ServingConfig(
        max_queue=256, max_batch=max_batch, batch_wait_s=0.004,
        call_timeout_s=0.5, admission_safety=1.3, seed=seed)

    def make_gen(gen_id):
        return fleet_mod.predictor_generation(
            gen_id, prefix, serving=scfg, executor_cache=cache,
            executor_wrap=pad_wrap)

    cfg = fleet_mod.FleetConfig(
        min_members=2, max_members=3, cooldown_s=0.0,
        scale_up_wait_s=0.2, scale_up_queue_depth=16,
        scale_down_idle_s=1e9, canary_shadow_fraction=0.5,
        canary_min_shadow=4, canary_timeout_s=20.0, seed=seed)
    fleet = fleet_mod.ServingFleet(make_gen(0), config=cfg,
                                   fleet_id="bench")
    fleet.start(control=True)
    rng = np.random.RandomState(seed + 7)

    baseline = run_phase(fleet, 0.5 * capacity, duration, deadline_s, rng)
    burst = run_phase(fleet, 1.8 * capacity, duration, deadline_s, rng)
    members_after_burst = fleet.stats()["members"]

    # live hot-swap under light traffic (the canary's shadow source):
    # republish the artifact with scaled weights as generation 1
    import pickle
    with open(prefix + ".pdiparams", "rb") as fh:
        blob = pickle.load(fh)
    blob["params"] = {k: np.asarray(v) * 1.05
                      for k, v in blob["params"].items()}
    with open(prefix + ".pdiparams.tmp", "wb") as fh:
        pickle.dump(blob, fh)
    os.replace(prefix + ".pdiparams.tmp", prefix + ".pdiparams")

    stop = threading.Event()

    def pump():
        while not stop.is_set():
            try:
                fleet.submit([rng.rand(1, IN_DIM).astype("float32")],
                             deadline_s=5.0)
            except RuntimeError:
                pass
            time.sleep(0.02)

    th = threading.Thread(target=pump, daemon=True)
    th.start()
    t_swap = time.monotonic()
    promoted = fleet.hot_swap(make_gen(1))
    swap_s = time.monotonic() - t_swap
    member_gens = list(fleet.stats()["member_generations"])
    stop.set()
    th.join(timeout=5.0)

    fleet.shutdown(drain=True)
    accounted = fleet.accounted()     # post-drain: everything terminal
    st = fleet.stats()
    checks = {
        "fleet_goodput_positive": baseline["goodput_rps"] > 0,
        "fleet_scaled_up": st["scale_ups"] >= 1
        and members_after_burst >= 3,
        "fleet_hot_swap_promoted": bool(promoted)
        and set(member_gens) == {1},
        "fleet_zero_lost": accounted and st["failed"] == 0,
        "fleet_cold_starts_closed": st["recompiles"] == 0,
    }
    return {
        "hot_swap_rollout_s": round(swap_s, 3),
        "baseline": baseline,
        "burst": burst,
        "members_after_burst": members_after_burst,
        "scale_ups": st["scale_ups"],
        "promoted": st["promoted"],
        "member_generations_after_swap": member_gens,
        "servers_ever": st["servers_ever"],
        "submitted": st["submitted"],
        "completed": st["completed"],
        "shed": st["shed"],
        "recompiles": st["recompiles"],
        "accounted": accounted,
        "checks": checks,
    }


def run_bench(smoke: bool, seed: int = 0) -> dict:
    from paddle_tpu import inference, telemetry
    from paddle_tpu.inference import serving
    from paddle_tpu.resilience import faults
    from paddle_tpu.telemetry import flight, tracing

    telemetry.enable()
    replicas, max_batch = 2, 4
    pad_s = 0.04
    capacity = replicas * max_batch / pad_s          # nominal rows/sec
    deadline_s = 0.4
    duration = 1.5 if smoke else 6.0

    tmp = tempfile.mkdtemp(prefix="bench_serving_")
    flight.reset()
    flight.configure(tmp)      # drain at shutdown writes a dump here
    tracing.reset()            # start from a clean (disabled) tracer
    prefix = build_model(tmp)
    cfg = inference.Config(prefix)
    pool = inference.PredictorPool(cfg, replicas)
    scfg = serving.ServingConfig(
        max_queue=512, max_batch=max_batch, batch_wait_s=0.004,
        call_timeout_s=0.5, admission_safety=1.3, probation_base_s=0.05,
        probation_max_s=0.5, seed=seed)
    server = serving.InferenceServer(
        [make_executor(pool.retrieve(i), pad_s) for i in range(replicas)],
        config=scfg)
    rng = np.random.RandomState(seed)

    with server:
        # -- warmup: touch every batch bucket so the compiled set closes
        for rows in range(1, max_batch + 1):
            server.submit([rng.rand(rows, IN_DIM).astype("float32")],
                          deadline_s=30.0).result(timeout=60)
        recompiles_warm = server.stats()["recompiles"]

        baseline = run_phase(server, 0.5 * capacity, duration,
                             deadline_s, rng)
        # -- disabled path: no tracer work at all — the request carries
        # no trace object and the span counter never moves
        probe = server.submit([rng.rand(1, IN_DIM).astype("float32")],
                              deadline_s=30.0)
        probe.result(timeout=60)
        tracing_disabled_zero = (probe._trace is None
                                 and tracing.accounting()["recorded"] == 0)

        # -- tracing overhead: same rate as baseline with recording ON
        # but tail sampling keeping NOTHING — the always-on cost
        tracing.reset(policy=tracing.KeepPolicy(keep_none=True))
        tracing.enable()
        traced = run_phase(server, 0.5 * capacity, duration,
                           deadline_s, rng)
        acct_traced = tracing.accounting()
        tracing.disable()

        overload = run_phase(server, 2.0 * capacity, duration,
                             deadline_s, rng)

        # -- failover: wedge one replica a few batches into the phase,
        # with tail sampling armed — the failover must yield kept traces
        tracing.reset()
        tracing.enable()
        stall_at = server.stats()["batches"] + 4
        with faults.inject("replica_stall", at_step=stall_at) as spec:
            failover = run_phase(server, 0.6 * capacity,
                                 max(duration, 2.0), 2.0, rng)
        kept_failover = [t for t in tracing.snapshot_kept()
                         if t.get("keep_reason") == "failover"]
        traces_path = tracing.write_kept(
            os.path.join(tmp, "traces_kept.json"))
        trace_accounting_closed = tracing.accounted()
        tracing.disable()
        failover["stall_fired"] = spec.fired
        stats = server.stats()
        recompiles_final = stats["recompiles"]
        accounted = server.accounted()
        server.shutdown(drain=True)
    flight_dumps = list(flight.get_recorder().dumps)

    decode = run_decode_bench(smoke, seed)
    decode_checks = decode.pop("checks")
    spec = run_spec_decode_bench(smoke, seed)
    spec_checks = spec.pop("checks")
    fleet = run_fleet_bench(smoke, seed)
    fleet_checks = fleet.pop("checks")

    shed_total = (overload["shed"] + overload["expired"])
    goodput_band_ok = (
        baseline["goodput_rps"] > 0
        and overload["goodput_rps"] >= 0.5 * baseline["goodput_rps"])
    overhead = None
    if baseline["p50_s"] and traced["p50_s"]:
        overhead = (traced["p50_s"] - baseline["p50_s"]) / baseline["p50_s"]
    checks = {
        "overload_sheds": shed_total > 0,
        "overload_p99_within_deadline": (
            overload["p99_s"] is not None
            and overload["p99_s"] <= deadline_s),
        "goodput_band": goodput_band_ok,
        "failover_happened": failover["failovers"] >= 1
        and failover["stall_fired"] == 1,
        "zero_requests_lost": accounted and failover["failed"] == 0,
        "buckets_closed": recompiles_final == recompiles_warm,
        # tracing acceptance: always-on recording with nothing kept must
        # cost <= 3% p50, and the disabled path must allocate nothing
        "tracing_overhead_p50_within_3pct": (
            overhead is not None and overhead <= 0.03),
        "tracing_nothing_kept": (acct_traced["recorded"] > 0
                                 and acct_traced["kept"] == 0),
        "tracing_disabled_zero_span_alloc": tracing_disabled_zero,
        "failover_trace_kept": len(kept_failover) >= 1,
        "trace_accounting_closed": trace_accounting_closed,
    }
    checks.update(decode_checks)
    checks.update(spec_checks)
    checks.update(fleet_checks)
    from paddle_tpu.telemetry import calibration
    return {
        "schema_version": 2,
        "metric": "serving_overload_goodput_rps",
        "value": overload["goodput_rps"],
        "unit": "req/s",
        # admission's modeled wait vs the measured queue wait, most
        # recent pair (telemetry.calibration; schema_version 2)
        "calibration": calibration.pair("serving_queue_wait"),
        "extra": {
            "smoke": smoke,
            "capacity_rps_nominal": capacity,
            "service_pad_s": pad_s,
            "replicas": replicas,
            "max_batch": max_batch,
            "baseline": baseline,
            "traced": traced,
            "overload": overload,
            "failover": failover,
            "requests_shed_total": shed_total,
            "replica_failover_total": failover["failovers"],
            "serving_recompiles_total": {
                "after_warmup": recompiles_warm,
                "final": recompiles_final,
                "closed": checks["buckets_closed"],
            },
            "accounted": accounted,
            "decode": decode,
            "spec_decode": spec,
            "fleet": fleet,
            "kv_cache_hit_rate": decode["kv_cache_hit_rate"],
            "stats": stats,
            "tracing": {
                "baseline_p50_s": baseline["p50_s"],
                "traced_p50_s": traced["p50_s"],
                "overhead_frac": overhead,
                "spans_recorded": acct_traced["recorded"],
                "kept_while_keep_none": acct_traced["kept"],
                "failover_traces_kept": len(kept_failover),
                "kept_traces_path": traces_path,
            },
            "flight_dumps": flight_dumps,
            "telemetry": {
                "prometheus_bytes": len(telemetry.prometheus_text()),
            },
            "checks": checks,
            "exit_code": 0 if all(checks.values()) else 1,
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="short phases + the CI self-check contract")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    res = run_bench(args.smoke, seed=args.seed)
    print(json.dumps(res))
    return res["extra"]["exit_code"]


if __name__ == "__main__":
    sys.exit(main())
