"""Weights/dataset download cache (reference:
python/paddle/utils/download.py — get_weights_path_from_url:75,
get_path_from_url:121 with md5 check + tar/zip decompress).

Zero-egress environments: network fetch is attempted only when the file is
not already in the cache; failures raise a clear error instead of hanging.
Cache layout matches the reference: ``~/.cache/paddle_tpu/<basename>``.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp
import shutil
import tarfile
import zipfile

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle_tpu/hapi/weights")
DATA_HOME = osp.expanduser("~/.cache/paddle_tpu/dataset")


def is_url(path: str) -> bool:
    return path.startswith(("http://", "https://"))


def _md5check(fullname: str, md5sum=None) -> bool:
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def get_path_from_url(url: str, root_dir: str, md5sum=None,
                      check_exist: bool = True, decompress: bool = True) -> str:
    fname = osp.split(url)[-1]
    fullname = osp.join(root_dir, fname)
    if check_exist and osp.exists(fullname) and _md5check(fullname, md5sum):
        return _maybe_decompress(fullname) if decompress else fullname
    os.makedirs(root_dir, exist_ok=True)
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=30) as r, \
                open(fullname + ".part", "wb") as f:
            shutil.copyfileobj(r, f)
    except Exception as e:
        raise RuntimeError(
            f"download of {url} failed ({e}); this environment has no "
            f"egress — place the file at {fullname} manually") from e
    os.replace(fullname + ".part", fullname)
    if not _md5check(fullname, md5sum):
        raise RuntimeError(f"md5 mismatch for {fullname}")
    return _maybe_decompress(fullname) if decompress else fullname


def _maybe_decompress(fullname: str) -> str:
    if tarfile.is_tarfile(fullname):
        dst = osp.splitext(fullname)[0]
        if not osp.exists(dst):
            with tarfile.open(fullname) as tf:
                tf.extractall(osp.dirname(fullname))
        return dst
    if zipfile.is_zipfile(fullname):
        dst = osp.splitext(fullname)[0]
        if not osp.exists(dst):
            with zipfile.ZipFile(fullname) as zf:
                zf.extractall(osp.dirname(fullname))
        return dst
    return fullname


def get_weights_path_from_url(url: str, md5sum=None) -> str:
    """Reference: download.py:75."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
