"""Benchmark suite: training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The primary metric is GPT-base (124M) bf16 tokens/sec/chip (best variant
of a small batch-size x loss-path sweep); "extra" carries the additional
BASELINE.md configs (ResNet-50 images/sec, BERT-base AMP samples/sec,
Wide&Deep CTR samples/sec, GPT-1.3B tokens/sec + peak HBM) so the perf
story is not a single model.

Process architecture (round-4): the parent process NEVER imports jax.
Every benchmark config — and a cheap init probe — runs in a fresh
subprocess with a hard wall-clock timeout. Rationale: the axon TPU
tunnel has wedged (jax.devices() hanging forever, no exception) in 2 of
3 rounds; a wedged native call poisons the whole process's plugin
state, so in-process retry loops (rounds 1-3) could only give up after
the first hang. With subprocess isolation a wedge costs one child, the
parent's remaining retries genuinely retry, and a mid-run wedge in one
config cannot take down the others. Each config is independently
guarded — a failure records {"error": ...} for that config instead of
crashing the whole bench.

FLOPs convention (stated per round-2 verdict): MFU uses the 6N
approximation — 6 FLOPs per parameter per token (fwd 2N + bwd 4N),
EXCLUDING attention score/context FLOPs (the PaLM-appendix convention
without the 12*L*H*Q*T term). Peak is the v5e bf16 197 TFLOP/s figure.

The reference publishes no in-repo numbers (BASELINE.md), so vs_baseline
is 1.0 on success; the absolute numbers are the tracked quantity.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import traceback

V5E_BF16_PEAK = 197e12
_MARK = "##BENCHJSON## "
_HERE = os.path.dirname(os.path.abspath(__file__))

# per-config child wall-clock budgets (compile + warmup + timed iters);
# the sweep configs compile several step variants
CHILD_TIMEOUT = {"probe": 150, "numerics": 300, "op_pallas": 420,
                 "gpt_base": 1200, "gpt_1p3b": 900, "heter_ctr": 600}
CHILD_TIMEOUT_DEFAULT = 600
GLOBAL_BUDGET_S = 2700  # stop launching new configs past this

# numerics first: the on-chip kernel-vs-dense validation (r3 item 10) is
# cheap and must not be starved by the budget; heter_ctr last (r3 item
# 2's 10x A/B — informative, not the headline)
CONFIG_ORDER = ("numerics", "op_pallas", "gpt_base", "resnet50",
                "bert_base_amp", "widedeep_ctr", "gpt_1p3b", "heter_ctr")


# --------------------------------------------------------------------------
# child side: one process = one backend init = one config
# --------------------------------------------------------------------------

def _child_setup():
    """Backend init inside the child. The parent enforces the hard
    timeout; the watchdog thread here only shortens the common case (a
    wedged init exits after attempt_timeout_s instead of burning the
    whole child budget)."""
    import threading
    result: dict = {}

    def _try():
        try:
            import jax
            # the axon plugin ignores the JAX_PLATFORMS env var; only the
            # config knob reliably forces CPU (used for bench self-tests)
            plat = os.environ.get("BENCH_PLATFORM")
            if plat:
                jax.config.update("jax_platforms", plat)
            # persistent compile cache: children share compiled programs
            # with each other and with later bench runs
            try:
                jax.config.update("jax_compilation_cache_dir",
                                  os.path.join(_HERE, ".jax_cache"))
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1.0)
            except Exception:
                pass
            devs = jax.devices()  # forces platform/plugin init
            float(jax.numpy.zeros(()).sum())  # proves the runtime works
            result["jax"], result["devs"] = jax, devs
        except Exception as e:  # noqa: BLE001
            result["err"] = e

    t = threading.Thread(target=_try, daemon=True)
    t.start()
    t.join(120.0)
    if "jax" in result:
        return result["jax"]
    if t.is_alive():
        raise RuntimeError("backend init hung > 120s (tunnel wedged)")
    raise RuntimeError(f"backend init failed: {result.get('err')}")


def _emit(payload: dict):
    sys.stdout.write(_MARK + json.dumps(payload) + "\n")
    sys.stdout.flush()


def _timed_steps(trainer, inputs, labels, warmup: int, iters: int):
    """Run warmup + timed steps; host-fetch the loss as the sync point
    (under the axon tunnel block_until_ready can return early)."""
    for _ in range(warmup):
        loss = trainer.train_step(inputs, labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.train_step(inputs, labels)
    final_loss = float(loss)
    return time.perf_counter() - t0, final_loss


def _hbm_peak_gb(jax):
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        return round(peak / 2**30, 3) if peak else None
    except Exception:
        return None


def _make_fused_loss(inner, chunk, ce_kernel="chunked"):
    """Wrap a model exposing fused_head_loss as a (ids, labels) -> loss
    Layer, so ParallelTrainer drives a fused head+CE path (the (B*S,
    vocab) logits never materialize). ce_kernel: "chunked" =
    ops/chunked_ce.py jnp scan, "pallas" = the Mosaic kernel in
    ops/pallas/fused_ce.py (interpret mode off-TPU)."""
    from paddle_tpu import nn

    class FusedLoss(nn.Layer):
        def __init__(self, inner_):
            super().__init__()
            self.inner = inner_

        def forward(self, batch_):
            ids, lbl = batch_
            return self.inner.fused_head_loss(ids, lbl, chunk=chunk,
                                              ce_kernel=ce_kernel)

    return FusedLoss(inner)


def _gpt_variant(jax, on_tpu, batch, seq, vocab, cfg, fused, chunk=8192,
                 remat=False, grad_sync=None, ce_kernel="chunked"):
    """Measure one (batch, loss-path, remat, grad-sync) GPT-base variant.

    fused=True routes through GPTForPretraining.fused_head_loss
    (ops/chunked_ce.py) so the (B*S, vocab) logits never materialize;
    fused=False is the dense-logits + lse-gather CE path. grad_sync
    ("int8"/"int4"/"bf16") compresses the DP gradient exchange
    (distributed/compressed.py) — over all local devices on TPU, a
    single-device mesh otherwise (measures the quantize overhead)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn, telemetry
    from paddle_tpu.distributed.engine import ParallelTrainer
    from tools._mesh_setup import data_mesh
    from paddle_tpu.text.models import GPTForPretraining

    paddle.seed(0)
    ndev = len(jax.devices()) if (on_tpu and grad_sync) else 1
    data_mesh(ndev)
    # fresh per-variant registry, no run_dir/profiler: the per-step sync
    # telemetry adds is the loss fetch _timed_steps does anyway
    with telemetry.scope(profile=False) as tel:
        model = GPTForPretraining(
            tensor_parallel=False, vocab_size=vocab, hidden_size=cfg["h"],
            num_layers=cfg["l"], num_heads=cfg["n"],
            max_position_embeddings=seq, attn_dropout=0.0,
            hidden_dropout=0.0)
        model.bfloat16()
        opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters())

        sync_kw = dict(grad_sync=grad_sync) if grad_sync else {}
        if fused:
            trainer = ParallelTrainer(
                _make_fused_loss(model, chunk, ce_kernel), opt,
                lambda out, _lbl: out, remat=remat, **sync_kw)
        else:
            trainer = ParallelTrainer(
                model, opt,
                # bf16 logits straight into the fused lse-gather CE fast
                # path (fp32 accumulation inside; astype here would
                # materialize a full fp32 (b, s, vocab) tensor)
                lambda logits, lbl: nn.functional.cross_entropy(logits, lbl),
                remat=remat, **sync_kw)

        rng = np.random.RandomState(0)
        ids = rng.randint(0, vocab, (batch, seq)).astype("int32")
        labels = rng.randint(0, vocab, (batch, seq)).astype("int32")
        iters = 16 if on_tpu else 3
        warmup = 8 if on_tpu else 2
        inputs = (ids, labels) if fused else ids
        lbls = 0.0 if fused else labels
        dt, final_loss = _timed_steps(trainer, inputs, lbls, warmup, iters)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    out = {"tokens_per_sec": round(batch * seq * iters / dt, 1),
           "params": n_params, "final_loss": round(final_loss, 4),
           "telemetry": _harvest_telemetry(tel.registry),
           # predicted-vs-measured step time of this variant's last step
           # (engine._record_step_telemetry pairs the overlap model's
           # makespan with the wall clock; telemetry.calibration)
           "calibration": telemetry.calibration.pair("step_time")}
    if on_tpu:
        # memory_stats peak is process-cumulative: attributable to THIS
        # variant only while the sweep runs smallest-footprint-first
        out["hbm_peak_so_far_gb"] = _hbm_peak_gb(jax)
    return out


def _harvest_telemetry(reg):
    """Registry -> the compact telemetry dict appended to bench JSON."""
    def val(name, default=None):
        m = reg.get(name)
        return m.value() if m is not None else default
    return {
        "mfu": round(val("mfu", 0.0), 6),
        "recompiles": int(val("recompiles_total", 0)),
        "wire_bytes": val("grad_sync_bytes_total", 0.0),
        "compression_x": round(val("grad_sync_compression_x", 0.0), 3),
        "step_time_avg_s": round(val("step_time_seconds", 0.0), 6),
    }


def bench_gpt(jax, on_tpu):
    """GPT-base 124M with a batch x loss-path sweep (round-3 verdict:
    the b=8 dense-only number sat at the bottom of the MFU band and the
    chunked-CE op was built but never measured). The best variant is the
    headline; every variant is recorded."""
    vocab, seq = (50304, 1024) if on_tpu else (1024, 128)
    cfg = {"h": 768, "l": 12, "n": 12} if on_tpu else \
        {"h": 128, "l": 2, "n": 4}
    # ordered smallest HBM footprint first (fused before dense at each
    # batch) so per-variant hbm_peak_so_far_gb increments are
    # attributable; remat trades FLOPs for memory — measured once at the
    # largest batch (the only place it could pay on this 124M model)
    variants = ([("fused_b8", dict(batch=8, fused=True)),
                 ("dense_b8", dict(batch=8, fused=False)),
                 ("fused_b16", dict(batch=16, fused=True)),
                 ("dense_b16", dict(batch=16, fused=False)),
                 ("fused_b32", dict(batch=32, fused=True)),
                 ("fused_b32_remat", dict(batch=32, fused=True,
                                          remat=True)),
                 ("dense_b32", dict(batch=32, fused=False)),
                 # compressed DP grad exchange over all chips (per-chip
                 # batch 8): same model, 4x (int8) / 7x (int4) fewer
                 # gradient bytes on wire
                 ("fused_b8_int8dp", dict(batch=8, fused=True,
                                          grad_sync="int8")),
                 ("fused_b8_int4dp", dict(batch=8, fused=True,
                                          grad_sync="int4")),
                 # Pallas fused-CE kernel (ops/pallas/fused_ce.py):
                 # head matmul + softmax-CE in one Mosaic kernel, block
                 # configs from the tuning DB
                 ("fused_b8_pallas_ce", dict(batch=8, fused=True,
                                             ce_kernel="pallas"))]
                if on_tpu else
                [("fused_b4", dict(batch=4, fused=True)),
                 ("dense_b4", dict(batch=4, fused=False)),
                 ("fused_b4_int8dp", dict(batch=4, fused=True,
                                          grad_sync="int8")),
                 ("fused_b4_int4dp", dict(batch=4, fused=True,
                                          grad_sync="int4")),
                 # interpret-mode on CPU: correctness + plumbing only
                 ("fused_b4_pallas_ce", dict(batch=4, fused=True,
                                             ce_kernel="pallas"))])
    sweep, best, best_name = {}, None, None
    out = None
    for name, kw in variants:
        try:
            r = _gpt_variant(jax, on_tpu, seq=seq, vocab=vocab, cfg=cfg,
                             **kw)
            sweep[name] = r
            if best is None or \
                    r["tokens_per_sec"] > best["tokens_per_sec"]:
                best, best_name = r, name
        except Exception as e:  # OOM etc.: record, keep sweeping
            sweep[name] = {"error": f"{type(e).__name__}: {e}"}
        if best is None:
            continue
        # interim emit: a wedge later in the sweep must not discard the
        # variants already measured (the parent keeps the LAST mark line)
        out = dict(best)
        out["variant"] = best_name
        out["sweep"] = dict(sweep)
        if on_tpu:
            out["mfu_6N"] = round(
                out["tokens_per_sec"] * 6 * out["params"] / V5E_BF16_PEAK,
                4)
        out["on_tpu"] = on_tpu
        out["partial"] = name != variants[-1][0]
        _emit(out)
    if best is None:
        raise RuntimeError(f"all GPT-base variants failed: {sweep}")
    # auto-parallel planner over the same shape: search the config
    # space on the calibrated cost model, RUN the chosen config, and
    # record planned-vs-measured step time so the drift lands in
    # calibration_drift_ratio{key=planner_step_time}
    try:
        out["planner"] = _gpt_planner(jax, on_tpu, vocab, seq, cfg)
    except Exception as e:
        out["planner"] = {"error": f"{type(e).__name__}: {e}"}
    out.pop("partial", None)
    return out


def _gpt_planner(jax, on_tpu, vocab, seq, cfg):
    """plan_search at the bench GPT shape + a measured run of its pick.

    Closes the planner's own calibration loop: predicted step time (the
    search's scoring model under the calibrated constants) vs the
    measured step time of actually running the chosen ParallelTrainer
    config, recorded under the planner_step_time key."""
    from paddle_tpu import telemetry
    from tools import bench_plan

    spec = dict(vocab=vocab, h=cfg["h"], layers=cfg["l"], heads=cfg["n"],
                seq=seq, batch_per_device=8 if on_tpu else 4)
    n = len(jax.devices()) if on_tpu else 1
    builder = bench_plan.make_gpt_builder(
        spec, spec["batch_per_device"] * n)
    ranked, baselines, n_params = bench_plan.search(
        spec, n, stage_top_k=1, builder=builder)
    pick = ranked[0]
    predicted = pick.predicted.total
    trainer, ids, labels = builder(pick)
    iters = 16 if on_tpu else 2
    warmup = 8 if on_tpu else 1
    dt, final_loss = _timed_steps(trainer, ids, labels, warmup, iters)
    measured = dt / iters
    telemetry.calibration.record("planner_step_time", predicted, measured)
    return {"pick": pick.to_dict(), "baselines": baselines,
            "predicted_s": predicted, "measured_s": measured,
            "final_loss": round(final_loss, 4),
            "calibration": telemetry.calibration.pair(
                "planner_step_time")}


def bench_gpt_1p3b(jax, on_tpu):
    """BASELINE configs[3]: GPT-3 1.3B on ONE chip — proves the memory
    machinery (remat + bf16 moments + chunked CE) at real scale. The
    hybrid multi-chip layout for the same model is exercised by
    __graft_entry__.dryrun_multichip; this measures what a single 16 GB
    v5e can hold: params bf16 2.6 GB + AdamW m/v bf16 5.3 GB + rematted
    activations, with the (B*S, 50304) logits never materialized."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.engine import ParallelTrainer
    from tools._mesh_setup import data_mesh
    from paddle_tpu.text.models import GPTForPretraining

    if on_tpu:
        vocab, h, layers, heads, seq, batch = 50304, 2048, 24, 16, 1024, 8
        iters, warmup = 10, 4
    else:
        vocab, h, layers, heads, seq, batch = 1024, 256, 4, 4, 128, 2
        iters, warmup = 2, 1

    paddle.seed(0)
    data_mesh(1)
    model = GPTForPretraining(
        tensor_parallel=False, vocab_size=vocab, hidden_size=h,
        num_layers=layers, num_heads=heads, max_position_embeddings=seq,
        attn_dropout=0.0, hidden_dropout=0.0)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(2e-4, parameters=model.parameters(),
                                 slot_dtype="bfloat16")
    trainer = ParallelTrainer(_make_fused_loss(model, 8192), opt,
                              lambda out, _lbl: out, remat=True)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, seq)).astype("int32")
    labels = rng.randint(0, vocab, (batch, seq)).astype("int32")
    dt, final_loss = _timed_steps(trainer, (ids, labels), 0.0,
                                  warmup, iters)
    tokens_per_sec = batch * seq * iters / dt
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    out = {"tokens_per_sec": round(tokens_per_sec, 1), "params": n_params,
           "final_loss": round(final_loss, 4)}
    if on_tpu:
        out["mfu_6N"] = round(
            tokens_per_sec * 6 * n_params / V5E_BF16_PEAK, 4)
        out["peak_hbm_gb"] = _hbm_peak_gb(jax)
    return out


def bench_resnet50(jax, on_tpu):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.engine import ParallelTrainer
    from tools._mesh_setup import data_mesh
    from paddle_tpu.vision.models import resnet50, resnet18

    paddle.seed(0)
    data_mesh(1)
    if on_tpu:
        model, batch, size, iters, warmup = resnet50(), 128, 224, 20, 8
    else:
        model, batch, size, iters, warmup = resnet18(), 4, 32, 2, 1
    model.bfloat16()  # TPU AMP O2 equivalent: bf16 params + compute
    opt = paddle.optimizer.Momentum(0.1, momentum=0.9,
                                    parameters=model.parameters())
    trainer = ParallelTrainer(
        model, opt, lambda o, y: nn.functional.cross_entropy(o, y))
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    # inputs must match the bf16 conv weights (XLA convs are same-dtype)
    imgs = jnp.asarray(rng.randn(batch, 3, size, size), dtype=jnp.bfloat16)
    lbls = rng.randint(0, 1000, (batch,)).astype("int32")
    dt, final_loss = _timed_steps(trainer, imgs, lbls, warmup, iters)
    return {"images_per_sec": round(batch * iters / dt, 1),
            "final_loss": round(final_loss, 4)}


def bench_widedeep(jax, on_tpu):
    """BASELINE configs[4]: sparse recommender throughput (Criteo-shaped
    synthetic CTR: 26 categorical fields + 13 dense)."""
    import numpy as np
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.distributed.engine import ParallelTrainer
    from tools._mesh_setup import data_mesh
    from paddle_tpu.rec import WideDeep

    paddle.seed(0)
    data_mesh(1)
    if on_tpu:
        fields, batch, iters, warmup = [100_000] * 26, 4096, 20, 8
        hidden = (400, 400, 400)
    else:
        fields, batch, iters, warmup = [1000] * 8, 256, 2, 1
        hidden = (64, 32)
    model = WideDeep(fields, dense_dim=13, embedding_dim=16,
                     hidden_sizes=hidden)
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())

    def bce(logit, y):
        return jnp.mean(jnp.maximum(logit, 0) - logit * y
                        + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    trainer = ParallelTrainer(model, opt, bce)
    rng = np.random.RandomState(0)
    ids = np.stack([rng.randint(0, d, batch) for d in fields], 1) \
        .astype("int64")
    dense = rng.randn(batch, 13).astype("float32")
    label = rng.randint(0, 2, batch).astype("float32")
    dt, final_loss = _timed_steps(trainer, (ids, dense), label,
                                  warmup, iters)
    return {"samples_per_sec": round(batch * iters / dt, 1),
            "final_loss": round(final_loss, 4)}


def bench_bert_amp(jax, on_tpu):
    """BERT-base MLM+NSP, bf16 (the TPU AMP: reference fp16_utils.py:322
    cast_model_to_fp16 O2 maps to whole-model bf16 on TPU)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.engine import ParallelTrainer
    from tools._mesh_setup import data_mesh
    from paddle_tpu.text.models import BertForPretraining

    paddle.seed(0)
    data_mesh(1)
    if on_tpu:
        cfg = dict(vocab_size=30528, hidden_size=768, num_layers=12,
                   num_heads=12, max_position_embeddings=512)
        batch, seq, iters, warmup = 16, 128, 20, 8
    else:
        cfg = dict(vocab_size=1024, hidden_size=128, num_layers=2,
                   num_heads=4, max_position_embeddings=128)
        batch, seq, iters, warmup = 4, 64, 2, 1
    model = BertForPretraining(tensor_parallel=False, attn_dropout=0.0,
                               hidden_dropout=0.0, **cfg)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())

    def loss_fn(outputs, labels):
        mlm_logits, nsp_logits = outputs
        mlm_labels, nsp_labels = labels
        return model.loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels)

    trainer = ParallelTrainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg["vocab_size"], (batch, seq)).astype("int32")
    mlm = np.full((batch, seq), -100, dtype="int32")
    mlm[:, ::8] = rng.randint(0, cfg["vocab_size"], (batch, seq // 8))
    nsp = rng.randint(0, 2, (batch,)).astype("int32")
    dt, final_loss = _timed_steps(trainer, ids, (mlm, nsp), warmup, iters)
    return {"samples_per_sec": round(batch * iters / dt, 1),
            "final_loss": round(final_loss, 4)}


def bench_numerics(jax, on_tpu):
    """On-chip numerics smoke (r3 verdict item 10): flash-attention
    fwd/bwd, chunked CE, bf16 matmul vs dense fp32 references on the
    LIVE backend — the tolerances that CPU-interpret testing cannot
    validate. Reuses tools/numerics_smoke.py's checks in-process."""
    sys.path.insert(0, os.path.join(_HERE, "tools"))
    import numerics_smoke as ns

    checks = []
    interpret = not on_tpu
    for fn in (lambda: ns.check_flash_attention(interpret),
               ns.check_chunked_ce, ns.check_bf16_matmul):
        checks.extend(fn())
    return {"numerics_ok": all(c.get("ok") for c in checks),
            "checks": checks}


def bench_heter_ctr(jax, on_tpu):
    """Heter device-tier vs host-PS embedding A/B on the Wide&Deep CTR
    shape (r3 verdict item 2's 10x target), overlapped prepare mode."""
    import numpy as np
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.distributed.engine import ParallelTrainer
    from tools._mesh_setup import data_mesh
    from paddle_tpu.rec import WideDeep

    if on_tpu:
        fields, batch, steps, warmup = [100_000] * 26, 4096, 12, 4
        hidden, cap = (400, 400, 400), 1_000_000
    else:
        fields, batch, steps, warmup = [1000] * 8, 256, 3, 1
        hidden, cap = (64, 32), 4096
    rng = np.random.RandomState(0)

    def draw_ids():
        u = rng.zipf(1.3, size=(batch, len(fields)))
        return (u % np.asarray(fields)[None, :]).astype("int64")

    batches = [(draw_ids(), rng.randn(batch, 13).astype("float32"),
                rng.randint(0, 2, batch).astype("float32"))
               for _ in range(steps + warmup)]

    def bce(logit, y):
        return jnp.mean(jnp.maximum(logit, 0) - logit * y
                        + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    out = {}
    for mode in ("heter", True):
        paddle.seed(0)
        data_mesh(1)
        model = WideDeep(fields, dense_dim=13, embedding_dim=16,
                         hidden_sizes=hidden, sparse=mode,
                         heter_capacity=cap)
        opt = paddle.optimizer.Adagrad(0.05, epsilon=1e-8,
                                       parameters=model.parameters())
        tr = ParallelTrainer(model, opt, bce)

        def run(bs):
            if mode != "heter":
                for ids, dense, y in bs:
                    loss = tr.train_step((ids, dense), y)
                return loss
            fut = model.prepare_batch_async(bs[0][0])
            for i, (ids, dense, y) in enumerate(bs):
                slots = fut.result()
                loss = tr.train_step((slots, dense), y)
                if i + 1 < len(bs):
                    fut = model.prepare_batch_async(bs[i + 1][0])
            return loss

        float(run(batches[:warmup]))
        t0 = time.perf_counter()
        float(run(batches[warmup:]))
        dt = time.perf_counter() - t0
        name = "heter_overlapped" if mode == "heter" else "host_ps"
        out[name + "_samples_per_sec"] = round(batch * steps / dt, 1)
        if mode == "heter":
            out["hot_hit_rate"] = round(model.ctr_table.hit_rate, 4)
    out["speedup_x"] = round(out["heter_overlapped_samples_per_sec"]
                             / out["host_ps_samples_per_sec"], 2)
    return out


def bench_op_pallas(jax, on_tpu):
    """Pallas kernel tier via tools/op_bench.py's pallas suite: tuned-vs-
    default block configs for flash attention + fused CE and the
    chunked-CE baseline. On TPU this is the autotuner's perf surface
    (run `python -m paddle_tpu.ops.pallas.tuner --suite bench` first to
    refresh the DB); on CPU the kernels run in interpret mode, so the
    value is plumbing + config-resolution coverage, not perf."""
    from paddle_tpu import telemetry
    from tools.op_bench import pallas_suite

    with telemetry.scope(profile=False) as tel:
        recs = pallas_suite(iters=20 if on_tpu else 2, smoke=not on_tpu)
    reg = tel.registry
    resolved = {}
    m = reg.get("pallas_config_resolved_total")
    if m is not None:
        for key, v in m.series().items():
            resolved[",".join(f"{k}={val}" for k, val in key)] = int(v)
    return {"ops": {r["op"]: {k: v for k, v in r.items() if k != "op"}
                    for r in recs},
            "config_resolutions": resolved}


CHILD_FNS = {"gpt_base": bench_gpt, "resnet50": bench_resnet50,
             "bert_base_amp": bench_bert_amp, "widedeep_ctr": bench_widedeep,
             "gpt_1p3b": bench_gpt_1p3b, "numerics": bench_numerics,
             "heter_ctr": bench_heter_ctr, "op_pallas": bench_op_pallas}


def child_main(name: str) -> int:
    if name == "probe":
        try:
            jax = _child_setup()
            _emit({"ok": True, "backend": jax.default_backend(),
                   "n_devices": len(jax.devices())})
            return 0
        except Exception as e:
            _emit({"ok": False, "error": str(e)})
            return 1
    try:
        jax = _child_setup()
        on_tpu = jax.default_backend() == "tpu"
        result = CHILD_FNS[name](jax, on_tpu)
        result["on_tpu"] = on_tpu
        _emit(result)
        return 0
    except Exception as e:
        sys.stderr.write(traceback.format_exc())
        _emit({"error": f"{type(e).__name__}: {e}"})
        return 1


# --------------------------------------------------------------------------
# parent side: orchestration, no jax
# --------------------------------------------------------------------------

def _run_child(name: str, timeout: float):
    """One fresh subprocess; returns (payload|None, err|None)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", name],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, f"timed out after {timeout:.0f}s (killed)"
    except Exception as e:  # spawn failure
        return None, f"spawn failed: {e}"
    payload = None
    for line in (proc.stdout or "").splitlines():
        if line.startswith(_MARK):
            try:
                payload = json.loads(line[len(_MARK):])
            except ValueError:
                pass
    if payload is None:
        tail = (proc.stderr or "").strip().splitlines()[-6:]
        return None, (f"exit {proc.returncode}, no result; "
                      f"stderr tail: {' | '.join(tail)}")
    if name != "probe" and proc.stderr:
        sys.stderr.write(proc.stderr)
    return payload, None


def _probe(max_attempts: int, backoff_s: float = 15.0):
    """Fresh-subprocess init probe with backoff. Returns (backend|None,
    last_error)."""
    last = None
    for attempt in range(max_attempts):
        payload, err = _run_child("probe", CHILD_TIMEOUT["probe"])
        if payload and payload.get("ok"):
            return payload["backend"], None
        last = err or (payload or {}).get("error", "unknown")
        sys.stderr.write(f"bench: probe {attempt + 1}/{max_attempts} "
                         f"failed: {last}\n")
        if attempt < max_attempts - 1:
            time.sleep(backoff_s * (attempt + 1))
    return None, last


def main():
    t_start = time.monotonic()
    backend, probe_err = _probe(max_attempts=4)
    if backend is None:
        print(json.dumps({
            "metric": "gpt_base_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/sec", "vs_baseline": 0.0,
            "error": f"backend init failed in 4 fresh subprocesses: "
                     f"{probe_err}"}))
        return 1

    extra = {}
    prev_timed_out = False
    for name in CONFIG_ORDER:
        elapsed = time.monotonic() - t_start
        if elapsed > GLOBAL_BUDGET_S:
            extra[name] = {"error": "skipped: global bench budget "
                                    f"exhausted ({elapsed:.0f}s)"}
            continue
        if prev_timed_out:
            # previous config wedged mid-run: cheap probe before burning
            # another full child budget on a dead tunnel
            ok, err = _probe(max_attempts=1)
            if ok is None:
                extra[name] = {"error": f"skipped: tunnel wedged ({err})"}
                continue
            prev_timed_out = False
        timeout = CHILD_TIMEOUT.get(name, CHILD_TIMEOUT_DEFAULT)
        payload, err = _run_child(name, timeout)
        if payload is None:
            extra[name] = {"error": err}
            prev_timed_out = "timed out" in (err or "")
        else:
            extra[name] = payload

    gpt = extra.get("gpt_base", {})
    ok = "tokens_per_sec" in gpt
    result = {
        "schema_version": 2,
        "metric": "gpt_base_train_tokens_per_sec_per_chip",
        "value": gpt.get("tokens_per_sec", 0.0),
        "unit": "tokens/sec",
        "vs_baseline": 1.0 if ok else 0.0,
        "backend": backend,
        "flops_convention": "6N per token (no attention term)",
        # best-variant {predicted, measured, drift} step-time triple
        # (telemetry.calibration; schema_version 2)
        "calibration": gpt.get("calibration"),
        "extra": extra,
    }
    if "mfu_6N" in gpt:
        result["mfu"] = gpt["mfu_6N"]
        result["params"] = gpt["params"]
        result["final_loss"] = gpt["final_loss"]
    if "telemetry" in gpt:
        # best-variant registry harvest (mfu from the cost model,
        # recompiles, wire bytes) — see paddle_tpu/telemetry
        result["telemetry"] = gpt["telemetry"]
    print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", default=None)
    args = ap.parse_args()
    sys.exit(child_main(args.child) if args.child else main())
