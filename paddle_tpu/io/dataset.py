"""Dataset abstractions (reference: python/paddle/io/__init__.py,
fluid/dataloader/dataset.py)."""
from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = [np.asarray(t) for t in tensors]
        assert all(t.shape[0] == self.tensors[0].shape[0] for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            sample.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx = len(self) + idx
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        off = idx - (self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0)
        return self.datasets[ds_idx][off]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(total)
    out, off = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[off:off + ln].tolist()))
        off += ln
    return out
