"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp


def mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def median(x, axis=None, keepdim=False, name=None):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return jnp.nanquantile(x, q, axis=axis, keepdims=keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return jnp.nansum(x, axis=axis, dtype=dtype, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False, name=None):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def numel(x, name=None):
    return jnp.asarray(x.size, dtype=jnp.int32)
