// TCP RPC layer for the PS tables — the capability of the reference's brpc
// parameter-server transport (reference behavior modeled:
// distributed/service/brpc_ps_server.cc / brpc_ps_client.cc — remote
// pull/push of sharded sparse tables; NOT a port: fresh blocking-socket
// design, thread-per-connection server, length-prefixed binary frames, C ABI
// for ctypes. Multi-host key routing happens ABOVE this layer in
// distributed/ps/service.py by key hash — each server owns one shard.)
//
// Protocol (little-endian, fixed header):
//   request : u8 op | u8 flag | i64 n
//             op=0 HELLO: no body;              response: i32 dim (v1)
//             op=9 HELLO2: no body;             response: i32 dim, i32 feat_dim
//             op=1 PULL : body n*i64 keys;      response: n*dim f32
//                         flag=1 -> create missing rows
//             op=2 PUSH : body n*i64 keys, n*dim f32 grads, f32 lr;
//                                               response: u8 1
//             op=3 SIZE : no body;              response: i64 nrows
// Graph ops (the server may also host a graph-table shard — the
// reference's common_graph_table.cc served by the same brpc PS server;
// cross-server NODE partitioning happens above by key hash):
//             op=4 GADD : body n*i64 src, n*i64 dst (+ n*f32 w if flag);
//                                               response: u8 1
//             op=5 GSAMP: body n*i64 keys, i32 k, u64 seed; flag=weighted
//                                               response: n*k i64 + n i64
//             op=6 GFEAT: body n*i64 keys;      response: n*feat_dim f32
//             op=7 GSETF: body n*i64 keys, n*feat_dim f32; response: u8 1
//             op=8 GNUM : no body;              response: i64 nnodes
// A malformed/short frame closes the connection. The server serves ONE
// sparse table (its key shard); clients keep one connection per server and
// serialize requests on it.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {
// from sparse_table.cc
void ps_sparse_pull(void* t, const int64_t* keys, int64_t n, float* out,
                    int create_missing);
void ps_sparse_push(void* t, const int64_t* keys, int64_t n,
                    const float* grads, float lr);
int64_t ps_sparse_size(void* t);
// from graph_table.cc
void ps_graph_add_edges(void* g, const int64_t* src, const int64_t* dst,
                        const float* w, int64_t n);
void ps_graph_sample_neighbors(void* g, const int64_t* keys, int64_t n,
                               int k, uint64_t seed, int64_t* out,
                               int64_t* counts, int weighted);
void ps_graph_get_feature(void* g, const int64_t* keys, float* out,
                          int64_t n);
void ps_graph_set_feature(void* g, const int64_t* keys, const float* feats,
                          int64_t n);
int64_t ps_graph_num_nodes(void* g);
}

namespace {

constexpr uint8_t kHello = 0, kPull = 1, kPush = 2, kSize = 3,
                  kGAdd = 4, kGSamp = 5, kGFeat = 6, kGSetF = 7, kGNum = 8,
                  kHello2 = 9;

bool ReadFull(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Server {
  void* table = nullptr;
  void* graph = nullptr;   // optional graph-table shard
  int feat_dim = 0;
  int dim = 0;
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::mutex conn_mu;
  // connection fds and their threads; Serve() only SHUTS DOWN its fd on
  // exit — the close (and thread join) happens in Reap()/ps_server_stop,
  // so a stopping server can unblock reads via shutdown() without an
  // fd-reuse race, and Serve threads never outlive the Server they
  // dereference. AcceptLoop reaps finished connections so long-lived
  // servers do not leak an fd + thread record per client.
  struct Conn {
    int fd;
    std::thread th;
    std::atomic<bool> done{false};
    Conn(int f) : fd(f) {}
  };
  std::vector<std::unique_ptr<Conn>> conns;

  void Serve(Conn* c) {
    ServeFd(c->fd);
    c->done.store(true);
  }

  void Reap() {  // caller holds conn_mu
    for (auto it = conns.begin(); it != conns.end();) {
      if ((*it)->done.load()) {
        if ((*it)->th.joinable()) (*it)->th.join();
        ::close((*it)->fd);
        it = conns.erase(it);
      } else {
        ++it;
      }
    }
  }

  void ServeFd(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::vector<int64_t> keys;
    std::vector<float> vals;
    for (;;) {
      uint8_t hdr[2];
      int64_t n = 0;
      if (!ReadFull(fd, hdr, 2) || !ReadFull(fd, &n, 8)) break;
      // Bound what one frame can make the server allocate, so a bad or
      // malicious frame closes the connection instead of bad_alloc-ing
      // the process: n caps the 8-byte key/dst arrays (kGAdd resizes
      // three of them), the n*width product caps the float payloads
      // (kPull/kPush n*dim, kGFeat/kGSetF n*feat_dim, kGSamp n*k).
      // 2^24 keys = 128MB/array, 2^27 floats = 512MB — far above any
      // real batch, far below an OOM kill.
      const int64_t kKeyCap = int64_t(1) << 24;
      const int64_t kElemCap = int64_t(1) << 27;
      if (n < 0 || n > kKeyCap) break;
      if ((hdr[0] == kPull || hdr[0] == kPush) &&
          n * static_cast<int64_t>(dim) > kElemCap)
        break;
      if ((hdr[0] == kGFeat || hdr[0] == kGSetF) &&
          n * static_cast<int64_t>(feat_dim) > kElemCap)
        break;
      if (hdr[0] == kHello) {
        // v1 handshake: 4-byte reply, kept exactly as-is so an OLD
        // client against a NEW server still works during rolling
        // upgrades of a multi-host deployment
        int32_t d = dim;
        if (!WriteFull(fd, &d, 4)) break;
      } else if (hdr[0] == kHello2) {
        // v2 handshake (adds feat_dim). A NEW client against an OLD
        // server fails fast: the old server closes on the unknown op,
        // so connect() errors instead of hanging on a short read.
        int32_t d[2] = {dim, feat_dim};
        if (!WriteFull(fd, d, 8)) break;
      } else if (hdr[0] == kGAdd && graph) {
        std::vector<int64_t> dst(static_cast<size_t>(n));
        std::vector<float> w;
        keys.resize(static_cast<size_t>(n));
        if (!ReadFull(fd, keys.data(), sizeof(int64_t) * n) ||
            !ReadFull(fd, dst.data(), sizeof(int64_t) * n))
          break;
        if (hdr[1]) {
          w.resize(static_cast<size_t>(n));
          if (!ReadFull(fd, w.data(), sizeof(float) * n)) break;
        }
        ps_graph_add_edges(graph, keys.data(), dst.data(),
                           hdr[1] ? w.data() : nullptr, n);
        uint8_t ok = 1;
        if (!WriteFull(fd, &ok, 1)) break;
      } else if (hdr[0] == kGSamp && graph) {
        int32_t k = 0;
        uint64_t seed = 0;
        keys.resize(static_cast<size_t>(n));
        if (!ReadFull(fd, keys.data(), sizeof(int64_t) * n) ||
            !ReadFull(fd, &k, 4) || !ReadFull(fd, &seed, 8) || k < 0 ||
            k > (1 << 20) || n * static_cast<int64_t>(k) > kElemCap)
          break;  // cap the PRODUCT too: a bad_alloc would kill the process
        std::vector<int64_t> nbrs(static_cast<size_t>(n) * k);
        std::vector<int64_t> counts(static_cast<size_t>(n));
        ps_graph_sample_neighbors(graph, keys.data(), n, k, seed,
                                  nbrs.data(), counts.data(),
                                  hdr[1] ? 1 : 0);
        if (!WriteFull(fd, nbrs.data(), sizeof(int64_t) * n * k) ||
            !WriteFull(fd, counts.data(), sizeof(int64_t) * n))
          break;
      } else if (hdr[0] == kGFeat && graph) {
        keys.resize(static_cast<size_t>(n));
        vals.resize(static_cast<size_t>(n) * feat_dim);
        if (!ReadFull(fd, keys.data(), sizeof(int64_t) * n)) break;
        ps_graph_get_feature(graph, keys.data(), vals.data(), n);
        if (!WriteFull(fd, vals.data(), sizeof(float) * n * feat_dim))
          break;
      } else if (hdr[0] == kGSetF && graph) {
        keys.resize(static_cast<size_t>(n));
        vals.resize(static_cast<size_t>(n) * feat_dim);
        if (!ReadFull(fd, keys.data(), sizeof(int64_t) * n) ||
            !ReadFull(fd, vals.data(), sizeof(float) * n * feat_dim))
          break;
        ps_graph_set_feature(graph, keys.data(), vals.data(), n);
        uint8_t ok = 1;
        if (!WriteFull(fd, &ok, 1)) break;
      } else if (hdr[0] == kGNum && graph) {
        int64_t sz = ps_graph_num_nodes(graph);
        if (!WriteFull(fd, &sz, 8)) break;
      } else if (hdr[0] == kPull) {
        keys.resize(static_cast<size_t>(n));
        vals.resize(static_cast<size_t>(n) * dim);
        if (!ReadFull(fd, keys.data(), sizeof(int64_t) * n)) break;
        ps_sparse_pull(table, keys.data(), n, vals.data(), hdr[1] ? 1 : 0);
        if (!WriteFull(fd, vals.data(), sizeof(float) * n * dim)) break;
      } else if (hdr[0] == kPush) {
        keys.resize(static_cast<size_t>(n));
        vals.resize(static_cast<size_t>(n) * dim);
        float lr = 0.0f;
        if (!ReadFull(fd, keys.data(), sizeof(int64_t) * n) ||
            !ReadFull(fd, vals.data(), sizeof(float) * n * dim) ||
            !ReadFull(fd, &lr, 4))
          break;
        ps_sparse_push(table, keys.data(), n, vals.data(), lr);
        uint8_t ok = 1;
        if (!WriteFull(fd, &ok, 1)) break;
      } else if (hdr[0] == kSize) {
        int64_t sz = ps_sparse_size(table);
        if (!WriteFull(fd, &sz, 8)) break;
      } else {
        break;
      }
    }
    ::shutdown(fd, SHUT_RDWR);  // close deferred to ps_server_stop
  }

  void AcceptLoop() {
    for (;;) {
      sockaddr_in peer{};
      socklen_t len = sizeof(peer);
      int fd = ::accept(listen_fd, reinterpret_cast<sockaddr*>(&peer), &len);
      if (fd < 0) {
        if (stop.load()) return;
        // persistent error (e.g. EMFILE): don't busy-spin the core
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
        std::lock_guard<std::mutex> lk(conn_mu);
        Reap();
        continue;
      }
      std::lock_guard<std::mutex> lk(conn_mu);
      Reap();
      conns.emplace_back(new Conn(fd));
      Conn* c = conns.back().get();
      c->th = std::thread([this, c]() { Serve(c); });
    }
  }
};

struct Client {
  int fd = -1;
  int dim = 0;
  int feat_dim = 0;
  std::mutex mu;  // serialize request/response pairs (blocking API)
  // pipelined API: sends and recvs lock independently so requests can
  // be written while earlier responses are still being read — the
  // server handles one connection's frames sequentially and TCP
  // preserves order, so replies match requests FIFO (no sequence
  // numbers needed on an ordered byte stream; the reference brpc
  // client multiplexes by call id because brpc responds out of order)
  std::mutex send_mu;
  std::mutex recv_mu;
};

}  // namespace

extern "C" {

// Start serving `sparse_table` (a ps_sparse_create handle) — and
// optionally `graph_table` (a ps_graph_create handle, null for none) —
// on `port` (0 = ephemeral). Returns a server handle or null.
void* ps_server_start2(void* sparse_table, int dim, void* graph_table,
                       int feat_dim, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  // all interfaces: servers must be reachable from OTHER hosts (the
  // multi-host PS topology); the endpoint string advertised to trainers
  // is chosen by the Python layer
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 64) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  auto* s = new Server();
  s->table = sparse_table;
  s->graph = graph_table;
  s->feat_dim = feat_dim;
  s->dim = dim;
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread([s]() { s->AcceptLoop(); });
  return s;
}

void* ps_server_start(void* sparse_table, int dim, int port) {
  return ps_server_start2(sparse_table, dim, nullptr, 0, port);
}

int ps_server_port(void* h) { return static_cast<Server*>(h)->port; }

void ps_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  s->stop.store(true);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    std::lock_guard<std::mutex> lk(s->conn_mu);
    for (auto& c : s->conns) {
      ::shutdown(c->fd, SHUT_RDWR);  // unblock any in-flight read
      if (c->th.joinable()) c->th.join();
      ::close(c->fd);
    }
    s->conns.clear();
  }
  delete s;
}

void* ps_client_connect(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  uint8_t hdr[2] = {kHello2, 0};
  int64_t n = 0;
  int32_t dims[2] = {0, 0};
  if (!WriteFull(fd, hdr, 2) || !WriteFull(fd, &n, 8) ||
      !ReadFull(fd, dims, 8)) {
    ::close(fd);
    return nullptr;
  }
  auto* c = new Client();
  c->fd = fd;
  c->dim = dims[0];
  c->feat_dim = dims[1];
  return c;
}

int ps_client_dim(void* h) { return static_cast<Client*>(h)->dim; }

int ps_client_feat_dim(void* h) {
  return static_cast<Client*>(h)->feat_dim;
}

int ps_client_graph_add_edges(void* h, const int64_t* src,
                              const int64_t* dst, const float* w,
                              int64_t n) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t hdr[2] = {kGAdd, static_cast<uint8_t>(w ? 1 : 0)};
  uint8_t ok = 0;
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !WriteFull(c->fd, src, sizeof(int64_t) * n) ||
      !WriteFull(c->fd, dst, sizeof(int64_t) * n) ||
      (w && !WriteFull(c->fd, w, sizeof(float) * n)) ||
      !ReadFull(c->fd, &ok, 1))
    return 0;
  return ok ? 1 : 0;
}

int ps_client_graph_sample(void* h, const int64_t* keys, int64_t n, int k,
                           uint64_t seed, int64_t* out, int64_t* counts,
                           int weighted) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t hdr[2] = {kGSamp, static_cast<uint8_t>(weighted ? 1 : 0)};
  int32_t k32 = k;
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !WriteFull(c->fd, keys, sizeof(int64_t) * n) ||
      !WriteFull(c->fd, &k32, 4) || !WriteFull(c->fd, &seed, 8) ||
      !ReadFull(c->fd, out, sizeof(int64_t) * n * k) ||
      !ReadFull(c->fd, counts, sizeof(int64_t) * n))
    return 0;
  return 1;
}

int ps_client_graph_feature(void* h, const int64_t* keys, int64_t n,
                            float* out) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t hdr[2] = {kGFeat, 0};
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !WriteFull(c->fd, keys, sizeof(int64_t) * n) ||
      !ReadFull(c->fd, out, sizeof(float) * n * c->feat_dim))
    return 0;
  return 1;
}

int ps_client_graph_set_feature(void* h, const int64_t* keys, int64_t n,
                                const float* feats) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t hdr[2] = {kGSetF, 0};
  uint8_t ok = 0;
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !WriteFull(c->fd, keys, sizeof(int64_t) * n) ||
      !WriteFull(c->fd, feats, sizeof(float) * n * c->feat_dim) ||
      !ReadFull(c->fd, &ok, 1))
    return 0;
  return ok ? 1 : 0;
}

int64_t ps_client_graph_num_nodes(void* h) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t hdr[2] = {kGNum, 0};
  int64_t n = 0, sz = -1;
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !ReadFull(c->fd, &sz, 8))
    return -1;
  return sz;
}

int ps_client_pull(void* h, const int64_t* keys, int64_t n, float* out,
                   int create_missing) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t hdr[2] = {kPull, static_cast<uint8_t>(create_missing ? 1 : 0)};
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !WriteFull(c->fd, keys, sizeof(int64_t) * n) ||
      !ReadFull(c->fd, out, sizeof(float) * n * c->dim))
    return 0;
  return 1;
}

int ps_client_push(void* h, const int64_t* keys, int64_t n,
                   const float* grads, float lr) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t hdr[2] = {kPush, 0};
  uint8_t ok = 0;
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !WriteFull(c->fd, keys, sizeof(int64_t) * n) ||
      !WriteFull(c->fd, grads, sizeof(float) * n * c->dim) ||
      !WriteFull(c->fd, &lr, 4) || !ReadFull(c->fd, &ok, 1))
    return 0;
  return ok ? 1 : 0;
}

// -- pipelined halves (brpc_ps_client.cc:120-210 async-stub parity) ------
// A caller keeps several requests in flight on one connection: issue
// *_send k times, then *_recv k times (FIFO). The blocking calls above
// take c->mu only, so do NOT interleave blocking and pipelined calls on
// one connection (the Python layer never does).

int ps_client_pull_send(void* h, const int64_t* keys, int64_t n,
                        int create_missing) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->send_mu);
  uint8_t hdr[2] = {kPull, static_cast<uint8_t>(create_missing ? 1 : 0)};
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !WriteFull(c->fd, keys, sizeof(int64_t) * n))
    return 0;
  return 1;
}

int ps_client_pull_recv(void* h, float* out, int64_t n) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->recv_mu);
  return ReadFull(c->fd, out, sizeof(float) * n * c->dim) ? 1 : 0;
}

int ps_client_push_send(void* h, const int64_t* keys, int64_t n,
                        const float* grads, float lr) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->send_mu);
  uint8_t hdr[2] = {kPush, 0};
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !WriteFull(c->fd, keys, sizeof(int64_t) * n) ||
      !WriteFull(c->fd, grads, sizeof(float) * n * c->dim) ||
      !WriteFull(c->fd, &lr, 4))
    return 0;
  return 1;
}

int ps_client_push_recv(void* h) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->recv_mu);
  uint8_t ok = 0;
  if (!ReadFull(c->fd, &ok, 1)) return 0;
  return ok ? 1 : 0;
}

int ps_client_graph_sample_send(void* h, const int64_t* keys, int64_t n,
                                int k, uint64_t seed, int weighted) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->send_mu);
  uint8_t hdr[2] = {kGSamp, static_cast<uint8_t>(weighted ? 1 : 0)};
  int32_t k32 = k;
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !WriteFull(c->fd, keys, sizeof(int64_t) * n) ||
      !WriteFull(c->fd, &k32, 4) || !WriteFull(c->fd, &seed, 8))
    return 0;
  return 1;
}

int ps_client_graph_sample_recv(void* h, int64_t n, int k, int64_t* out,
                                int64_t* counts) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->recv_mu);
  if (!ReadFull(c->fd, out, sizeof(int64_t) * n * k) ||
      !ReadFull(c->fd, counts, sizeof(int64_t) * n))
    return 0;
  return 1;
}

int64_t ps_client_size(void* h) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t hdr[2] = {kSize, 0};
  int64_t n = 0, sz = -1;
  if (!WriteFull(c->fd, hdr, 2) || !WriteFull(c->fd, &n, 8) ||
      !ReadFull(c->fd, &sz, 8))
    return -1;
  return sz;
}

void ps_client_close(void* h) {
  auto* c = static_cast<Client*>(h);
  ::close(c->fd);
  delete c;
}

}  // extern "C"
