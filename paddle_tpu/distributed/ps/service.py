"""Multi-host parameter-server service: RPC servers + key-hash routing.

Capability map (reference): distributed/service/brpc_ps_server.cc /
brpc_ps_client.cc (RPC pull/push of sharded tables) and
service/communicator.h:197 (the async Communicator: trainers push grads to
a send queue drained by a background thread — "geo"-style bounded
staleness). The transport here is the fresh blocking-socket layer in
csrc/ps/ps_service.cc; this module adds what the reference's
`table/common_sparse_table.cc` sharding does across hosts: every logical
key is owned by exactly one server, chosen by the same 64-bit hash mix the
native table uses internally (``table.shard_keys``).

Topology: each training process typically hosts ONE ``PsServer`` (its key
shard) and a ``DistributedSparseTable`` client routing to ALL servers —
rendezvous of "host:port" endpoints is left to the launcher (env vars /
shared filesystem), mirroring PADDLE_PSERVER_ENDPOINTS.
"""
from __future__ import annotations

import ctypes
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence

import numpy as np

from .native import lib
from .table import SparseTable, shard_keys, _as_f32, _as_i64, _fp, _ip


class PsServer:
    """Serves one key shard of a sparse table — and optionally one NODE
    shard of a graph table (``graph_feat_dim``) — over TCP (reference:
    brpc_ps_server.cc serving common_sparse_table + common_graph_table).
    Owns the tables; keeps them accessible in-process (e.g. for
    checkpointing via ``table.save``)."""

    def __init__(self, dim: int, optimizer: str = "adagrad", port: int = 0,
                 host: str = "127.0.0.1",
                 graph_feat_dim: Optional[int] = None, **table_kwargs):
        from .table import GraphTable
        self.table = SparseTable(dim, optimizer, **table_kwargs)
        self.graph = (GraphTable(graph_feat_dim)
                      if graph_feat_dim is not None else None)
        self._lib = lib()
        self._h = self._lib.ps_server_start2(
            self.table._h, dim,
            self.graph._h if self.graph is not None else None,
            graph_feat_dim or 0, port)
        if not self._h:
            raise OSError(f"failed to start PS server on port {port}")
        self.host = host
        self.port = int(self._lib.ps_server_port(self._h))

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def stop(self):
        if getattr(self, "_h", None):
            self._lib.ps_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class _Conn:
    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self._lib = lib()
        self._h = self._lib.ps_client_connect(host.encode(), int(port))
        if not self._h:
            raise ConnectionError(f"cannot connect to PS at {endpoint}")
        self.dim = int(self._lib.ps_client_dim(self._h))

    @property
    def feat_dim(self) -> int:
        return int(self._lib.ps_client_feat_dim(self._h))

    def graph_add_edges(self, src, dst, w=None):
        wp = _fp(w) if w is not None else None
        if not self._lib.ps_client_graph_add_edges(self._h, _ip(src),
                                                   _ip(dst), wp, src.size):
            raise ConnectionError("PS graph add_edges RPC failed")

    def graph_sample(self, keys, k, seed, weighted):
        out = np.empty((keys.size, k), dtype=np.int64)
        counts = np.empty((keys.size,), dtype=np.int64)
        if not self._lib.ps_client_graph_sample(
                self._h, _ip(keys), keys.size, int(k), int(seed), _ip(out),
                _ip(counts), 1 if weighted else 0):
            raise ConnectionError("PS graph sample RPC failed")
        return out, counts

    def graph_feature(self, keys, feat_dim):
        out = np.empty((keys.size, feat_dim), dtype=np.float32)
        if not self._lib.ps_client_graph_feature(self._h, _ip(keys),
                                                 keys.size, _fp(out)):
            raise ConnectionError("PS graph feature RPC failed")
        return out

    def graph_set_feature(self, keys, feats):
        if not self._lib.ps_client_graph_set_feature(self._h, _ip(keys),
                                                     keys.size, _fp(feats)):
            raise ConnectionError("PS graph set_feature RPC failed")

    def graph_num_nodes(self) -> int:
        n = int(self._lib.ps_client_graph_num_nodes(self._h))
        if n < 0:
            raise ConnectionError("PS graph num_nodes RPC failed")
        return n

    def pull(self, keys: np.ndarray, create: bool) -> np.ndarray:
        out = np.empty((keys.size, self.dim), dtype=np.float32)
        if not self._lib.ps_client_pull(self._h, _ip(keys), keys.size,
                                        _fp(out), 1 if create else 0):
            raise ConnectionError("PS pull RPC failed")
        return out

    def push(self, keys: np.ndarray, grads: np.ndarray, lr: float):
        if not self._lib.ps_client_push(self._h, _ip(keys), keys.size,
                                        _fp(grads), lr):
            raise ConnectionError("PS push RPC failed")

    def size(self) -> int:
        return int(self._lib.ps_client_size(self._h))

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ps_client_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _ShardedClient:
    """Shared key-hash routing + concurrent per-shard fan-out (each _Conn
    has its own socket+lock — the reference brpc client's parallel
    fan-out; sequential round trips would cost n_shards x RTT)."""

    def __init__(self, endpoints: Sequence[str]):
        assert endpoints, "need at least one PS endpoint"
        self.conns: List[_Conn] = [_Conn(e) for e in endpoints]
        self.n_shards = len(self.conns)
        self._pool = (ThreadPoolExecutor(max_workers=self.n_shards)
                      if self.n_shards > 1 else None)

    def _route(self, keys: np.ndarray):
        assign = shard_keys(keys, self.n_shards)
        for s in range(self.n_shards):
            idx = np.nonzero(assign == s)[0]
            if idx.size:
                yield s, idx

    def _fan_out(self, jobs):
        if self._pool is None or len(jobs) <= 1:
            for j in jobs:
                j()
            return
        futs = [self._pool.submit(j) for j in jobs]
        for f in futs:
            f.result()  # re-raises ConnectionError from any shard

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=True)
        for c in self.conns:
            c.close()


class DistributedSparseTable(_ShardedClient):
    """Client view of a sparse table sharded across PS servers by key hash.

    ``pull``/``push`` route each key to its owning server (reference:
    brpc_ps_client pull_sparse/push_sparse fan-out). ``async_mode`` drains
    pushes from a bounded queue on a background thread — the reference
    Communicator's geo/async semantics (communicator.h:197): training does
    not block on the push RPC, staleness is bounded by the queue depth.
    """

    def __init__(self, endpoints: Sequence[str], async_mode: bool = False,
                 max_pending: int = 8):
        super().__init__(endpoints)
        try:
            self.dim = self.conns[0].dim
            for e, c in zip(endpoints, self.conns):
                if c.dim != self.dim:
                    raise ValueError(
                        f"PS dim mismatch: {endpoints[0]} serves dim "
                        f"{self.dim} but {e} serves dim {c.dim}")
        except Exception:
            super().close()  # don't leak sockets/pool on a failed build
            raise
        self.async_mode = async_mode
        self._err: Optional[BaseException] = None
        if async_mode:
            self._q: "queue.Queue" = queue.Queue(maxsize=max_pending)
            self._worker = threading.Thread(target=self._drain, daemon=True)
            self._worker.start()

    def pull(self, keys, create_missing: bool = True) -> np.ndarray:
        keys = _as_i64(keys)
        flat = keys.reshape(-1)
        out = np.empty((flat.size, self.dim), dtype=np.float32)

        def job(s, idx):
            def go():
                out[idx] = self.conns[s].pull(
                    np.ascontiguousarray(flat[idx]), create_missing)
            return go

        self._fan_out([job(s, idx) for s, idx in self._route(flat)])
        return out.reshape(keys.shape + (self.dim,))

    def _push_sync(self, keys: np.ndarray, grads: np.ndarray, lr: float):
        def job(s, idx):
            def go():
                self.conns[s].push(np.ascontiguousarray(keys[idx]),
                                   np.ascontiguousarray(grads[idx]), lr)
            return go

        self._fan_out([job(s, idx) for s, idx in self._route(keys)])

    def push(self, keys, grads, lr: float):
        keys = _as_i64(keys).reshape(-1)
        grads = _as_f32(grads).reshape(keys.size, self.dim)
        if not self.async_mode:
            self._push_sync(keys, grads, lr)
            return
        if self._err is not None:
            err, self._err = self._err, None
            raise err
        # copies: the caller may reuse/donate its buffers
        self._q.put((keys.copy(), grads.copy(), float(lr)))

    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                self._push_sync(*item)
            except BaseException as e:  # surfaced on next push/flush
                self._err = e
            finally:
                self._q.task_done()

    def flush(self):
        """Barrier for async pushes (reference Communicator barrier)."""
        if self.async_mode:
            self._q.join()
            if self._err is not None:
                err, self._err = self._err, None
                raise err

    def shard_sizes(self) -> List[int]:
        return [c.size() for c in self.conns]

    def close(self):
        if self.async_mode and self._worker.is_alive():
            self._q.join()
            self._q.put(None)
            self._worker.join(timeout=5)
        super().close()


class DistributedGraphTable(_ShardedClient):
    """Client view of a graph table NODE-partitioned across PS servers
    (reference: common_graph_table.cc:1-596 served by brpc — each server
    owns the adjacency + features of its hash shard of the node space).

    Edges live with their SOURCE node's owner, so neighbor sampling for
    a node is one RPC to its owner; sampled neighbor ids may belong to
    ANY server — multi-hop sampling (``sample_hops``) re-routes each
    hop's frontier to the owning servers, which is the cross-server
    walk the reference's graph service performs.
    """

    def __init__(self, endpoints: Sequence[str]):
        super().__init__(endpoints)
        try:
            self.feat_dim = self.conns[0].feat_dim
            for e, c in zip(endpoints, self.conns):
                if c.feat_dim != self.feat_dim:
                    raise ValueError(f"graph feat_dim mismatch at {e}")
            if self.feat_dim <= 0:
                raise ValueError(
                    "endpoints serve no graph table (PsServer was built "
                    "without graph_feat_dim) — graph RPCs against them "
                    "would close the connection")
        except Exception:
            super().close()  # don't leak sockets/pool on a failed build
            raise

    def add_edges(self, src, dst, weights=None):
        src = _as_i64(src).reshape(-1)
        dst = _as_i64(dst).reshape(-1)
        w = _as_f32(weights).reshape(-1) if weights is not None else None

        def job(s, idx):
            def go():
                self.conns[s].graph_add_edges(
                    np.ascontiguousarray(src[idx]),
                    np.ascontiguousarray(dst[idx]),
                    np.ascontiguousarray(w[idx]) if w is not None
                    else None)
            return go

        self._fan_out([job(s, i) for s, i in self._route(src)])

    def sample_neighbors(self, keys, k: int, seed: int = 0,
                         weighted: bool = False):
        """(neighbors (N, k) padded with -1, counts (N,)): each key's
        sample comes from its owning server's adjacency shard."""
        keys = _as_i64(keys).reshape(-1)
        out = np.full((keys.size, k), -1, dtype=np.int64)
        counts = np.zeros((keys.size,), dtype=np.int64)

        def job(s, idx):
            def go():
                o, c = self.conns[s].graph_sample(
                    np.ascontiguousarray(keys[idx]), k, seed, weighted)
                out[idx] = o
                counts[idx] = c
            return go

        self._fan_out([job(s, i) for s, i in self._route(keys)])
        return out, counts

    def sample_hops(self, keys, fanouts: Sequence[int], seed: int = 0,
                    weighted: bool = False):
        """Multi-hop neighborhood sampling: hop h samples ``fanouts[h]``
        neighbors of the previous frontier, re-routing every hop to the
        owners of its (possibly remote) nodes. Returns a list of
        (src (F,), neighbors (F, k), counts (F,)) per hop."""
        frontier = np.unique(_as_i64(keys).reshape(-1))
        out = []
        for h, k in enumerate(fanouts):
            nbrs, counts = self.sample_neighbors(frontier, k,
                                                 seed=seed + h,
                                                 weighted=weighted)
            out.append((frontier, nbrs, counts))
            nxt = nbrs[nbrs >= 0]
            if nxt.size == 0:
                break
            frontier = np.unique(nxt)
        return out

    def node_feature(self, keys) -> np.ndarray:
        keys = _as_i64(keys).reshape(-1)
        out = np.zeros((keys.size, self.feat_dim), dtype=np.float32)

        def job(s, idx):
            def go():
                out[idx] = self.conns[s].graph_feature(
                    np.ascontiguousarray(keys[idx]), self.feat_dim)
            return go

        self._fan_out([job(s, i) for s, i in self._route(keys)])
        return out

    def set_node_feature(self, keys, feats):
        keys = _as_i64(keys).reshape(-1)
        feats = _as_f32(feats).reshape(keys.size, self.feat_dim)

        def job(s, idx):
            def go():
                self.conns[s].graph_set_feature(
                    np.ascontiguousarray(keys[idx]),
                    np.ascontiguousarray(feats[idx]))
            return go

        self._fan_out([job(s, i) for s, i in self._route(keys)])

    def num_nodes(self) -> int:
        return sum(c.graph_num_nodes() for c in self.conns)
