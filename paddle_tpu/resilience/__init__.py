"""Fault-resilient training runtime.

Six small parts compose the recovery story (see each module's docstring):

- ``faults``  — deterministic fault injection (every recovery path has a
  reproducible trigger)
- ``retry``   — jittered exponential backoff at the I/O seams
- ``guard``   — fused all-finite reduction for the in-graph NaN step-guard
  (wired into distributed.engine + amp.GradScaler)
- ``runner``  — ``run_resilient``: auto-resume, graceful SIGTERM/SIGINT
  drain, elastic-restart and simulated-crash recovery
- ``elastic`` — the elastic multi-host runtime: coordinated restore
  barrier (min-reduced common step + barrier before the first train
  step), scale-up/down remesh + reshard through the sharded checkpoint,
  comm_err residual remapping; ``hostsim`` runs N subprocess "hosts"
  over the file-KV so the whole thing is testable on one CPU box.
- ``integrity`` — silent-corruption defense: in-graph replica-divergence
  fingerprinting (checked every ``integrity_check_every`` steps at zero
  cost in between), majority-vote replica quarantine, per-array content
  digests behind the deep checkpoint verify, deterministic step replay
  (SDC vs nondeterminism), and the hang watchdog that turns a wedged
  host into an ordinary host-loss remesh.

Crash-consistent checkpoint commits live with the checkpoint code itself
(``distributed.checkpoint``: manifest write/verify + fallback restore).
"""
from . import faults  # noqa: F401
from .elastic import (CoordinatorTimeout, ElasticRuntime,  # noqa: F401
                      FileCoordinator, coordinated_restore,
                      data_parallel_remesh_fn, remap_comm_err,
                      reshard_trainer)
from .faults import HostLost, SimulatedCrash, inject  # noqa: F401
from .guard import all_finite, all_finite_value  # noqa: F401
from .integrity import (HangWatchdog, compare_digests,  # noqa: F401
                        count_fingerprint_collectives, inject_param_flip,
                        quarantine_outliers, replay_step, tree_digests)
from .retry import RetryBytesExhausted, call_with_retry, retry  # noqa: F401
from .runner import RunResult, run_resilient  # noqa: F401

__all__ = ["faults", "SimulatedCrash", "HostLost", "inject", "all_finite",
           "all_finite_value", "retry", "call_with_retry",
           "RetryBytesExhausted", "RunResult", "run_resilient",
           "CoordinatorTimeout", "FileCoordinator", "coordinated_restore",
           "remap_comm_err", "reshard_trainer", "ElasticRuntime",
           "data_parallel_remesh_fn", "HangWatchdog", "tree_digests",
           "compare_digests", "count_fingerprint_collectives",
           "inject_param_flip", "quarantine_outliers", "replay_step"]
