"""paddle_tpu.telemetry.slo — rolling-window burn-rate monitoring.

A small SLO monitor over the metrics registry: each rule watches the
ratio of two counter families (numerator / denominator, summed across
label series) over a rolling time window and fires when the burn rate
crosses a threshold — e.g. "more than 30% of admissions shed over the
last 5 seconds".  Firing bumps ``slo_alerts_total{rule}`` and invokes
the rule's callback; the default callback dumps the flight recorder
(``flight_slo_<rule>_<step>.json`` via reason ``slo_<rule>``), which is
how "shedding crossed a burn-rate threshold" becomes a post-mortem
artifact.

Hysteresis: a rule that fired stays latched until its burn rate falls
below half the threshold, so a sustained breach produces one alert (and
one dump), not one per poll.

Actions: each rule carries a registry of alert actions
(``rule.on_alert(fn)`` registers ``fn(rule, burn)``; usable as a
decorator) so breaches can trigger behavior — scale up a serving fleet,
open a breaker — not just dumps.  When no action is registered the
flight-recorder dump remains the default.  ``rule.on_clear(fn)``
registers the symmetric unlatch hook, fired once when a latched rule's
burn falls below threshold/2.  Action exceptions are swallowed:
monitoring must never take down the monitored.

``poll(now=)`` takes an explicit timestamp so tests drive time directly;
``maybe_poll`` rate-limits polling for hot-path callers (the serving
admission path pokes it on shed).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional

__all__ = ["SloRule", "SloMonitor", "get_monitor", "set_monitor",
           "maybe_poll", "install_shed_rule", "reset", "default_alert"]


def _registry():
    from paddle_tpu import telemetry
    return telemetry.get_registry()


def _counter_total(reg, family: str) -> float:
    m = reg.get(family)
    if m is None:
        return 0.0
    try:
        return float(sum(m.series().values()))
    except Exception:
        return 0.0


class SloRule:
    """Burn rate = Δnumerator / Δdenominator over ``window_s``."""

    def __init__(self, name: str, numerator: str, denominator: str,
                 threshold: float, window_s: float = 5.0,
                 min_denominator: float = 10.0,
                 on_alert: Optional[Callable] = None):
        self.name = name
        self.numerator = numerator
        self.denominator = denominator
        self.threshold = threshold
        self.window_s = window_s
        self.min_denominator = min_denominator
        self._alert_actions: List[Callable] = []
        self._clear_actions: List[Callable] = []
        if on_alert is not None:
            self._alert_actions.append(on_alert)
        self._samples = deque()   # (t, num_total, den_total)
        self.latched = False
        self.alerts = 0
        self.clears = 0
        self.last_burn = 0.0

    def on_alert(self, fn: Callable) -> Callable:
        """Register ``fn(rule, burn)`` to run when this rule fires.

        Registering any action replaces the default flight dump; register
        ``slo.default_alert`` explicitly to keep the dump alongside other
        actions.  Returns ``fn`` so this works as a decorator.
        """
        self._alert_actions.append(fn)
        return fn

    def on_clear(self, fn: Callable) -> Callable:
        """Register ``fn(rule, burn)`` to run when a latched rule
        unlatches (burn fell below threshold/2).  Decorator-friendly."""
        self._clear_actions.append(fn)
        return fn

    def sample(self, now: float, reg) -> Optional[float]:
        """Record a sample; return the burn rate when the rule fires.

        Unlatching bumps ``self.clears`` — ``SloMonitor.poll`` watches
        the latch transition to run ``on_clear`` actions.
        """
        num = _counter_total(reg, self.numerator)
        den = _counter_total(reg, self.denominator)
        self._samples.append((now, num, den))
        while self._samples and self._samples[0][0] < now - self.window_s:
            self._samples.popleft()
        t0, num0, den0 = self._samples[0]
        d_num, d_den = num - num0, den - den0
        if d_den < self.min_denominator:
            return None
        burn = d_num / d_den if d_den > 0 else 0.0
        self.last_burn = burn
        if self.latched:
            if burn < self.threshold / 2.0:
                self.latched = False
                self.clears += 1
            return None
        if burn > self.threshold:
            self.latched = True
            self.alerts += 1
            return burn
        return None


class SloMonitor:
    def __init__(self, rules: List[SloRule],
                 registry=None, min_poll_interval_s: float = 0.25):
        self.rules = rules
        self._registry = registry
        self.min_poll_interval_s = min_poll_interval_s
        self._lock = threading.Lock()
        self._last_poll = 0.0

    def _reg(self):
        return self._registry if self._registry is not None else _registry()

    def poll(self, now: Optional[float] = None):
        """Sample every rule; fire callbacks for threshold crossings."""
        now = time.monotonic() if now is None else now
        reg = self._reg()
        fired = []
        cleared = []
        with self._lock:
            self._last_poll = now
            for rule in self.rules:
                was_latched = rule.latched
                burn = rule.sample(now, reg)
                if burn is not None:
                    fired.append((rule, burn))
                elif was_latched and not rule.latched:
                    cleared.append((rule, rule.last_burn))
        for rule, burn in fired:
            reg.counter("slo_alerts_total").inc(rule=rule.name)
            actions = rule._alert_actions or [_default_alert]
            for cb in actions:
                try:
                    cb(rule, burn)
                except Exception:
                    pass   # monitoring must never take down the monitored
        for rule, burn in cleared:
            for cb in rule._clear_actions:
                try:
                    cb(rule, burn)
                except Exception:
                    pass
        return fired

    def maybe_poll(self, now: Optional[float] = None):
        """Rate-limited poll for hot-path callers; cheap when recently
        polled."""
        now = time.monotonic() if now is None else now
        if now - self._last_poll < self.min_poll_interval_s:
            return []
        return self.poll(now)


def _default_alert(rule: SloRule, burn: float):
    from . import flight
    flight.dump(f"slo_{rule.name}",
                extra={"burn_rate": burn, "threshold": rule.threshold,
                       "window_s": rule.window_s})


#: Public name for the default dump action, so callers that register
#: their own ``on_alert`` actions can keep the dump too.
default_alert = _default_alert


_monitor: Optional[SloMonitor] = None


def get_monitor() -> Optional[SloMonitor]:
    return _monitor


def set_monitor(monitor: Optional[SloMonitor]):
    global _monitor
    _monitor = monitor


def reset():
    set_monitor(None)


def install_shed_rule(threshold: float = 0.3, window_s: float = 5.0,
                      min_denominator: float = 10.0,
                      on_alert: Optional[Callable] = None) -> SloMonitor:
    """The default serving SLO: shed burn rate over admissions.

    ``serving_requests_shed_total / serving_requests_total`` over the
    window; on breach, flight-dump with reason ``slo_shed_burn``.
    """
    mon = SloMonitor([
        SloRule("shed_burn",
                numerator="serving_requests_shed_total",
                denominator="serving_requests_total",
                threshold=threshold, window_s=window_s,
                min_denominator=min_denominator, on_alert=on_alert),
    ])
    set_monitor(mon)
    return mon


def maybe_poll():
    """Module-level poke used by hot paths; no-op without a monitor."""
    mon = _monitor
    if mon is not None:
        mon.maybe_poll()
