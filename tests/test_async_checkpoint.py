"""Async hierarchical checkpointing (ISSUE 13): the off-step-path commit
pipeline, tiered deep/cheap saves, the dirty-flag x in-flight-snapshot
interaction, crash-consistency of aborted commits, and save-stall
attribution (step_time must not fold checkpoint wall in)."""
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, telemetry
from paddle_tpu.distributed import checkpoint as ck
from paddle_tpu.distributed.checkpoint import (PENDING_PREFIX,
                                               CheckpointManager)
from paddle_tpu.distributed.engine import ParallelTrainer
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.resilience import faults, run_resilient
from paddle_tpu.resilience.elastic import FileCoordinator, coordinated_restore
from paddle_tpu.resilience.integrity import compare_digests, tree_digests


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def fresh_registry():
    old_reg = telemetry.get_registry()
    old_on = telemetry.enabled()
    reg = telemetry.Registry()
    telemetry._set_registry(reg)
    telemetry.enable(True)
    yield reg
    telemetry._set_registry(old_reg)
    telemetry.enable(old_on)


def _series_total(reg, name):
    series = reg.to_dict().get(name, {}).get("series", {})
    return sum(series.values())


def _state(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": rng.randn(32, 8).astype(np.float32),
            "b": rng.randn(8).astype(np.float32),
            "step": np.int64(seed)}  # np scalar: exercises promotion


def _mlp_trainer(check_every=0, seed=7):
    paddle.seed(seed)
    mesh = build_mesh({"data": 4 if check_every else 2})

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(8, 16)
            self.l2 = nn.Linear(16, 4)

        def forward(self, x):
            return self.l2(nn.functional.relu(self.l1(x)))

    model = MLP()
    opt = paddle.optimizer.Momentum(0.05, momentum=0.9,
                                    parameters=model.parameters())
    kw = {}
    if check_every:
        kw = dict(grad_sync="int8", grad_sync_block=8,
                  integrity_check_every=check_every)
    return ParallelTrainer(model, opt,
                           lambda out, y: jnp.mean((out - y) ** 2),
                           mesh=mesh, **kw)


def _loader(n=4, batch=8, seed=3):
    rng = np.random.RandomState(seed)
    return [(rng.randn(batch, 8).astype(np.float32),
             rng.randn(batch, 4).astype(np.float32)) for _ in range(n)]


# ---------------------------------------------------------------------------
# async commit pipeline
# ---------------------------------------------------------------------------

class TestAsyncPipeline:
    def test_roundtrip_bitwise_identical_to_sync(self, tmp_path):
        states = [_state(i) for i in range(3)]
        ms = CheckpointManager(str(tmp_path / "sync"), use_async=False,
                               max_to_keep=5)
        ma = CheckpointManager(str(tmp_path / "async"), async_commit=True,
                               max_to_keep=5)
        for i, st in enumerate(states):
            ms.save(i, st)
            ma.save(i, st)
            ma.flush()  # commit each so none is superseded
        for i, st in enumerate(states):
            ref = tree_digests(st)
            assert not compare_digests(ref, tree_digests(ms.restore(i)))
            assert not compare_digests(ref, tree_digests(ma.restore(i)))
        assert ma.committed_total == 3 and ma.accounted()
        ms.close()
        ma.close()

    def test_save_returns_before_commit(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              commit_delay=0.3)
        t0 = time.perf_counter()
        m.save(1, _state())
        assert time.perf_counter() - t0 < 0.25  # snapshot only, no IO wait
        assert m.inflight() >= 1
        assert m.flush()
        assert m.inflight() == 0
        assert m.latest_valid_step() == 1
        m.close()

    def test_double_buffer_supersedes_staged_snapshot(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              max_to_keep=8)
        m.pause_commits()
        for s in (1, 2, 3, 4):
            m.save(s, _state(s))
        m.resume_commits()
        assert m.flush()
        # only the newest staged snapshot commits; the rest superseded
        assert m.committed_total == 1
        assert m.superseded_total == 3
        assert m.snapshots_total == 4 and m.accounted()
        assert m.latest_valid_step() == 4
        assert sorted(m.all_steps() or []) == [4]
        m.close()

    def test_restore_flushes_inflight_commit(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              commit_delay=0.15)
        st = _state(5)
        m.save(5, st)
        out = m.restore()  # must see the committed step, not race it
        assert m.last_restored_step == 5
        assert not compare_digests(tree_digests(st), tree_digests(out))
        m.close()

    def test_wait_until_finished_drains_pipeline(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              commit_delay=0.05)
        m.save(1, _state())
        m.wait_until_finished()
        assert m.inflight() == 0 and m.latest_valid_step() == 1
        m.close()

    def test_committer_simulated_crash_surfaces_at_flush(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              max_to_keep=5)
        m.save(1, _state(1))
        assert m.flush()
        with faults.inject("ckpt_torn", at_step=2):
            m.save(2, _state(2))
            with pytest.raises(faults.SimulatedCrash):
                m.flush()
        # the aborted step is debris (live marker, no manifest): the
        # previous latest_valid_step is intact and restoring past it
        # costs NO fallback
        assert m.failed_total == 1
        assert m.latest_valid_step() == 1
        m.restore()
        assert m.last_restored_step == 1
        assert m.restore_fallbacks_total == 0
        m.close()

    def test_aborted_commit_debris_reclaimed_on_replay(self, tmp_path):
        root = str(tmp_path)
        m = CheckpointManager(root, async_commit=True, max_to_keep=5)
        m.save(1, _state(1))
        assert m.flush()
        with faults.inject("ckpt_torn", at_step=2):
            m.save(2, _state(2))
            with pytest.raises(faults.SimulatedCrash):
                m.flush()
        assert os.path.exists(os.path.join(root, PENDING_PREFIX + "2"))
        # the restart replays step 2: the new commit must clear the
        # marker and make step 2 the newest valid step
        m.save(2, _state(2))
        assert m.flush()
        assert m.latest_valid_step() == 2
        assert not os.path.exists(os.path.join(root, PENDING_PREFIX + "2"))
        assert m.accounted()
        m.close()


# ---------------------------------------------------------------------------
# dirty flag x in-flight snapshot (the subtle interaction)
# ---------------------------------------------------------------------------

class TestDirtyInflight:
    def test_verdict_between_snapshot_and_commit_suppresses(self, tmp_path):
        dirty = {"v": False}
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              dirty_probe=lambda: dirty["v"])
        m.save(1, _state(1))
        assert m.flush()
        m.pause_commits()
        m.save(2, _state(2))      # tainted snapshot staged, not committed
        dirty["v"] = True          # the quarantine verdict lands NOW
        m.resume_commits()
        assert m.flush()
        assert m.suppressed_dirty_total == 1
        assert m.latest_valid_step() == 1
        assert 2 not in (m.all_steps() or [])  # provably never committed
        m.close()

    def test_later_clean_check_reenables_saves(self, tmp_path):
        dirty = {"v": False}
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              dirty_probe=lambda: dirty["v"])
        m.save(1, _state(1))
        assert m.flush()
        dirty["v"] = True
        m.save(2, _state(2))
        assert m.flush()
        assert m.suppressed_dirty_total == 1
        dirty["v"] = False         # a later check step came back clean
        m.save(3, _state(3))
        assert m.flush()
        assert m.latest_valid_step() == 3
        assert m.committed_total == 2 and m.accounted()
        m.close()

    def test_drain_flush_suppresses_dirty_and_commits_clean(self, tmp_path):
        """The two drain paths: a clean staged snapshot is flushed to
        disk; a dirty one is suppressed — both leave the pipeline
        accounted."""
        dirty = {"v": False}
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              dirty_probe=lambda: dirty["v"])
        m.pause_commits()
        m.save(1, _state(1))
        m.resume_commits()
        assert m.flush()           # clean path: drain commits it
        assert m.latest_valid_step() == 1
        m.pause_commits()
        m.save(2, _state(2))
        dirty["v"] = True
        m.resume_commits()
        assert m.flush()           # dirty path: drain suppresses it
        assert m.suppressed_dirty_total == 1
        assert m.latest_valid_step() == 1 and m.accounted()
        m.close()

    def test_runner_quarantine_verdict_suppresses_inflight(
            self, fresh_registry, tmp_path):
        """End-to-end: param_flip SDC mid-run with slow commits — the
        divergence verdict must suppress whatever snapshot is in flight,
        the tainted step must never land on disk, and the run must
        recover and finish with the pipeline fully accounted."""
        mgr = CheckpointManager(str(tmp_path / "ckpt"), async_commit=True,
                                max_to_keep=12, commit_delay=0.08)
        with faults.inject("param_flip", at_step=5, seed=11) as f:
            res = run_resilient(_mlp_trainer(check_every=2), _loader(),
                                steps=8, manager=mgr, save_every=1,
                                handle_signals=False)
        assert f.fired == 1
        assert res.exit_code == 0 and res.divergences >= 1
        # the verdict raced at least one in-flight snapshot into
        # suppression (commit_delay guarantees commits lag the loop)
        assert mgr.suppressed_dirty_total >= 1
        assert _series_total(fresh_registry, "ckpt_suppressed_total") >= 1
        mgr.flush()
        assert mgr.accounted()
        # after the clean check re-enabled saves the run kept committing
        assert mgr.latest_valid_step() is not None
        mgr.close()

    def test_runner_registers_dirty_probe(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_commit=True)
        assert mgr.dirty_probe is None
        run_resilient(_mlp_trainer(), _loader(), steps=2, manager=mgr,
                      save_every=1, handle_signals=False)
        assert callable(mgr.dirty_probe)
        assert mgr.dirty_probe() is False  # run ended clean
        mgr.close()

    def test_runner_sigterm_drain_flushes_async_save(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_commit=True,
                                max_to_keep=12, commit_delay=0.05)
        with faults.inject("sigterm", at_step=3):
            res = run_resilient(_mlp_trainer(), _loader(), steps=8,
                                manager=mgr, save_every=1)
        assert res.exit_code == 143 and res.status == "sigterm"
        # the drain save landed durably before return
        assert mgr.inflight() == 0
        assert mgr.latest_valid_step() == 2
        mgr.close()


# ---------------------------------------------------------------------------
# hierarchical tiers
# ---------------------------------------------------------------------------

class TestTiers:
    def test_deep_every_cadence(self, tmp_path):
        m = CheckpointManager(str(tmp_path), use_async=False,
                              max_to_keep=8, deep_every=2)
        for s in (1, 2, 3, 4):
            m.save(s, _state(s))
        # save #0,#2 are deep (digest-bearing), #1,#3 cheap
        assert m._manifest_arrays(1) and m._manifest_arrays(3)
        assert not m._manifest_arrays(2) and not m._manifest_arrays(4)
        m.close()

    def test_explicit_tier_flag_wins(self, tmp_path):
        m = CheckpointManager(str(tmp_path), use_async=False,
                              max_to_keep=8, deep_every=2)
        m.save(1, _state(1), deep=False)   # would be deep by cadence
        m.save(2, _state(2), deep=True)    # would be cheap by cadence
        assert not m._manifest_arrays(1)
        assert m._manifest_arrays(2)
        m.close()

    def test_async_tiers_digest_from_host_snapshot(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              max_to_keep=8, deep_every=2)
        for s in (1, 2):
            m.save(s, _state(s))
            assert m.flush()
        assert m._manifest_arrays(1) and not m._manifest_arrays(2)
        assert m.verify(1, deep=True) is True
        m.close()

    def test_prefer_deep_picks_newest_deep_anchor(self, tmp_path):
        m = CheckpointManager(str(tmp_path), use_async=False,
                              max_to_keep=8, deep_every=2)
        for s in (1, 2, 3, 4):
            m.save(s, _state(s))
        out = m.restore(prefer_deep=True)
        # step 3 is the newest deep-verified step (4 is cheap)
        assert m.last_restored_step == 3
        assert not compare_digests(tree_digests(_state(3)),
                                   tree_digests(out))
        assert m.restore_fallbacks_total == 0
        m.close()

    def test_prefer_deep_falls_back_through_cheap_tiers(
            self, fresh_registry, tmp_path):
        root = str(tmp_path)
        m = CheckpointManager(root, use_async=False, max_to_keep=8,
                              deep_every=2)
        for s in (1, 2, 3, 4):
            m.save(s, _state(s))

        def _rot(step):
            # flip a payload byte and re-attest its CRC: shallow passes,
            # only the content digests can catch it
            sdir = os.path.join(root, str(step))
            best, size = None, -1
            for r, _d, names in os.walk(sdir):
                if "ocdbt.process_" in r:
                    continue
                for n in names:
                    if n.startswith("MANIFEST"):
                        continue
                    p = os.path.join(r, n)
                    if os.path.getsize(p) > size:
                        best, size = p, os.path.getsize(p)
            with open(best, "r+b") as f:
                f.seek(size // 2)
                b = f.read(1)
                f.seek(size // 2)
                f.write(bytes([b[0] ^ 0x01]))
            import json as _json
            mpath = os.path.join(sdir, ck.MANIFEST_NAME)
            with open(mpath) as f:
                man = _json.load(f)
            man["files"][os.path.relpath(best, sdir)] = {
                "size": os.path.getsize(best), "crc32": ck._crc_file(best)}
            with open(mpath, "w") as f:
                _json.dump(man, f)

        _rot(1)
        _rot(3)  # every deep anchor now carries silent rot
        out = m.restore(prefer_deep=True)
        # both deep steps fall (reason=deep), the newest cheap step wins
        assert m.last_restored_step == 4
        assert not compare_digests(tree_digests(_state(4)),
                                   tree_digests(out))
        assert m.restore_fallbacks_total == 2
        assert _series_total(fresh_registry,
                             "ckpt_restore_fallbacks_total") == 2
        m.close()

    def test_runner_forwards_deep_every(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), use_async=False,
                                max_to_keep=12)
        run_resilient(_mlp_trainer(), _loader(), steps=4, manager=mgr,
                      save_every=1, deep_every=2, handle_signals=False)
        assert mgr.deep_every == 2
        steps = sorted(mgr.all_steps() or [])
        tiers = [bool(mgr._manifest_arrays(s)) for s in steps]
        assert any(tiers) and not all(tiers)  # both tiers present
        # resume restores through the tier-aware path
        mgr2 = CheckpointManager(str(tmp_path), use_async=False,
                                 max_to_keep=12)
        res = run_resilient(_mlp_trainer(), _loader(), steps=6,
                            manager=mgr2, save_every=1, deep_every=2,
                            handle_signals=False)
        assert res.exit_code == 0 and mgr2.last_restored_step is not None
        mgr.close()
        mgr2.close()


# ---------------------------------------------------------------------------
# crash consistency of aborted commits (in-process view)
# ---------------------------------------------------------------------------

class TestAbortedCommitDebris:
    def _make_uncommitted(self, root, m, step):
        """Forge the on-disk shape of a commit killed between payload
        write and manifest: step dir present, no manifest, live marker."""
        m.save(step, _state(step))
        os.remove(os.path.join(root, str(step), ck.MANIFEST_NAME))
        ck._write_pending_marker(root, step)

    def test_uncommitted_step_invisible_no_fallback(self, tmp_path):
        root = str(tmp_path)
        m = CheckpointManager(root, use_async=False, max_to_keep=8)
        for s in (1, 2):
            m.save(s, _state(s))
        self._make_uncommitted(root, m, 3)
        assert m.latest_valid_step() == 2
        m.restore()
        assert m.last_restored_step == 2
        assert m.restore_fallbacks_total == 0
        m.close()

    def test_legacy_manifestless_step_still_restorable(self, tmp_path):
        """No marker + no manifest = a pre-manifest legacy step, NOT
        debris: it must stay restorable (three-valued verify contract)."""
        root = str(tmp_path)
        m = CheckpointManager(root, use_async=False, max_to_keep=8)
        m.save(1, _state(1))
        os.remove(os.path.join(root, "1", ck.MANIFEST_NAME))
        assert m.latest_valid_step() == 1
        m.restore()
        assert m.last_restored_step == 1
        m.close()

    def test_gc_reclaims_debris_and_stale_markers(self, tmp_path):
        root = str(tmp_path)
        m = CheckpointManager(root, use_async=False, max_to_keep=2)
        for s in (1, 2):
            m.save(s, _state(s))
        self._make_uncommitted(root, m, 3)
        # a marker whose step dir never materialized (crash before any
        # byte landed)
        ck._write_pending_marker(root, 9)
        for s in (4, 5):
            m.save(s, _state(s))
        steps = sorted(m.all_steps() or [])
        assert 3 not in steps  # debris collected despite being "newest-ish"
        assert not os.path.exists(os.path.join(root, PENDING_PREFIX + "3"))
        assert not os.path.exists(os.path.join(root, PENDING_PREFIX + "9"))
        assert m.latest_valid_step() == 5
        m.close()


# ---------------------------------------------------------------------------
# elastic integration: only committed steps cross the barrier
# ---------------------------------------------------------------------------

class TestElasticSeesCommittedOnly:
    def test_coordinated_restore_flushes_inflight(self, tmp_path):
        m = CheckpointManager(str(tmp_path / "ckpt"), async_commit=True,
                              commit_delay=0.15)
        st = {"w": np.arange(8, dtype=np.float32)}
        m.save(3, {"w": st["w"]})
        coord = FileCoordinator(str(tmp_path / "coord"), job_id="j",
                                host="a", poll=0.01)
        restored, common = coordinated_restore(
            m, {"w": st["w"]}, coord, lambda: ["a"], timeout=30.0)
        # without the flush the barrier would min-reduce None (-1):
        # the in-flight commit must land before this host reports
        assert common == 3
        np.testing.assert_array_equal(np.asarray(restored["w"]), st["w"])
        m.close()


# ---------------------------------------------------------------------------
# save-stall attribution (satellite: step_time must exclude save wall)
# ---------------------------------------------------------------------------

class TestStallAttribution:
    def test_sync_save_feeds_stall_ledger(self, tmp_path):
        m = CheckpointManager(str(tmp_path), use_async=False)
        before = ck.stall_seconds()
        t0 = time.perf_counter()
        m.save(1, _state())
        wall = time.perf_counter() - t0
        delta = ck.stall_seconds() - before
        assert delta > 0 and delta <= wall * 1.5
        m.close()

    def test_async_save_stall_is_snapshot_only(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              commit_delay=0.2)
        before = ck.stall_seconds()
        m.save(1, _state())
        delta = ck.stall_seconds() - before
        assert delta < 0.1  # the 0.2s commit never touched the ledger
        m.flush()
        assert ck.stall_seconds() - before - delta < 0.01
        m.close()

    def test_telemetry_callback_excludes_save_stall(self, fresh_registry):
        from paddle_tpu.hapi.callbacks import TelemetryCallback
        cb = TelemetryCallback()
        cb.on_train_batch_begin(0)
        with ck.attributing_stall():
            time.sleep(0.2)        # "the save" inside the batch window
        time.sleep(0.02)           # "the compute"
        logs = {}
        cb.on_train_batch_end(0, logs)
        # the regression this guards: 0.2s of save wall must NOT appear
        # as step_time (it used to, sinking MFU on checkpoint steps)
        assert logs["step_time"] < 0.1
        assert logs["ckpt_stall_ms"] >= 150.0
        hist = fresh_registry.to_dict().get("step_time_seconds", {})
        assert hist, "step_time histogram must still be recorded"

    def test_stall_histogram_series_recorded(self, fresh_registry,
                                             tmp_path):
        ms = CheckpointManager(str(tmp_path / "s"), use_async=False)
        ms.save(1, _state())
        stall = fresh_registry.to_dict().get("ckpt_step_stall_ms", {})
        assert sum(s["count"] for s in stall.get("series", {}).values()) >= 1
        ma = CheckpointManager(str(tmp_path / "a"), async_commit=True)
        ma.save(1, _state())
        ma.flush()
        reg = fresh_registry.to_dict()
        assert "ckpt_snapshot_ms" in reg
        assert "ckpt_commit_ms" in reg
        assert reg["ckpt_inflight"]["series"][""] == 0  # drained
        ms.close()
        ma.close()

    def test_suppressed_counter_reasons(self, fresh_registry, tmp_path):
        dirty = {"v": False}
        m = CheckpointManager(str(tmp_path), async_commit=True,
                              dirty_probe=lambda: dirty["v"])
        m.pause_commits()
        m.save(1, _state(1))
        m.save(2, _state(2))       # supersedes 1
        dirty["v"] = True
        m.resume_commits()
        m.flush()                  # 2 suppressed as dirty
        series = fresh_registry.to_dict().get(
            "ckpt_suppressed_total", {}).get("series", {})
        assert any("superseded" in k for k in series)
        assert any("dirty" in k for k in series)
        m.close()
