"""Hybrid-parallel wrappers (reference: fleet/meta_parallel/)."""
from .parallel_layers.mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding)
from .parallel_layers.pp_layers import (  # noqa: F401
    LayerDesc, PipelineLayer, SegmentLayers, SharedLayerDesc)
from .tensor_parallel import TensorParallel  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .sharding_parallel import ShardingParallel  # noqa: F401
from .hybrid_optimizer import (  # noqa: F401
    HybridParallelGradScaler, HybridParallelOptimizer)
