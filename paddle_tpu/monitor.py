"""paddle_tpu.monitor — named int64 gauges (legacy bridge).

Capability map: platform/monitor.h:44 StatValue (thread-safe named gauges
with add/sub/set/reset, registered in a global registry) exposed to Python
via pybind/global_value_getter_setter.cc.

DEPRECATION PATH: since ISSUE 3 this module is a thin bridge onto
``paddle_tpu.telemetry`` — every ``StatValue`` stores through a telemetry
``Gauge`` of the same name in the CURRENT default registry, so monitor
stats appear in ``telemetry.prometheus_text()`` / the JSONL summary with
one source of truth. ``stat``/``get_all_stats``/``reset_all_stats`` keep
their int-valued API for existing callers; new code should use
``telemetry.counter/gauge/histogram`` directly (labels, histograms, and
exporters live there). This module will eventually become a pure alias.
"""
from __future__ import annotations

import threading
from typing import Dict

from . import telemetry as _telemetry

__all__ = ["StatValue", "stat", "get_all_stats", "reset_all_stats"]

_registry: Dict[str, "StatValue"] = {}
_reg_lock = threading.Lock()


class StatValue:
    """reference: platform/monitor.h:44 — int view over a telemetry Gauge.

    The gauge is looked up per operation (not cached) so a
    ``telemetry.scope(fresh=True)`` registry swap is observed immediately.
    """

    def __init__(self, name: str, value: int = 0):
        self.name = name
        if value:
            self._gauge().set(int(value))

    def _gauge(self):
        return _telemetry.gauge(self.name, "monitor.StatValue bridge")

    def increase(self, n: int = 1) -> int:
        return int(self._gauge().inc(int(n)))

    def decrease(self, n: int = 1) -> int:
        return int(self._gauge().dec(int(n)))

    def set(self, v: int) -> int:
        return int(self._gauge().set(int(v)))

    def reset(self) -> int:
        return self.set(0)

    def get(self) -> int:
        return int(self._gauge().value())

    def __repr__(self):
        return f"StatValue({self.name}={self.get()})"


def stat(name: str) -> StatValue:
    """Get-or-create the gauge named ``name`` (DEFINE_INT_STATUS +
    USE_INT_STAT collapse into one call; monitor.h:154,165)."""
    with _reg_lock:
        sv = _registry.get(name)
        if sv is None:
            sv = _registry[name] = StatValue(name)
        return sv


def get_all_stats() -> Dict[str, int]:
    with _reg_lock:
        return {k: v.get() for k, v in _registry.items()}


def reset_all_stats():
    with _reg_lock:
        for v in _registry.values():
            v.reset()
