"""Transformer layers (reference: python/paddle/nn/layer/transformer.py:109
MultiHeadAttention, :437 TransformerEncoderLayer, :1112 Transformer).

Attention routes through F.scaled_dot_product_attention, which selects the
Pallas flash-attention kernel on TPU (O(S) memory) instead of materializing
the S×S score matrix like the reference's fused/multihead_matmul_op.cu.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import functional as F
from ..layer import Layer
from .common import Dropout, Linear
from .norm import LayerNorm


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    if attn_mask.dtype == jnp.bool_:
        return attn_mask
    return attn_mask.astype(dtype)


class MultiHeadAttention(Layer):
    Cache = tuple  # (k, v)
    StaticCache = tuple

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x):
        b, s, _ = x.shape
        return jnp.reshape(x, (b, s, self.num_heads, self.head_dim))

    def gen_cache(self, key, value=None, type=None):
        if type == self.StaticCache or value is not None:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value if value is not None else key))
            return (k, v)
        b = key.shape[0]
        k = jnp.zeros((b, 0, self.num_heads, self.head_dim), dtype=key.dtype)
        v = jnp.zeros((b, 0, self.num_heads, self.head_dim), dtype=key.dtype)
        return (k, v)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._shape(self.q_proj(query))
        if cache is not None and len(cache) == 2 and cache[0].shape[1] and key is query:
            # incremental decode with concatenated cache
            k_new = self._shape(self.k_proj(key))
            v_new = self._shape(self.v_proj(value))
            k = jnp.concatenate([cache[0], k_new], axis=1)
            v = jnp.concatenate([cache[1], v_new], axis=1)
            new_cache = (k, v)
        else:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value))
            new_cache = (k, v)
        mask = _convert_attention_mask(attn_mask, q.dtype)
        if mask is not None and mask.ndim == 3:
            mask = mask[:, None] if mask.shape[0] == q.shape[0] else mask[None]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout,
            training=self.training)
        b, s = out.shape[0], out.shape[1]
        out = jnp.reshape(out, (b, s, self.embed_dim))
        out = self.out_proj(out)
        if cache is not None:
            return out, new_cache
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        from .container import LayerList
        import copy
        self.layers = LayerList([encoder_layer] +
                                [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, c = mod(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [l.gen_cache(src) for l in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                             weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incr_cache, static_cache = None, None
        else:
            incr_cache, static_cache = cache
            tgt, incr_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, incr_cache)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if static_cache is not None:
            k, v = static_cache
            q = self.cross_attn._shape(self.cross_attn.q_proj(tgt))
            mask = _convert_attention_mask(memory_mask, q.dtype)
            if mask is not None and mask.ndim == 3:
                mask = mask[:, None]
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=mask,
                                                 dropout_p=self.cross_attn.dropout,
                                                 training=self.training)
            b, s = out.shape[0], out.shape[1]
            tgt = self.cross_attn.out_proj(jnp.reshape(out, (b, s, -1)))
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is None:
            return tgt
        return tgt, (incr_cache, static_cache)

    def gen_cache(self, memory):
        incr = self.self_attn.gen_cache(memory, type=MultiHeadAttention.Cache)
        static = self.cross_attn.gen_cache(memory, memory,
                                           type=MultiHeadAttention.StaticCache)
        return (incr, static)


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        from .container import LayerList
        import copy
        self.layers = LayerList([decoder_layer] +
                                [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, c = mod(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        return [l.gen_cache(memory) for l in self.layers]


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        return jnp.where(
            jnp.tril(jnp.ones((length, length), dtype=bool)),
            0.0, float(jnp.finfo(jnp.float32).min)).astype(jnp.float32)
