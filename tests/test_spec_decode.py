"""Speculative decoding e2e (SpeculativeDecodeServer over the paged KV
cache): greedy acceptance must keep every generation EXACTLY equal to
the dense no-cache oracle — through self-speculative n-gram drafts, a
draft-model drafter (including one routed through a second
DecodeServer), adversarial all-rejected drafts, EOS landing mid-verify-
window, replica failover mid-verify (idempotent re-execution of the
draft chunk), and LRU eviction pressure against pinned draft forks —
while the zero-silent-loss (``accounted``) and page-accounting
contracts keep holding.
"""
import numpy as np
import pytest

from paddle_tpu.inference import serving
from paddle_tpu.inference.decode_model import (dense_generate,
                                               init_decode_model,
                                               make_step_fn)
from paddle_tpu.inference.kv_cache import PagedKVCache
from paddle_tpu.inference.spec_decode import (DraftModelDrafter,
                                              NGramDrafter,
                                              SpeculativeDecodeServer)
from paddle_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.reset()


# vocab-32 / seed-5: this toy LM's greedy continuations settle into a
# short cycle, so the self-speculative n-gram drafter actually locks on
# (the same workload the bench spec phase uses)
VOCAB = 32
PARAMS = init_decode_model(vocab=VOCAB, num_heads=2, head_dim=32, seed=5)
RS = np.random.RandomState(11)
SYSTEM = [int(t) for t in RS.randint(0, VOCAB, 8)]   # 2 full pages @ ps=4


def prompt(i, extra=4):
    rs = np.random.RandomState(100 + i)
    return SYSTEM + [int(t) for t in rs.randint(0, VOCAB, extra)]


def oracle(p, n):
    return dense_generate(PARAMS, p, n)


class OracleDrafter:
    """Drafts the exact greedy continuation — every window fully
    accepts, driving the fork-adoption (COW append) commit path."""

    def propose(self, history, k):
        return oracle([int(t) for t in history], k)


class WrongDrafter:
    """Drafts (true_token + 1) mod V — every draft token is rejected,
    driving the fork-release commit path on every verify step."""

    def propose(self, history, k):
        return [(int(t) + 1) % VOCAB
                for t in oracle([int(t) for t in history], k)]


def make_stack(drafter=None, spec_k=4, num_pages=64, page_size=4,
               max_pages_per_seq=16, replicas=2, **srv_kw):
    cache = PagedKVCache(num_pages, page_size, 2, 32)
    fn = make_step_fn(PARAMS, cache)
    cfg_kw = dict(max_batch=32, call_timeout_s=30.0, batch_wait_s=0.002)
    cfg_kw.update(srv_kw.pop("cfg_kw", {}))
    cfg = serving.ServingConfig(**cfg_kw)
    srv = SpeculativeDecodeServer(fn, cache, drafter=drafter,
                                  spec_k=spec_k, replicas=replicas,
                                  config=cfg, prefill_chunk=8,
                                  max_pages_per_seq=max_pages_per_seq,
                                  **srv_kw)
    return srv, cache


def assert_drained_leak_free(cache):
    st = cache.stats()
    assert st["pages_used"] == st["evictable"], st


# -- drafter contracts --------------------------------------------------------

def test_ngram_drafter_contract():
    d = NGramDrafter(max_ngram=3, min_ngram=1)
    # bigram (1,2) recurs; replay the continuation after its earlier
    # occurrence — exactly k tokens
    assert d.propose([1, 2, 3, 1, 2], 3) == [3, 1, 2]
    # short continuation pads with its last token
    assert d.propose([5, 9, 5], 4) == [9, 5, 5, 5]
    # no repeated n-gram at all: no draft (plain decode)
    assert d.propose([1, 2, 3, 4], 3) == []
    assert d.propose([], 3) == []
    with pytest.raises(ValueError):
        NGramDrafter(max_ngram=1, min_ngram=2)


def test_draft_model_drafter_contract():
    assert DraftModelDrafter(lambda h, k: [1, 2, 3, 4, 5]).propose([0], 3) \
        == [1, 2, 3]                                   # truncates
    assert DraftModelDrafter(lambda h, k: [7]).propose([0], 3) \
        == [7, 7, 7]                                   # pads
    assert DraftModelDrafter(lambda h, k: []).propose([0], 3) == []
    def boom(h, k):
        raise RuntimeError("draft model down")
    assert DraftModelDrafter(boom).propose([0], 3) == []  # degrades


# -- exactness ----------------------------------------------------------------

def test_spec_generations_match_dense_oracle():
    srv, cache = make_stack(drafter=NGramDrafter())
    with srv:
        reqs = [(p, srv.submit_generate(p, 12))
                for p in (prompt(i) for i in range(6))]
        for i, (p, r) in enumerate(reqs):
            assert [int(t) for t in r.result(timeout=60)[0]] \
                == oracle(p, 12), f"request {i} diverged"
        assert srv.accounted()
        sd = srv.stats()["spec_decode"]
        assert sd["draft_tokens"] > 0 and sd["verify_steps"] > 0
    assert_drained_leak_free(cache)


def test_full_accept_adopts_fork_and_multiplies_tokens_per_step():
    srv, cache = make_stack(drafter=OracleDrafter(), replicas=1)
    with srv:
        p = prompt(0)
        r = srv.submit_generate(p, 12)
        assert [int(t) for t in r.result(timeout=60)[0]] == oracle(p, 12)
        sd = srv.stats()["spec_decode"]
        # a perfect drafter fully accepts every window...
        assert sd["verify_steps"] >= 2
        assert sd["accept_rate"] == 1.0
        # ...so decode tokens per target-model step rises well above the
        # plain-decode 1.0 (the whole point of speculation)
        assert sd["tokens_per_target_step"] > 2.0
        assert srv.accounted()
    assert_drained_leak_free(cache)


def test_k0_falls_back_to_plain_decode():
    srv, cache = make_stack(drafter=OracleDrafter(), spec_k=0)
    with srv:
        p = prompt(1)
        r = srv.submit_generate(p, 6)
        assert [int(t) for t in r.result(timeout=60)[0]] == oracle(p, 6)
        sd = srv.stats()["spec_decode"]
        assert sd["draft_tokens"] == 0 and sd["verify_steps"] == 0
        assert sd["tokens_per_target_step"] == 1.0
        assert srv.accounted()
    assert_drained_leak_free(cache)


def test_all_rejected_drafts_stay_exact_and_release_forks():
    srv, cache = make_stack(drafter=WrongDrafter(), replicas=1)
    with srv:
        for i in range(3):
            p = prompt(i)
            r = srv.submit_generate(p, 8)
            assert [int(t) for t in r.result(timeout=60)[0]] \
                == oracle(p, 8), f"request {i} diverged"
        sd = srv.stats()["spec_decode"]
        assert sd["verify_steps"] > 0
        assert sd["accepted_tokens"] == 0 and sd["accept_rate"] == 0.0
        # every rejected window still commits the one real token
        assert sd["tokens_per_target_step"] == 1.0
        assert srv.accounted()
    # every draft fork was released — no speculative page leaked
    assert_drained_leak_free(cache)


def test_eos_mid_verify_window_truncates_exactly():
    srv, cache = make_stack(drafter=OracleDrafter(), replicas=1)
    with srv:
        p = prompt(2)
        stream = oracle(p, 16)
        eos = stream[6]            # first occurrence lands mid-window
        want = stream[:stream.index(eos) + 1]
        r = srv.submit_generate(p, 16, eos_token=eos)
        assert [int(t) for t in r.result(timeout=60)[0]] == want
        assert srv.accounted()
    assert_drained_leak_free(cache)


# -- resilience / memory pressure ---------------------------------------------

def test_failover_mid_verify_is_idempotent():
    srv, cache = make_stack(
        drafter=NGramDrafter(),
        cfg_kw=dict(max_batch=32, call_timeout_s=1.0, batch_wait_s=0.002,
                    probation_base_s=0.02, probation_max_s=0.2, seed=3))
    with srv:
        srv.submit_generate(prompt(0), 3).result(timeout=30)  # warm-up
        with faults.inject("replica_stall") as spec:
            reqs = [(prompt(i), srv.submit_generate(prompt(i), 10))
                    for i in (1, 2, 3)]
            for i, (p, r) in enumerate(reqs):
                assert [int(t) for t in r.result(timeout=90)[0]] \
                    == oracle(p, 10), f"request {i} diverged"
        assert spec.fired == 1
        s = srv.stats()
        assert s["failovers"] >= 1 and s["failed"] == 0
        assert s["spec_decode"]["verify_steps"] > 0
        assert srv.accounted()
    assert_drained_leak_free(cache)


def test_eviction_pressure_with_pinned_forks_stays_exact():
    # a pool small enough that each speculative generation (which pins
    # its prefix via the draft fork while live) forces LRU evictions of
    # the registered prefix pages left behind by completed requests
    srv, cache = make_stack(drafter=OracleDrafter(), num_pages=12,
                            max_pages_per_seq=8, replicas=1)
    with srv:
        for i in range(6):
            p = prompt(i * 17 + 1, extra=6)   # distinct 14-token prompts
            r = srv.submit_generate(p, 10)
            assert [int(t) for t in r.result(timeout=60)[0]] \
                == oracle(p, 10), f"request {i} diverged"
        assert cache.evictions >= 1
        assert srv.stats()["spec_decode"]["verify_steps"] > 0
        assert srv.accounted()
    assert_drained_leak_free(cache)


# -- draft-model hook ---------------------------------------------------------

def test_draft_model_drafter_via_second_decode_server():
    # the "small draft model" is a second DecodeServer (here on the same
    # toy weights, so drafts are perfect and every window fully accepts)
    draft_cache = PagedKVCache(64, 4, 2, 32)
    draft_srv = serving.DecodeServer(
        make_step_fn(PARAMS, draft_cache), draft_cache, replicas=1,
        config=serving.ServingConfig(max_batch=32, call_timeout_s=30.0,
                                     batch_wait_s=0.002),
        prefill_chunk=8, max_pages_per_seq=16)
    srv, cache = make_stack(
        drafter=DraftModelDrafter.from_decode_server(draft_srv),
        replicas=1)
    with draft_srv, srv:
        p = prompt(3)
        r = srv.submit_generate(p, 10)
        assert [int(t) for t in r.result(timeout=90)[0]] == oracle(p, 10)
        sd = srv.stats()["spec_decode"]
        assert sd["accepted_tokens"] > 0
        assert srv.accounted() and draft_srv.accounted()
    assert_drained_leak_free(cache)
