"""Machine-translation Transformer (encoder-decoder) with beam-search
generation — completes the text zoo next to the WMT14/16 datasets
(text/datasets). Reference capability: the Transformer layer stack is
python/paddle/nn/layer/transformer.py:109; the full seq2seq assembly +
beam search matched here lives in the reference ecosystem's
transformer.py (InferTransformerModel).

TPU notes: generation runs under one jit as a lax.scan over decode steps
with STATIC shapes — the target buffer is pre-allocated at max_out_len
and each step re-runs the decoder over the full prefix behind a causal
mask (O(L²) per sequence but fully compiled; no dynamic cache shapes).
Beam search is batched as (batch*beam) rows with a flat top-k over
(beam × vocab) per step.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ... import nn
from ...nn.layer import Layer

__all__ = ["TransformerModel", "InferTransformerModel",
           "position_encoding_init"]


def position_encoding_init(n_position: int, d_model: int) -> np.ndarray:
    """Sinusoidal position table (reference transformer position_encoding)."""
    pos = np.arange(n_position)[:, None]
    dim = np.arange(d_model)[None, :]
    angle = pos / np.power(10000, 2 * (dim // 2) / d_model)
    out = np.zeros((n_position, d_model), np.float32)
    out[:, 0::2] = np.sin(angle[:, 0::2])
    out[:, 1::2] = np.cos(angle[:, 1::2])
    return out


class TransformerModel(Layer):
    """Training-time MT transformer: (src, trg) -> next-token logits.
    ``bos_id`` doubles as the pad id (reference convention)."""

    def __init__(self, src_vocab_size, trg_vocab_size, max_length=256,
                 num_encoder_layers=6, num_decoder_layers=6, n_head=8,
                 d_model=512, d_inner_hid=2048, dropout=0.1,
                 weight_sharing=False, bos_id=0, eos_id=1):
        super().__init__()
        self.d_model = d_model
        self.bos_id, self.eos_id = bos_id, eos_id
        self.trg_vocab_size = trg_vocab_size
        self.src_emb = nn.Embedding(src_vocab_size, d_model)
        if weight_sharing:
            assert src_vocab_size == trg_vocab_size, \
                "weight_sharing needs a joint vocabulary"
            self.trg_emb = self.src_emb
        else:
            self.trg_emb = nn.Embedding(trg_vocab_size, d_model)
        self.register_buffer(
            "pos_table",
            jnp.asarray(position_encoding_init(max_length, d_model)),
            persistable=False)
        self.dropout = nn.Dropout(dropout)
        self.transformer = nn.Transformer(
            d_model=d_model, nhead=n_head,
            num_encoder_layers=num_encoder_layers,
            num_decoder_layers=num_decoder_layers,
            dim_feedforward=d_inner_hid, dropout=dropout,
            normalize_before=True)
        self._share_out = weight_sharing
        if not weight_sharing:
            self.out_proj = nn.Linear(d_model, trg_vocab_size,
                                      bias_attr=False)

    def _embed(self, ids, emb):
        x = emb(ids) * jnp.sqrt(jnp.asarray(self.d_model, jnp.float32))
        return self.dropout(x + self.pos_table[None, :ids.shape[1]])

    def _masks(self, src, trg):
        neg = jnp.asarray(-1e9, jnp.float32)
        src_pad = (src == self.bos_id)
        src_mask = jnp.where(src_pad[:, None, None, :], neg, 0.0)
        t = trg.shape[1]
        causal = jnp.triu(jnp.full((t, t), neg), k=1)[None, None]
        return src_mask, causal

    def _project(self, h):
        if self._share_out:
            return h @ jnp.swapaxes(self.trg_emb.weight.value, 0, 1)
        return self.out_proj(h)

    def forward(self, src_word, trg_word):
        src = jnp.asarray(src_word)
        trg = jnp.asarray(trg_word)
        src_mask, trg_mask = self._masks(src, trg)
        enc = self.transformer.encoder(self._embed(src, self.src_emb),
                                       src_mask)
        dec = self.transformer.decoder(self._embed(trg, self.trg_emb), enc,
                                       trg_mask, src_mask)
        return self._project(dec)


class InferTransformerModel(TransformerModel):
    """Adds beam-search generation: forward(src) -> (ids, scores) with
    ids (batch, beam, max_out_len) best-first (reference
    InferTransformerModel; beam_size=1 is greedy)."""

    def __init__(self, *args, beam_size=4, max_out_len=64, alpha=0.6,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.beam_size = beam_size
        self.max_out_len = max_out_len
        self.alpha = alpha

    def forward(self, src_word):
        src = jnp.asarray(src_word)
        b, beam, v = src.shape[0], self.beam_size, self.trg_vocab_size
        neg = jnp.asarray(-1e9, jnp.float32)

        src_mask, _ = self._masks(src, src[:, :1])
        enc = self.transformer.encoder(self._embed(src, self.src_emb),
                                       src_mask)
        # expand to (b*beam) rows
        enc = jnp.repeat(enc, beam, axis=0)
        src_mask = jnp.repeat(src_mask, beam, axis=0)

        T = self.max_out_len
        seqs = jnp.full((b, beam, T + 1), self.bos_id, jnp.int32)
        scores = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (beam - 1))[None], (b, 1))
        finished = jnp.zeros((b, beam), bool)

        def step(carry, t):
            seqs, scores, finished = carry
            flat = seqs.reshape(b * beam, T + 1)
            _, causal = self._masks(src[:1], flat)
            dec = self.transformer.decoder(
                self._embed(flat, self.trg_emb), enc, causal, src_mask)
            logits = self._project(dec)                     # (b*beam,T+1,V)
            logp = jax.nn.log_softmax(
                jnp.take_along_axis(
                    logits, jnp.full((b * beam, 1, 1), t, jnp.int32)
                    .repeat(logits.shape[-1], -1), axis=1)[:, 0]
                .astype(jnp.float32)).reshape(b, beam, v)
            # finished beams: only EOS continues, at no cost
            eos_only = jnp.full((v,), -jnp.inf).at[self.eos_id].set(0.0)
            logp = jnp.where(finished[..., None], eos_only[None, None],
                             logp)
            total = scores[..., None] + logp                 # (b, beam, V)
            top, idx = lax.top_k(total.reshape(b, beam * v), beam)
            src_beam = idx // v
            token = (idx % v).astype(jnp.int32)
            seqs = jnp.take_along_axis(seqs, src_beam[..., None], axis=1)
            finished = jnp.take_along_axis(finished, src_beam, axis=1)
            seqs = seqs.at[:, :, t + 1].set(
                jnp.where(finished, self.eos_id, token))
            finished = finished | (token == self.eos_id)
            return (seqs, top, finished), None

        (seqs, scores, finished), _ = lax.scan(
            step, (seqs, scores, finished), jnp.arange(T))
        # length-penalty rerank ((5+len)/6)^alpha, reference GNMT style
        lengths = (seqs[:, :, 1:] != self.eos_id).sum(-1) + 1
        penalty = jnp.power((5.0 + lengths.astype(jnp.float32)) / 6.0,
                            self.alpha)
        norm = scores / penalty
        order = jnp.argsort(-norm, axis=1)
        seqs = jnp.take_along_axis(seqs, order[..., None], axis=1)
        norm = jnp.take_along_axis(norm, order, axis=1)
        return seqs[:, :, 1:], norm
