"""Loss functionals (reference: python/paddle/nn/functional/loss.py;
operators/softmax_with_cross_entropy_op.*, bce_loss_op.*, …)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    """Reference: softmax_with_cross_entropy_op.cc — numerically-stable
    log-softmax + NLL in one fused XLA graph."""
    if use_softmax:
        logp = None if not soft_label else jax.nn.log_softmax(input, axis=axis)
    else:
        logp = jnp.log(jnp.clip(input, 1e-30, None))
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis)
    else:
        label = label.astype(jnp.int32)
        lbl = jnp.squeeze(label, axis=axis) if label.ndim == input.ndim else label
        valid = (lbl != ignore_index)
        safe = jnp.where(valid, lbl, 0)
        idx = safe[..., None] if axis in (-1, input.ndim - 1) \
            else jnp.expand_dims(safe, axis)
        if logp is None:
            # Hard-label fast path: loss = lse(logits) - logit[label]. Avoids
            # materializing the full log-prob tensor — for an LM head this is
            # (batch, seq, vocab) of HBM traffic saved (+5% GPT-base MFU on
            # TPU, tools/op_bench.py). lse accumulates in fp32 for bf16
            # stability.
            lse = jax.nn.logsumexp(input.astype(jnp.float32), axis=axis)
            picked = jnp.take_along_axis(input, idx, axis=axis) \
                .astype(jnp.float32)
            loss = lse - jnp.squeeze(picked, axis=axis)
        else:
            picked = jnp.take_along_axis(logp, idx, axis=axis)
            loss = -jnp.squeeze(picked, axis=axis)
        if weight is not None:
            w = jnp.take(weight, safe)
            loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, jnp.take(weight, safe) if weight is not None
                                      else jnp.ones_like(loss), 0.0))
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = jnp.expand_dims(loss, axis)
    if return_softmax:
        return loss, jax.nn.softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    lbl = label.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    picked = -jnp.take_along_axis(input, safe[..., None], axis=-1)[..., 0] \
        if input.ndim == 2 else -jnp.take_along_axis(input, safe[:, None], axis=1)[:, 0]
    if weight is not None:
        picked = picked * jnp.take(weight, safe)
    picked = jnp.where(valid, picked, 0.0)
    if reduction == "mean":
        denom = jnp.sum(jnp.where(valid, jnp.take(weight, safe) if weight is not None
                                  else jnp.ones_like(picked), 0.0))
        return jnp.sum(picked) / jnp.maximum(denom, 1e-12)
    return _reduce(picked, reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return _reduce(jnp.square(input - label), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _reduce(jnp.abs(input - label), reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    diff = jnp.abs(input - label)
    loss = jnp.where(diff < delta, 0.5 * diff * diff / delta, diff - 0.5 * delta)
    # paddle multiplies by delta
    return _reduce(loss * delta, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.clip(input, eps, None)) +
             (1.0 - label) * jnp.log(jnp.clip(1.0 - input, eps, None)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    max_val = jnp.clip(-logit, 0, None)
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = (1.0 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1.0 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):
    loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    loss = jnp.clip(-label * (input - other) + margin, 0.0, None)
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    loss = jnp.where(label == 1.0, input, jnp.clip(margin - input, 0.0, None))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    from .common import cosine_similarity
    cos = cosine_similarity(input1, input2, axis=1)
    loss = jnp.where(label == 1, 1.0 - cos, jnp.clip(cos - margin, 0.0, None))
    return _reduce(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def dist(a, b):
        return jnp.sum(jnp.abs(a - b + epsilon) ** p, axis=-1) ** (1.0 / p)
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn = jnp.minimum(dn, dist(positive, negative))
    return _reduce(jnp.clip(dp - dn + margin, 0.0, None), reduction)


def log_loss(input, label, epsilon=1e-4, name=None):
    return -(label * jnp.log(input + epsilon) +
             (1.0 - label) * jnp.log(1.0 - input + epsilon))


def square_error_cost(input, label):
    return jnp.square(input - label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    p = jax.nn.sigmoid(logit)
    ce = binary_cross_entropy_with_logits(logit, label, reduction="none")
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space, scan over time.

    Reference: operators/warpctc_op.* (wraps warp-ctc). Implemented natively
    with lax.scan — static shapes, TPU-friendly.
    log_probs: (T, B, C) log-softmax outputs. labels: (B, L) padded.
    """
    T, B, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    NEG = -1e30
    blanks = jnp.full((B, L + 1), blank, dtype=labels.dtype)
    ext = jnp.zeros((B, S), dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    ext = ext.at[:, 0::2].set(blanks)
    # allow skip when ext[s] != ext[s-2] and ext[s] != blank
    can_skip = jnp.concatenate(
        [jnp.zeros((B, 2), bool),
         (ext[:, 2:] != ext[:, :-2]) & (ext[:, 2:] != blank)], axis=1)

    alpha0 = jnp.full((B, S), NEG)
    alpha0 = alpha0.at[:, 0].set(log_probs[0, jnp.arange(B), ext[:, 0]])
    alpha0 = alpha0.at[:, 1].set(jnp.where(L > 0, log_probs[0, jnp.arange(B), ext[:, 1]], NEG))

    def step(alpha, logp_t):
        emit = jnp.take_along_axis(logp_t, ext, axis=1)  # (B, S)
        prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(can_skip, prev2, NEG)
        new = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2) + emit
        return new, new

    _, alphas = jax.lax.scan(step, alpha0, log_probs[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, B, S)
    t_idx = jnp.clip(input_lengths - 1, 0, T - 1)
    last = alphas[t_idx, jnp.arange(B)]  # (B, S)
    s_last = 2 * label_lengths  # blank after last label
    s_prev = jnp.clip(2 * label_lengths - 1, 0, S - 1)
    ll = jnp.logaddexp(
        jnp.take_along_axis(last, s_last[:, None], axis=1)[:, 0],
        jnp.take_along_axis(last, s_prev[:, None], axis=1)[:, 0])
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(input_lengths.astype(loss.dtype), 1.0)
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(label_lengths.astype(loss.dtype), 1.0))
    return _reduce(loss, reduction)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), axis=1)) +
                    jnp.mean(jnp.sum(jnp.square(positive), axis=1))) * 0.25
    sim = anchor @ positive.T
    labels = jnp.reshape(labels, (-1,))
    same = (labels[:, None] == labels[None, :]).astype(sim.dtype)
    tgt = same / jnp.sum(same, axis=1, keepdims=True)
    xe = -jnp.sum(tgt * jax.nn.log_softmax(sim, axis=1), axis=1)
    return jnp.mean(xe) + reg


def mae_loss(input, label, reduction="mean"):
    return l1_loss(input, label, reduction)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice loss for segmentation (reference nn/functional/loss.py dice_loss):
    input (N, ..., C) class probabilities, label (N, ..., 1) int labels."""
    label = jnp.squeeze(label, axis=-1)
    onehot = jax.nn.one_hot(label, input.shape[-1], dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    intersect = jnp.sum(input * onehot, axis=reduce_dims)
    denom = jnp.sum(input, axis=reduce_dims) + jnp.sum(onehot, axis=reduce_dims)
    dice = (2 * intersect + epsilon) / (denom + epsilon)
    return jnp.mean(1 - dice)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid loss (reference nn/functional/loss.py
    hsigmoid_loss; operators/hierarchical_sigmoid_op.cc). Default tree =
    heap-numbered complete binary tree over ``num_classes`` leaves: internal
    nodes 1..num_classes-1 map to rows of ``weight`` (num_classes-1, D);
    custom trees come in via ``path_table``/``path_code``.
    On TPU the per-sample variable-length path is padded to max depth and
    masked (static shapes for XLA).
    """
    import numpy as _np
    x = jnp.asarray(input)
    weight = jnp.asarray(weight)
    if bias is not None:
        bias = jnp.asarray(bias)
    label = jnp.asarray(label).reshape(-1)
    if path_table is not None:
        table = jnp.asarray(path_table)
        code = jnp.asarray(path_code)
        node_ids = jnp.take(table, label, axis=0)        # (N, depth)
        codes = jnp.take(code, label, axis=0).astype(x.dtype)
        mask = (node_ids >= 0).astype(x.dtype)
        rows = jnp.maximum(node_ids, 0)
    else:
        depth = max(1, int(_np.ceil(_np.log2(max(2, num_classes)))))
        leaf = label + num_classes                        # heap leaf id
        nodes, codes_l = [], []
        node = leaf
        for _ in range(depth):
            codes_l.append((node % 2).astype(x.dtype))
            node = node // 2
            nodes.append(node)
        node_ids = jnp.stack(nodes, axis=1)               # ancestors, (N, depth)
        codes = jnp.stack(codes_l, axis=1)
        mask = (node_ids >= 1).astype(x.dtype)
        rows = jnp.maximum(node_ids - 1, 0)               # weight row index
    w = jnp.take(weight, rows, axis=0)                    # (N, depth, D)
    logits = jnp.einsum("nd,nkd->nk", x, w)
    if bias is not None:
        logits = logits + jnp.take(jnp.asarray(bias).reshape(-1), rows, axis=0)
    # reference clips pre_out to [-40, 40] (hierarchical_sigmoid_op.h:107)
    logits = jnp.clip(logits, -40.0, 40.0)
    # reference loss_j = softplus(z) - bit*z = -log sigmoid((2*bit-1) * z):
    # bit==1 is trained toward +inf (matrix_bit_code.h calc_bit +
    # hierarchical_sigmoid_op.h:112-115 Sum(scale=-1) + softplus row-sum)
    sign = 2.0 * codes - 1.0
    loss = -jax.nn.log_sigmoid(sign * logits) * mask
    return jnp.sum(loss, axis=1, keepdims=True)


def fused_linear_cross_entropy(hidden, weight, labels, ignore_index=-100,
                               chunk=8192, kernel="auto", interpret=None,
                               name=None):
    """LM-head projection + softmax cross entropy WITHOUT materializing
    the (tokens, vocab) logits tensor — mean fp32 loss over labels !=
    ``ignore_index``, gradients to hidden and weight.

    hidden: (..., H); weight: (H, V); labels: (...,) int targets.

    kernel selects the implementation:
      - ``"pallas"``: the fused Mosaic kernel
        (``ops/pallas/fused_ce.py``; interpret mode auto-selected
        off-TPU unless ``interpret`` says otherwise),
      - ``"chunked"``: the jnp online-logsumexp scan
        (``ops/chunked_ce.py``, ``chunk`` classes per step),
      - ``"auto"``: pallas on TPU, chunked elsewhere — the chunked
        route is counted as ``pallas_config_resolved_total{
        kernel="fused_ce", source="fallback"}``.
    """
    from ...ops.chunked_ce import chunked_lm_ce
    from ...ops.pallas.fused_ce import fused_ce_supported, fused_lm_ce
    if kernel == "auto":
        if fused_ce_supported():
            kernel = "pallas"
        else:
            from ...ops.pallas.tuner import record_fallback
            record_fallback("fused_ce")
            kernel = "chunked"
    if kernel == "pallas":
        return fused_lm_ce(hidden, weight, labels,
                           ignore_index=ignore_index, interpret=interpret)
    if kernel == "chunked":
        return chunked_lm_ce(hidden, weight, labels, chunk=chunk,
                             ignore_index=ignore_index)
    raise ValueError(
        f"kernel must be 'auto', 'pallas' or 'chunked', got {kernel!r}")
