#!/usr/bin/env bash
# Nightly `-m slow` lane (ISSUE 17 satellite; ROADMAP carried item).
#
# The tier-1 gate runs `pytest -m 'not slow'` under an 870 s cap; the
# ~38 heavy tests behind the `slow` marker (subprocess smokes, PP/SEP
# composition trajectories, hostsim scenarios) need their own lane.
# This script is that lane: cron it nightly, e.g.
#
#   17 3 * * * cd /path/to/repo && tools/run_slow_lane.sh >> /var/log/slow_lane.log 2>&1
#
# It runs the slow suite under its own timeout and prints ONE JSON line
# (machine-greppable) summarizing the outcome:
#
#   {"lane": "slow", "rc": 0, "passed": 38, "failed": 0, "errors": 0,
#    "skipped": 1, "duration_s": 1234.5, "timed_out": false, "log": "..."}
#
# rc 0 = all green; rc of pytest otherwise; rc 124/137 = lane timeout.
# The same JSON line is also written to SLOW_LANE_JSON so
# tools/nightly_report.py can scrape it without parsing the cron log.
# Env knobs: SLOW_LANE_TIMEOUT (seconds, default 5400),
#            SLOW_LANE_LOG (default /tmp/_slow_lane.log),
#            SLOW_LANE_JSON (default /tmp/_slow_lane_summary.json).
set -u
cd "$(dirname "$0")/.."

TIMEOUT="${SLOW_LANE_TIMEOUT:-5400}"
LOG="${SLOW_LANE_LOG:-/tmp/_slow_lane.log}"
rm -f "$LOG"

start=$(date +%s)
timeout -k 30 "$TIMEOUT" env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/ -q -m slow --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    >"$LOG" 2>&1
rc=$?
end=$(date +%s)

timed_out=false
if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    timed_out=true
fi

# pull pass/fail/skip counts out of pytest's summary line; 0 when absent
count() {
    grep -aoE "[0-9]+ $1" "$LOG" | tail -1 | grep -oE '[0-9]+' || echo 0
}
passed=$(count passed)
failed=$(count failed)
errors=$(count error)
skipped=$(count skipped)

JSON_OUT="${SLOW_LANE_JSON:-/tmp/_slow_lane_summary.json}"
line=$(printf '{"lane": "slow", "rc": %d, "passed": %s, "failed": %s, "errors": %s, "skipped": %s, "duration_s": %d, "timed_out": %s, "log": "%s"}' \
    "$rc" "$passed" "$failed" "$errors" "$skipped" "$((end - start))" \
    "$timed_out" "$LOG")
echo "$line"
echo "$line" >"$JSON_OUT"
exit "$rc"
