"""Backward-overlapped bucketed gradient exchange (grad_sync_buckets).

Covers the reverse-order byte-balanced bucket partition, numerical
parity of the bucketed exchange against the monolithic one (fp32 exact,
int8/int4 within EF-accumulation tolerance), per-bucket error-feedback
state durability (checkpoint save/restore + remap_comm_err on remesh),
ZeRO-2 sharded-leaf error feedback, the `exchange-not-overlapped`
analysis rule (seeded + clean + gated variants), and the dependency-
driven overlap model in analysis.cost — including the acceptance
inequality: overlap_efficiency strictly greater for K >= 2 than for the
monolithic K = 1 staging of the same model.
"""
import importlib.util
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.analysis import AnalysisConfig, analyze_jaxpr
from paddle_tpu.analysis import cost as acost
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.engine import (ParallelTrainer,
                                           partition_reverse_buckets)
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.resilience import remap_comm_err

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N = 4


def _mlp_trainer(grad_sync, zero_stage=0, ndata=N, nshard=1, **kw):
    paddle.seed(7)
    mesh = build_mesh({"data": ndata, "sharding": nshard})

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(16, 32)
            self.l2 = nn.Linear(32, 4)

        def forward(self, x):
            return self.l2(nn.functional.relu(self.l1(x)))

    model = MLP()
    opt = paddle.optimizer.Momentum(0.05, momentum=0.9,
                                    parameters=model.parameters())
    return ParallelTrainer(model, opt,
                           lambda out, y: jnp.mean((out - y) ** 2),
                           mesh=mesh, grad_sync=grad_sync,
                           grad_sync_block=64, zero_stage=zero_stage, **kw)


def _regression_batch():
    rng = np.random.RandomState(3)
    X = rng.randn(64, 16).astype(np.float32)
    W = rng.randn(16, 4).astype(np.float32)
    return X, X @ W


def _params_after(policy, k, steps=8):
    X, Y = _regression_batch()
    tr = _mlp_trainer(policy, grad_sync_buckets=k)
    for _ in range(steps):
        loss = tr.train_step(X, Y)
    assert np.isfinite(float(loss))
    return {key: np.asarray(jax.device_get(v))
            for key, v in tr.state["params"].items()}


_LOSS = {}  # policy -> 30-step fp32 reference loss (paddle.seed-fixed)


def _loss_after(policy, k, steps=30):
    X, Y = _regression_batch()
    tr = _mlp_trainer(policy, grad_sync_buckets=k)
    for _ in range(steps):
        loss = tr.train_step(X, Y)
    return float(loss)


def rule_hits(report, rule):
    return [f for f in report.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# bucket partitioning
# ---------------------------------------------------------------------------

class TestBucketPartition:
    ITEMS = [("l0.w", 4096), ("l0.b", 64), ("l1.w", 4096), ("l1.b", 64),
             ("l2.w", 2048), ("l2.b", 32)]

    def test_k1_is_single_reverse_bucket(self):
        (only,) = partition_reverse_buckets(self.ITEMS, 1)
        assert only == [k for k, _ in reversed(self.ITEMS)]

    def test_partition_covers_all_keys_once_in_reverse_order(self):
        for k in (2, 3, 4):
            buckets = partition_reverse_buckets(self.ITEMS, k)
            assert len(buckets) == k
            assert all(b for b in buckets)
            flat = [key for b in buckets for key in b]
            assert flat == [key for key, _ in reversed(self.ITEMS)]

    def test_bucket_zero_holds_last_layer(self):
        buckets = partition_reverse_buckets(self.ITEMS, 3)
        assert buckets[0][0] == "l2.b"  # grads that materialize first

    def test_k_clamped_to_item_count(self):
        buckets = partition_reverse_buckets(self.ITEMS[:2], 5)
        assert len(buckets) == 2
        assert all(len(b) == 1 for b in buckets)

    def test_byte_balance_beats_naive_split(self):
        """Greedy close-at-target: no bucket should hoard nearly all the
        bytes when k=2 (the pre-fix behavior collapsed to one bucket)."""
        sizes = dict(self.ITEMS)
        buckets = partition_reverse_buckets(self.ITEMS, 2)
        per = [sum(sizes[key] for key in b) for b in buckets]
        assert max(per) <= 0.8 * sum(per)

    def test_trainer_exposes_bucket_keys(self):
        tr = _mlp_trainer("fp32", grad_sync_buckets=4)
        X, Y = _regression_batch()
        tr.train_step(X, Y)
        assert len(tr.grad_sync_bucket_keys) == 4
        flat = {k for b in tr.grad_sync_bucket_keys for k in b}
        assert flat == {k for k, t in tr.trainable.items() if t}
        # reverse layer order: bucket 0 starts at the output layer
        assert tr.grad_sync_bucket_keys[0][0].startswith("l2")


# ---------------------------------------------------------------------------
# numerical parity: bucketed == monolithic
# ---------------------------------------------------------------------------

class TestBucketParity:
    def test_fp32_buckets_bitwise_equal_to_monolithic(self):
        mono = _params_after("fp32", 1)
        for k in (2, 4):
            got = _params_after("fp32", k)
            for key in mono:
                np.testing.assert_array_equal(
                    got[key], mono[key], err_msg=f"K={k} leaf {key}")

    def test_int8_bucketed_params_track_monolithic(self):
        """Bucketing regroups the quantization blocks, so int8 parity is
        tolerance- not bit-level; EF keeps the two paths within a small
        absolute band of each other after a handful of steps. (int4's
        wire noise is too coarse for a per-leaf band — its parity bar is
        the convergence test below.)"""
        mono = _params_after("int8", 1)
        got = _params_after("int8", 4)
        for key in mono:
            np.testing.assert_allclose(
                got[key], mono[key], rtol=0.1, atol=5e-3,
                err_msg=f"int8 K=4 leaf {key}")

    def test_int8_bucketed_convergence_within_2pct_of_fp32(self):
        """EF-accumulation tolerance: the bucketed int8 exchange must
        meet the same acceptance bar as the monolithic one — loss after
        30 steps within 2% of the fp32 path."""
        fp32 = _LOSS.setdefault("fp32", _loss_after("fp32", 1))
        for k in (1, 4):
            got = _loss_after("int8", k)
            rel = abs(got - fp32) / fp32
            assert rel < 0.02, (k, got, fp32)

    @pytest.mark.slow
    def test_int4_bucketed_convergence_matches_monolithic(self):
        """int4 wire noise dominates the mid-descent (step-30) loss, so
        its parity point is step 60, where EF has averaged the coarser
        quantization out: every bucketing within 10% of monolithic."""
        mono = _loss_after("int4", 1, steps=60)
        for k in (2, 4):
            got = _loss_after("int4", k, steps=60)
            rel = abs(got - mono) / mono
            assert rel < 0.10, (k, got, mono)

    def test_bucketed_residual_state_covers_every_leaf(self):
        X, Y = _regression_batch()
        tr = _mlp_trainer("int8", grad_sync_buckets=2)
        tr.train_step(X, Y)
        assert set(tr.state["comm_err"]) == \
            {k for k, t in tr.trainable.items() if t}
        assert all(np.abs(np.asarray(v)).max() > 0
                   for v in tr.state["comm_err"].values())


# ---------------------------------------------------------------------------
# EF state durability: checkpoint + remesh
# ---------------------------------------------------------------------------

class TestResidualDurability:
    def test_bucketed_comm_err_survives_checkpoint_roundtrip(
            self, tmp_path):
        X, Y = _regression_batch()
        tr = _mlp_trainer("int8", grad_sync_buckets=2)
        tr.train_step(X, Y)
        tr.train_step(X, Y)
        saved = {k: np.asarray(jax.device_get(v))
                 for k, v in tr.state["comm_err"].items()}
        mgr = CheckpointManager(str(tmp_path), use_async=False)
        mgr.save(0, saved)
        mgr.close()

        tr2 = _mlp_trainer("int8", grad_sync_buckets=2)
        template = {k: np.zeros_like(v) for k, v in saved.items()}
        mgr2 = CheckpointManager(str(tmp_path), use_async=False)
        restored = mgr2.restore(template=template)
        mgr2.close()
        remap_comm_err(restored, tr2)
        for k, v in tr2.state["comm_err"].items():
            np.testing.assert_allclose(np.asarray(jax.device_get(v)),
                                       saved[k], rtol=1e-6)
        # the restored residuals must be usable, not just equal
        assert np.isfinite(float(tr2.train_step(X, Y)))

    def test_bucketed_comm_err_survives_remesh_remap(self):
        X, Y = _regression_batch()
        tr = _mlp_trainer("int8", grad_sync_buckets=2, ndata=4)
        tr.train_step(X, Y)
        tr.train_step(X, Y)
        old = {k: np.asarray(jax.device_get(v))
               for k, v in tr.state["comm_err"].items()}
        assert all(v.shape[0] == 4 for v in old.values())
        tr.remesh(build_mesh({"data": 2, "sharding": 1}))
        remap_comm_err(old, tr)
        new = {k: np.asarray(jax.device_get(v))
               for k, v in tr.state["comm_err"].items()}
        assert set(new) == set(old)
        for k in old:
            assert new[k].shape[0] == 2
            np.testing.assert_allclose(new[k], old[k][:2], rtol=1e-6)
        assert np.isfinite(float(tr.train_step(X, Y)))


# ---------------------------------------------------------------------------
# ZeRO-2 sharded-leaf error feedback
# ---------------------------------------------------------------------------

class TestZero2ErrorFeedback:
    def _loss_after(self, policy, steps=30):
        X, Y = _regression_batch()
        tr = _mlp_trainer(policy, zero_stage=2, ndata=2, nshard=2)
        for _ in range(steps):
            loss = tr.train_step(X, Y)
        return float(loss), tr

    def test_zero2_residuals_exist_for_sharded_leaves(self):
        _, tr = self._loss_after("int8", steps=2)
        assert set(tr.state["comm_err"]) == \
            {k for k, t in tr.trainable.items() if t}
        assert any(np.abs(np.asarray(jax.device_get(v))).max() > 0
                   for v in tr.state["comm_err"].values())

    @pytest.mark.parametrize("policy", ["int8", "int4"])
    def test_zero2_quantized_loss_within_2pct_of_fp32(self, policy):
        """The sharded-grad acceptance bar: compressed_psum_scatter with
        EF converges within 2% of the fp32 ZeRO-2 path."""
        fp32, _ = self._loss_after("fp32")
        got, _ = self._loss_after(policy)
        rel = abs(got - fp32) / fp32
        assert rel < 0.02, (policy, got, fp32)


# ---------------------------------------------------------------------------
# exchange-not-overlapped rule
# ---------------------------------------------------------------------------

class TestExchangeNotOverlappedRule:
    def _jaxpr(self, interleaved):
        """Heavy dot (2*64^3 FLOPs) + two grad-sync-shaped psums (16 KiB
        each over 'data'); interleaved=True puts the dot BETWEEN them."""
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

        def serialized(v, w):
            h = jnp.dot(v, w)
            a = lax.psum(h, "data")
            b = lax.psum(v, "data")
            return a + b

        def overlapped(v, w):
            a = lax.psum(v, "data")
            h = jnp.dot(v, w)
            b = lax.psum(h, "data")
            return a + b

        f = jax.shard_map(overlapped if interleaved else serialized,
                          mesh=mesh, in_specs=(P(), P()), out_specs=P(),
                          check_vma=False)
        cj = jax.make_jaxpr(f)(jnp.zeros((64, 64), jnp.float32),
                               jnp.zeros((64, 64), jnp.float32))
        return cj, mesh

    def test_fires_once_on_serialized_exchange(self):
        cj, mesh = self._jaxpr(interleaved=False)
        rep = analyze_jaxpr(cj, mesh=mesh,
                            config=AnalysisConfig(grad_sync_buckets=2))
        hits = rule_hits(rep, "exchange-not-overlapped")
        assert len(hits) == 1
        assert "serialized" in hits[0].message
        assert rep.ok  # warning severity: lints, does not gate

    def test_silent_when_compute_interleaves(self):
        cj, mesh = self._jaxpr(interleaved=True)
        rep = analyze_jaxpr(cj, mesh=mesh,
                            config=AnalysisConfig(grad_sync_buckets=2))
        assert not rule_hits(rep, "exchange-not-overlapped")

    @pytest.mark.parametrize("k", [0, 1])
    def test_gated_off_below_two_buckets(self, k):
        """K=1 is monolithic by design and K=0 means the caller declared
        nothing — the serialized shape must not warn in either mode."""
        cj, mesh = self._jaxpr(interleaved=False)
        rep = analyze_jaxpr(cj, mesh=mesh,
                            config=AnalysisConfig(grad_sync_buckets=k))
        assert not rule_hits(rep, "exchange-not-overlapped")

    def test_real_bucketed_trainer_is_clean(self):
        """compile(analyze=True) injects the trainer's own K; the shipped
        bucketed step must not trip its own rule."""
        tr = _mlp_trainer("fp32", grad_sync_buckets=2)
        X, Y = _regression_batch()
        step, rep = tr.compile(X, Y, analyze=True)
        assert callable(step)
        assert not rule_hits(rep, "exchange-not-overlapped"), rep.to_text()


# ---------------------------------------------------------------------------
# overlap model (analysis.cost.overlap_summary)
# ---------------------------------------------------------------------------

class TestOverlapModel:
    def _efficiency(self, k):
        tr = _mlp_trainer("fp32", grad_sync_buckets=k)
        X, Y = _regression_batch()
        sched = acost.overlap_summary(tr.staged_jaxpr(X, Y), tr.mesh)
        return sched

    def test_bucketed_staging_strictly_beats_monolithic(self):
        """The PR's acceptance inequality on the small model: K=2 hides
        strictly more collective time behind backward compute than the
        monolithic exchange."""
        e1 = self._efficiency(1)["overlap_efficiency"]
        e2 = self._efficiency(2)["overlap_efficiency"]
        assert e1 is not None and e2 is not None
        assert 0.0 <= e1 <= 1.0 and 0.0 <= e2 <= 1.0
        assert e2 > e1, (e1, e2)

    def test_summary_reports_collectives_and_times(self):
        sched = self._efficiency(2)
        assert sched["n_collectives"] >= 2
        assert sched["collective_time"] > 0
        assert sched["compute_time"] > 0
        assert sched["makespan"] >= sched["compute_time"]
        assert sched["stalled_time"] >= 0

    def test_no_collectives_yields_none_efficiency(self):
        cj = jax.make_jaxpr(
            lambda x: jnp.tanh(jnp.dot(x, x)))(jnp.zeros((8, 8)))
        mesh = build_mesh({"data": 1})
        sched = acost.overlap_summary(cj, mesh)
        assert sched["overlap_efficiency"] is None
        assert sched["n_collectives"] == 0

    def test_timeline_entries_are_ordered_and_typed(self):
        tr = _mlp_trainer("fp32", grad_sync_buckets=2)
        X, Y = _regression_batch()
        sched = acost.overlap_summary(tr.staged_jaxpr(X, Y), tr.mesh,
                                      include_timeline=True)
        tl = sched["timeline"]
        assert tl
        assert all(e["end"] >= e["start"] for e in tl)
        assert all(e["kind"] in ("compute", "collective") for e in tl)
        starts = [e["start"] for e in tl]
        assert starts == sorted(starts)
        colls = [e for e in tl if e["kind"] == "collective"]
        assert colls
        assert all(e["bytes"] > 0 and e["link"] in ("ici", "dcn")
                   for e in colls)


# ---------------------------------------------------------------------------
# schedule dump renderer (tools/lint_program.py --dump-schedule)
# ---------------------------------------------------------------------------

def _load_lint_program():
    import sys
    tools = os.path.join(REPO, "tools")
    spec = importlib.util.spec_from_file_location(
        "lint_program", os.path.join(tools, "lint_program.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, tools)  # lint_program imports its _mesh_setup sibling
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(tools)
    return mod


class TestScheduleText:
    SCHED = {
        "overlap_efficiency": 0.625,
        "collective_time": 4e-6, "compute_time": 1e-5,
        "stalled_time": 1.5e-6, "makespan": 1.15e-5,
        "n_collectives": 1,
        "timeline": [
            {"kind": "compute", "primitive": "dot_general",
             "start": 0.0, "end": 1e-5, "flops": 2e6,
             "stall": 2.5e-7, "path": "<top>", "eqn_index": 0},
            {"kind": "collective", "primitive": "psum",
             "start": 2e-6, "end": 6e-6, "bytes": 262144.0,
             "link": "ici", "path": "shard_map", "eqn_index": 1,
             "axes": ["data"]},
        ],
    }

    def test_renders_rows_and_efficiency(self):
        mod = _load_lint_program()
        text = mod._schedule_text("gpt", self.SCHED)
        assert "overlap_efficiency" in text
        assert "0.62" in text
        assert "psum" in text and "dot_general" in text
        assert "collective" in text

    def test_none_efficiency_renders_na(self):
        mod = _load_lint_program()
        sched = dict(self.SCHED, overlap_efficiency=None, n_collectives=0,
                     timeline=[self.SCHED["timeline"][0]])
        text = mod._schedule_text("gpt", sched)
        assert "n/a" in text
        assert "nan" not in text
