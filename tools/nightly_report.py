"""Nightly lane report: fold the slow-lane JSON and the tier-1 duration
budget into ONE summary file (ISSUE 18 satellite; ROADMAP carried item).

``tools/run_slow_lane.sh`` already prints (and writes, via
``SLOW_LANE_JSON``) a one-line outcome JSON, and ``tests/conftest.py``
writes the per-file tier-1 duration budget to ``TIER1_DURATIONS_JSON``.
This script is the missing last step of the nightly wiring: scrape both,
emit a single machine-greppable summary, and exit non-zero when either
lane is unhealthy. Cron it right after the slow lane:

    17 3 * * * cd repo && tools/run_slow_lane.sh; tools/nightly_report.py

Inputs (either may be missing — recorded as null, and counted unhealthy
only if ``--require`` lists it):

- ``--slow-lane``: the slow-lane JSON file (default ``SLOW_LANE_JSON``
  env or /tmp/_slow_lane_summary.json). Plain log files work too: the
  last line that parses as JSON with a "lane" key wins, so pointing this
  at the cron log is fine.
- ``--tier1-durations``: the conftest budget report (default
  ``TIER1_DURATIONS_JSON`` env or /tmp/tier1_durations.json).

Output: ``--out`` (default /tmp/nightly_report.json) plus the same
summary on stdout as one JSON line:

    {"report": "nightly", "ok": true, "slow_lane": {...},
     "tier1_durations": {...}, "problems": []}

``ok`` = slow lane rc 0 and not timed out, and tier-1 not over budget
(for whichever inputs are present). ``--smoke`` self-checks the whole
flow against synthetic inputs in a tempdir (registered in
tests/test_bench_smoke.py).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional


def _read_json_line(path: str, require_key: str) -> Optional[dict]:
    """Last line of ``path`` that parses as a JSON object containing
    ``require_key`` (None when the file is missing or has no such line).
    Scanning backwards lets this read both the dedicated summary file
    and an appended cron log."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and require_key in obj:
            return obj
    return None


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            obj = json.load(f)
        return obj if isinstance(obj, dict) else None
    except (OSError, ValueError):
        return None


def build_report(slow_lane_path: str, durations_path: str,
                 require: tuple = ()) -> dict:
    slow = _read_json_line(slow_lane_path, "lane")
    durations = _read_json(durations_path)

    problems = []
    if slow is None:
        if "slow" in require:
            problems.append(f"slow-lane JSON missing: {slow_lane_path}")
    else:
        if slow.get("rc", 1) != 0:
            problems.append(f"slow lane rc={slow.get('rc')} "
                            f"(failed={slow.get('failed')}, "
                            f"errors={slow.get('errors')})")
        if slow.get("timed_out"):
            problems.append("slow lane timed out")
    if durations is None:
        if "tier1" in require:
            problems.append(f"tier-1 durations missing: {durations_path}")
    else:
        if durations.get("over_budget"):
            problems.append(
                f"tier-1 over budget: {durations.get('total_s')}s "
                f"> {durations.get('budget_s')}s")

    return {
        "report": "nightly",
        "ok": not problems,
        "slow_lane": slow,
        "tier1_durations": durations,
        "problems": problems,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--slow-lane",
                    default=os.environ.get("SLOW_LANE_JSON",
                                           "/tmp/_slow_lane_summary.json"))
    ap.add_argument("--tier1-durations",
                    default=os.environ.get("TIER1_DURATIONS_JSON",
                                           "/tmp/tier1_durations.json"))
    ap.add_argument("--out", default="/tmp/nightly_report.json")
    ap.add_argument("--require", default="",
                    help="comma list of inputs that MUST be present "
                         "(slow,tier1); missing ones then fail the "
                         "report instead of reading as null")
    ap.add_argument("--smoke", action="store_true",
                    help="self-test against synthetic inputs in a "
                         "tempdir (ignores the path flags)")
    args = ap.parse_args(argv)

    if args.smoke:
        return _smoke()

    require = tuple(p for p in args.require.split(",") if p)
    report = build_report(args.slow_lane, args.tier1_durations,
                          require=require)
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
    os.replace(tmp, args.out)
    print(json.dumps(report))
    return 0 if report["ok"] else 1


def _smoke() -> int:
    """End-to-end self-check: green inputs -> ok, red inputs -> problems."""
    import tempfile
    with tempfile.TemporaryDirectory(prefix="nightly_report_") as td:
        slow = os.path.join(td, "slow.json")
        dur = os.path.join(td, "durations.json")
        out = os.path.join(td, "report.json")

        # green: a healthy slow lane buried in cron-log noise + an
        # in-budget tier-1 report
        with open(slow, "w") as f:
            f.write("some cron banner\n"
                    "not json {{{\n"
                    '{"lane": "slow", "rc": 0, "passed": 38, "failed": 0,'
                    ' "errors": 0, "skipped": 1, "duration_s": 1234.5,'
                    ' "timed_out": false, "log": "/tmp/x.log"}\n')
        with open(dur, "w") as f:
            json.dump({"total_s": 500.0, "budget_s": 870.0,
                       "over_budget": False, "markexpr": "not slow",
                       "per_file": {"tests/test_x.py": 500.0}}, f)
        rc = main(["--slow-lane", slow, "--tier1-durations", dur,
                   "--out", out, "--require", "slow,tier1"])
        assert rc == 0, rc
        rep = _read_json(out)
        assert rep and rep["ok"] and rep["slow_lane"]["passed"] == 38, rep

        # red: failing slow lane + over-budget tier-1
        with open(slow, "w") as f:
            f.write('{"lane": "slow", "rc": 1, "passed": 30, "failed": 8,'
                    ' "errors": 0, "skipped": 1, "duration_s": 2000,'
                    ' "timed_out": false, "log": "/tmp/x.log"}\n')
        with open(dur, "w") as f:
            json.dump({"total_s": 900.0, "budget_s": 870.0,
                       "over_budget": True, "markexpr": "not slow",
                       "per_file": {}}, f)
        rc = main(["--slow-lane", slow, "--tier1-durations", dur,
                   "--out", out])
        assert rc == 1, rc
        rep = _read_json(out)
        assert rep and not rep["ok"] and len(rep["problems"]) == 2, rep

        # missing inputs: null without --require, problem with it
        missing = os.path.join(td, "nope.json")
        rc = main(["--slow-lane", missing, "--tier1-durations", missing,
                   "--out", out])
        assert rc == 0, rc
        rc = main(["--slow-lane", missing, "--tier1-durations", missing,
                   "--out", out, "--require", "slow,tier1"])
        assert rc == 1, rc
    print(json.dumps({"metric": "nightly_report_smoke", "value": 1,
                      "unit": "ok", "extra": {"checks": 4}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
