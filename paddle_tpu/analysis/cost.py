"""Per-equation FLOPs / bytes / peak-live-bytes estimation over jaxprs.

The estimates feed two consumers: the "top-k most expensive equations"
table in analysis reports (where per-site numbers matter) and total-cost
regressions like tools/pipeline_flops.py (where traversal semantics
matter: scan bodies bill per trip — XLA's cost_analysis prices a While
body once, hiding exactly the per-tick redundancy pipeline schedules can
hide — and cond branches bill at their MAX, the busiest device's bill).

Conventions:
- dot_general: 2*B*M*N*K multiply-adds; conv_general_dilated the same
  over the implied patch matmul.
- elementwise / everything unpriced: one FLOP per output element.
- bytes: operands + results (HBM traffic lower bound, ignores fusion).
- peak-live-bytes: linear-scan liveness over each jaxpr, inner jaxprs
  billed as their own peak at their call point; an estimate of the
  unfused working set, not an XLA allocator prediction.
"""
from __future__ import annotations

from typing import Callable, List

from .report import CostRow, CostSummary
from .walker import source_summary, subjaxprs, unwrap, walk

__all__ = [
    "aval_bytes", "eqn_flops", "eqn_bytes", "dot_general_flops",
    "total_flops", "matmul_flops", "peak_live_bytes", "top_equations",
    "summarize",
]


def aval_bytes(aval) -> float:
    """Concrete byte size of an abstract value (0 for tokens etc.)."""
    dtype = getattr(aval, "dtype", None)
    size = getattr(aval, "size", None)
    if dtype is None or size is None:
        return 0.0
    return float(size) * getattr(dtype, "itemsize", 4)


def _var_bytes(v) -> float:
    return aval_bytes(getattr(v, "aval", None))


def dot_general_flops(eqn) -> float:
    """2*B*M*N*K for a dot_general from its dimension numbers."""
    (lc, _rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = 1
    for i in lb:
        batch *= lhs[i]
    k = 1
    for i in lc:
        k *= lhs[i]
    m = 1
    for i, d in enumerate(lhs):
        if i not in lc and i not in lb:
            m *= d
    n = 1
    rc, rb = set(_rc), set(_rb)
    for i, d in enumerate(rhs):
        if i not in rc and i not in rb:
            n *= d
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    """2 * out_elements * (kernel_spatial * in_channels / groups)."""
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval.shape  # OIHW-ordered by dimension_numbers
    dn = eqn.params["dimension_numbers"]
    groups = int(eqn.params.get("feature_group_count", 1))
    in_ch = rhs[dn.rhs_spec[1]]
    spatial = 1
    for i in dn.rhs_spec[2:]:
        spatial *= rhs[i]
    return 2.0 * float(out.size) * spatial * in_ch / max(groups, 1)


def _out_elems(eqn) -> float:
    return float(sum(getattr(v.aval, "size", 0) for v in eqn.outvars))


def _reduce_flops(eqn) -> float:
    return float(sum(getattr(v.aval, "size", 0) for v in eqn.invars
                     if hasattr(v, "aval")))


_FLOPS_FNS = {
    "dot_general": dot_general_flops,
    "conv_general_dilated": _conv_flops,
    "reduce_sum": _reduce_flops,
    "reduce_max": _reduce_flops,
    "reduce_min": _reduce_flops,
    "reduce_prod": _reduce_flops,
    "reduce_and": _reduce_flops,
    "reduce_or": _reduce_flops,
    "argmax": _reduce_flops,
    "argmin": _reduce_flops,
}

# pure data movement / bookkeeping: zero FLOPs
_FREE = frozenset({
    "reshape", "squeeze", "expand_dims", "broadcast_in_dim", "transpose",
    "convert_element_type", "bitcast_convert_type", "stop_gradient",
    "copy", "device_put", "slice", "dynamic_slice", "dynamic_update_slice",
    "concatenate", "pad", "gather", "scatter", "rev", "iota",
    "split", "select_n",
})


def eqn_flops(eqn) -> float:
    """FLOPs of one equation body (inner jaxprs NOT included)."""
    name = eqn.primitive.name
    fn = _FLOPS_FNS.get(name)
    if fn is not None:
        try:
            return fn(eqn)
        except Exception:
            return _out_elems(eqn)
    if name in _FREE:
        return 0.0
    return _out_elems(eqn)


def eqn_bytes(eqn) -> float:
    """Operand + result bytes of one equation (traffic lower bound)."""
    total = 0.0
    for v in eqn.invars:
        total += _var_bytes(v)
    for v in eqn.outvars:
        total += _var_bytes(v)
    return total


# -- totals with traversal semantics ----------------------------------------

def _total(jaxpr, cost_fn: Callable, while_trips: float = 1.0) -> float:
    raw, _ = unwrap(jaxpr)
    tot = 0.0
    for eqn in raw.eqns:
        subs = list(subjaxprs(eqn))
        if not subs:
            tot += cost_fn(eqn)
            continue
        kind = subs[0].kind
        if kind == "scan":
            tot += subs[0].trips * _total(subs[0].jaxpr, cost_fn,
                                          while_trips)
        elif kind == "cond":
            tot += max(_total(s.jaxpr, cost_fn, while_trips)
                       for s in subs)
        elif kind == "while":
            tot += while_trips * sum(_total(s.jaxpr, cost_fn, while_trips)
                                     for s in subs)
        else:  # transparent call / shard_map / unknown higher-order
            tot += sum(_total(s.jaxpr, cost_fn, while_trips) for s in subs)
    return tot


def total_flops(jaxpr, while_trips: float = 1.0) -> float:
    """All-primitive FLOPs estimate (scan x length, cond max)."""
    return _total(jaxpr, eqn_flops, while_trips)


def matmul_flops(jaxpr, while_trips: float = 1.0) -> float:
    """dot_general-only FLOPs — the pipeline_flops regression metric."""
    return _total(
        jaxpr,
        lambda e: dot_general_flops(e)
        if e.primitive.name == "dot_general" else 0.0,
        while_trips)


def total_bytes(jaxpr, while_trips: float = 1.0) -> float:
    return _total(jaxpr, eqn_bytes, while_trips)


# -- peak live bytes ---------------------------------------------------------

def peak_live_bytes(jaxpr) -> float:
    """Liveness-scan peak working set for one jaxpr (recursive: a call
    equation contributes its body's peak at its program point)."""
    raw, _ = unwrap(jaxpr)
    is_var = lambda a: hasattr(a, "aval") and not hasattr(a, "val")  # noqa: E731
    last_use = {}
    for i, eqn in enumerate(raw.eqns):
        for a in eqn.invars:
            if is_var(a):
                last_use[id(a)] = i
    n = len(raw.eqns)
    for v in raw.outvars:
        if is_var(v):
            last_use[id(v)] = n  # outputs stay live to the end
    sizes = {}
    cur = 0.0
    for v in list(raw.invars) + list(raw.constvars):
        sizes[id(v)] = _var_bytes(v)
        cur += sizes[id(v)]
    peak = cur
    for i, eqn in enumerate(raw.eqns):
        inner = 0.0
        for s in subjaxprs(eqn):
            inner = max(inner, peak_live_bytes(s.jaxpr))
        out_b = 0.0
        for v in eqn.outvars:
            sizes[id(v)] = _var_bytes(v)
            out_b += sizes[id(v)]
        cur += out_b
        peak = max(peak, cur + inner)
        # free everything whose last use is this equation (including
        # outputs never consumed downstream)
        for a in list(eqn.invars) + list(eqn.outvars):
            if is_var(a) and last_use.get(id(a), -1) <= i:
                cur -= sizes.pop(id(a), 0.0)
                last_use.pop(id(a), None)
    return peak


# -- top-k table -------------------------------------------------------------

def _out_sig(eqn) -> str:
    sigs = []
    for v in eqn.outvars:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            continue
        dt = getattr(aval, "dtype", None)
        sigs.append(f"{getattr(dt, 'name', dt)}"
                    f"[{','.join(str(d) for d in aval.shape)}]")
    return " ".join(sigs)


def top_equations(jaxpr, k: int = 10) -> List[CostRow]:
    """The k most expensive equations by trip-multiplied FLOPs (bytes
    break ties, so huge data movers surface even at 0 FLOPs)."""
    rows = []
    for site in walk(jaxpr):
        if has_inner_cheap(site.eqn):
            continue  # call shells: their cost is their inner equations'
        f = eqn_flops(site.eqn) * site.trips
        b = eqn_bytes(site.eqn) * site.trips
        if f <= 0 and b <= 0:
            continue
        rows.append(CostRow(
            primitive=site.primitive, path="/".join(site.path) or "<top>",
            eqn_index=site.index, flops=f, bytes=b,
            out=_out_sig(site.eqn), trips=site.trips,
            source=source_summary(site.eqn)))
    rows.sort(key=lambda r: (-r.flops, -r.bytes))
    return rows[:k]


def has_inner_cheap(eqn) -> bool:
    for _ in subjaxprs(eqn):
        return True
    return False


def summarize(jaxpr, k: int = 10, while_trips: float = 1.0) -> CostSummary:
    raw, _ = unwrap(jaxpr)
    arg_bytes = float(sum(_var_bytes(v) for v in raw.invars))
    return CostSummary(
        total_flops=total_flops(jaxpr, while_trips),
        matmul_flops=matmul_flops(jaxpr, while_trips),
        total_bytes=total_bytes(jaxpr, while_trips),
        peak_live_bytes=peak_live_bytes(jaxpr),
        arg_bytes=arg_bytes,
        top=top_equations(jaxpr, k))
