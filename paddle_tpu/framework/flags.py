"""Global runtime flags (reference: platform/flags.cc — 35 gflags; python
surface paddle.set_flags/get_flags via pybind/global_value_getter_setter.cc).

TPU-native translation: a single in-process registry, initialised from
``FLAGS_*`` environment variables exactly like gflags' env fallback.  Flags
that configured CUDA allocator/cudnn behaviour have no TPU meaning and are
registered as accepted-but-inert (documented per flag) so reference code that
sets them keeps working.  The debugging flags are live:

- ``FLAGS_check_nan_inf`` (reference platform/flags.cc:44, consumed at
  operator.cc:1183): here consumed by ``check_numerics`` which the optimizer
  and trainer call on grads/loss when the flag is on (eager host check).
- ``FLAGS_benchmark`` (operator.cc:1171): makes the trainer block on the
  device after every step.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_REGISTRY: Dict[str, Any] = {}


def _define(name: str, default, help_: str = ""):
    env = os.environ.get("FLAGS_" + name)
    if env is not None:
        if isinstance(default, bool):
            default = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            default = int(env)
        elif isinstance(default, float):
            default = float(env)
        else:
            default = env
    _REGISTRY[name] = default


# Debugging / numerics (live)
_define("check_nan_inf", False, "scan outputs/grads for NaN/Inf each step")
_define("check_replication", False,
        "verify params declared replicated are bit-identical on every "
        "device after each step (debug aid for the shard_map "
        "check_vma=False declarations)")
_define("benchmark", False, "synchronise device after each op/step")
_define("check_kernel_launch", False, "alias of benchmark on TPU")
# Threading / host (live where meaningful)
_define("paddle_num_threads", 1, "host threads for data feed")
# Memory flags (inert: XLA's BFC allocator manages HBM; kept for parity)
_define("fraction_of_gpu_memory_to_use", 0.92, "inert on TPU")
_define("initial_gpu_memory_in_mb", 0, "inert on TPU")
_define("reallocate_gpu_memory_in_mb", 0, "inert on TPU")
_define("memory_fraction_of_eager_deletion", 1.0, "inert: XLA liveness")
_define("eager_delete_tensor_gb", 0.0, "inert: XLA liveness")
_define("allocator_strategy", "auto_growth", "inert: XLA BFC")
_define("use_pinned_memory", True, "host staging buffers")
# cudnn/conv flags (inert; XLA picks conv algorithms)
_define("cudnn_deterministic", False, "maps to XLA deterministic ops")
_define("cudnn_exhaustive_search", False, "inert: XLA autotuning")
_define("conv_workspace_size_limit", 512, "inert")
# Distributed
_define("sync_nccl_allreduce", True, "inert: XLA schedules collectives")


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags — accepts both 'FLAGS_x' and bare 'x' keys."""
    for k, v in flags.items():
        name = k[6:] if k.startswith("FLAGS_") else k
        if name not in _REGISTRY:
            raise ValueError(f"unknown flag {k!r}")
        _REGISTRY[name] = v


def get_flags(flags):
    """paddle.get_flags — returns {'FLAGS_x': value}."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        name = k[6:] if k.startswith("FLAGS_") else k
        if name not in _REGISTRY:
            raise ValueError(f"unknown flag {k!r}")
        out["FLAGS_" + name] = _REGISTRY[name]
    return out


def flag(name: str):
    return _REGISTRY[name]


def check_numerics(tree, tag: str = ""):
    """Host-side NaN/Inf scan over a pytree when FLAGS_check_nan_inf is on.

    Reference analogue: operator.cc:1183 → details/nan_inf_utils_detail.cu
    (per-op output scan). Here the scan sits at step granularity: optimizer
    grads and trainer loss. Raises FloatingPointError naming the first bad
    leaf, like the reference's enforce failure.
    """
    if not _REGISTRY["check_nan_inf"]:
        return
    import jax
    import numpy as np
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            name = jax.tree_util.keystr(path)
            raise FloatingPointError(
                f"FLAGS_check_nan_inf: non-finite value in {tag}{name}")
