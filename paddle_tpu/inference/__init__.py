"""paddle.inference — serving-style predictor API (reference:
paddle/fluid/inference/api/ — AnalysisPredictor analysis_predictor.h:82,
Run at analysis_predictor.cc:389, CreatePaddlePredictor :1197; python
surface paddle/inference with Config / create_predictor / handles).

TPU-native design: the reference's graph-analysis pipeline (ir passes, TRT
subgraphs, NaiveExecutor) collapses into the StableHLO artifact written by
``paddle_tpu.jit.save`` — XLA *is* the analysis+fusion pipeline, applied at
load time when the exported program is recompiled for the serving device.
The handle API (get_input_handle / copy_from_cpu / run / copy_to_cpu)
matches the reference's zero-copy tensor handles.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np


class Config:
    """reference: paddle_analysis_config.h AnalysisConfig (python
    paddle.inference.Config). Holds the model path + device choice; the
    CUDA/TRT/MKLDNN toggles are accepted for source compat and mapped onto
    the single XLA compilation path."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file is not None:
            for suffix in (".stablehlo", ".pdmodel", ".json"):
                if prog_file.endswith(suffix):
                    prog_file = prog_file[:-len(suffix)]
                    break
        self._prefix = prog_file
        self._device = None  # default backend
        self._enable_profile = False
        self._memory_pool_mb = 0
        self._weight_quant = None  # (policy, block) once enabled

    # -- model ---------------------------------------------------------------
    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        """Point the config at a new model WITHOUT wiping the device /
        profiling / quantization choices already made on it (the
        reference keeps those orthogonal to the model path)."""
        kept = (self._device, self._enable_profile, self._memory_pool_mb,
                self._weight_quant)
        self.__init__(prog_file, params_file)
        (self._device, self._enable_profile, self._memory_pool_mb,
         self._weight_quant) = kept

    def prog_file(self) -> str:
        return (self._prefix or "") + ".stablehlo"

    def params_file(self) -> str:
        return (self._prefix or "") + ".pdiparams"

    def model_dir(self) -> str:
        return os.path.dirname(self._prefix or "")

    # -- device --------------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0):
        """Source-compat: selects the accelerator backend (TPU here)."""
        self._memory_pool_mb = memory_pool_init_size_mb
        self._device = None  # default accelerator

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self) -> bool:
        import jax
        return (self._device is None
                and jax.default_backend() != "cpu")

    def enable_profile(self):
        self._enable_profile = True

    # -- weight quantization ---------------------------------------------
    def enable_weight_quantize(self, policy: str = "int8",
                               block: Optional[int] = None):
        """Serve with block-quantized weights (int8/int4 at rest, see
        ``inference.quant``) — the EQuARX block quantizer applied to the
        loaded parameters instead of the gradient wire."""
        if policy not in ("int8", "int4"):
            raise ValueError(f"weight quant policy must be int8/int4, "
                             f"got {policy!r}")
        self._weight_quant = (policy, block)

    # accepted-but-inert reference toggles (XLA owns these optimizations).
    # Each warns once per process so callers porting reference configs are
    # told their knob does nothing here instead of silently ignored.
    _warned_toggles: set = set()

    def _inert(self, name: str, owner: str):
        if name not in Config._warned_toggles:
            Config._warned_toggles.add(name)
            import warnings
            warnings.warn(
                f"Config.{name} is accepted for source compatibility but "
                f"has no effect on TPU: {owner}", stacklevel=3)

    def switch_ir_optim(self, flag: bool = True):
        self._inert("switch_ir_optim",
                    "XLA applies its own graph optimizations under jit")

    def enable_memory_optim(self):
        self._inert("enable_memory_optim",
                    "XLA's buffer assignment owns memory reuse")

    def enable_tensorrt_engine(self, *a, **kw):
        self._inert("enable_tensorrt_engine",
                    "there is no TensorRT on TPU; XLA compiles the graph")

    def enable_mkldnn(self):
        self._inert("enable_mkldnn",
                    "there is no oneDNN path; XLA:CPU/TPU compiles the graph")

    def set_cpu_math_library_num_threads(self, n: int):
        self._inert("set_cpu_math_library_num_threads",
                    "XLA:CPU owns its thread pool")


class Tensor:
    """Input/output handle (reference: paddle_tensor.h ZeroCopyTensor —
    copy_from_cpu/copy_to_cpu)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self._name = name
        self._owner = owner
        self._is_input = is_input

    def name(self) -> str:
        return self._name

    def copy_from_cpu(self, data):
        assert self._is_input, "cannot write an output handle"
        self._owner._inputs[self._name] = np.ascontiguousarray(data)

    def copy_to_cpu(self):
        assert not self._is_input, "cannot read an input handle"
        return np.asarray(self._owner._outputs[self._name])

    def shape(self):
        store = (self._owner._inputs if self._is_input
                 else self._owner._outputs)
        return list(np.shape(store[self._name]))


# Per-prefix load cache: N predictors over the same artifact share ONE
# loaded layer (exported program + params), so a PredictorPool genuinely
# shares the compiled executable instead of re-running jit.load per
# member. Keyed on (abspath, mtime_ns, size) of both artifact files so a
# re-saved model is reloaded, not served stale. Weight-quantized views
# are cached next to the raw layer under the quant spec.
#
# Hot-swap interplay: when a new model generation overwrites the artifact
# in place, the incumbent generation's entry must survive until the
# rollout is decided — a rollback that re-stats the file would key onto
# the NEW bytes and load the very artifact it is rolling back from. The
# fleet therefore pins the incumbent's key (``pin_layer``) before a
# canary starts and loads through ``Predictor(config, layer_key=key)``;
# pinned entries are immune to ``evict_stale_layers``.
_LAYER_CACHE: Dict[tuple, object] = {}
_LAYER_CACHE_PINS: Dict[tuple, int] = {}
_LAYER_CACHE_LOCK = threading.Lock()


def _layer_cache_key(prefix: str, quant=None) -> tuple:
    key = [os.path.abspath(prefix), quant]
    for suffix in (".stablehlo", ".pdiparams"):
        try:
            st = os.stat(prefix + suffix)
            key.append((st.st_mtime_ns, st.st_size))
        except OSError:
            key.append(None)
    return tuple(key)


def layer_cache_key(prefix: str, quant=None) -> tuple:
    """Public form of the cache key for the artifact currently on disk —
    capture it BEFORE a hot-swap overwrites the files, then pin it."""
    return _layer_cache_key(prefix, quant)


def pin_layer(key: tuple) -> None:
    """Refcount-pin a cache entry so eviction never drops it; loading
    through ``_load_layer(..., key=key)`` then serves the pinned bytes
    regardless of what the artifact files say now."""
    with _LAYER_CACHE_LOCK:
        _LAYER_CACHE_PINS[key] = _LAYER_CACHE_PINS.get(key, 0) + 1


def unpin_layer(key: tuple) -> None:
    with _LAYER_CACHE_LOCK:
        n = _LAYER_CACHE_PINS.get(key, 0) - 1
        if n > 0:
            _LAYER_CACHE_PINS[key] = n
        else:
            _LAYER_CACHE_PINS.pop(key, None)


def evict_stale_layers() -> int:
    """Drop cache entries whose artifact files changed since load
    (stat key no longer matches) — EXCEPT pinned ones. Returns the
    number evicted."""
    evicted = 0
    with _LAYER_CACHE_LOCK:
        for key in list(_LAYER_CACHE):
            if _LAYER_CACHE_PINS.get(key):
                continue
            prefix, quant = key[0], key[1]
            if _layer_cache_key(prefix, quant) != key:
                del _LAYER_CACHE[key]
                evicted += 1
    return evicted


def _load_layer(prefix: str, quant=None, key: Optional[tuple] = None):
    """Load (or fetch cached) the exported layer for ``prefix``.

    ``key`` requests a SPECIFIC cached generation (normally pinned): the
    load must not fall back to whatever bytes are on disk now — if the
    entry is gone and the on-disk artifact no longer matches the key,
    that generation is unrecoverable and this raises ``KeyError`` rather
    than silently serving the wrong model.
    """
    if key is not None:
        with _LAYER_CACHE_LOCK:
            layer = _LAYER_CACHE.get(key)
        if layer is not None:
            return layer
        if _layer_cache_key(prefix, quant) != key:
            raise KeyError(
                f"pinned layer generation {key!r} is not cached and the "
                f"on-disk artifact no longer matches it")
        # files still match the requested key: a normal load is that
        # generation
    key = _layer_cache_key(prefix, quant) if key is None else key
    with _LAYER_CACHE_LOCK:
        layer = _LAYER_CACHE.get(key)
    if layer is not None:
        return layer
    if quant is None:
        from .. import jit
        layer = jit.load(prefix)
    else:
        from . import quant as quant_mod
        layer, _ = quant_mod.quantized_layer(
            _load_layer(prefix), policy=quant[0], block=quant[1])
    with _LAYER_CACHE_LOCK:
        return _LAYER_CACHE.setdefault(key, layer)


def clear_layer_cache():
    with _LAYER_CACHE_LOCK:
        _LAYER_CACHE.clear()
        _LAYER_CACHE_PINS.clear()


class Predictor:
    """reference: AnalysisPredictor. Loads the StableHLO artifact once
    (shared per prefix across a pool); ``run`` executes the compiled
    program on the serving device."""

    def __init__(self, config: Config, layer_key: Optional[tuple] = None):
        self._config = config
        self._layer = _load_layer(config._prefix, config._weight_quant,
                                  key=layer_key)
        self._layer_key = (layer_key if layer_key is not None
                           else _layer_cache_key(config._prefix,
                                                 config._weight_quant))
        self._input_names = [f"x{i}" for i in range(self._n_user_inputs())]
        self._output_names = ["out0"]
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}

    def _n_user_inputs(self) -> int:
        exported = self._layer._exported
        try:
            # jit.save exports with in_tree ((params, buffers, *args), kwargs)
            args = exported.in_tree.children()[0].children()
            return max(len(args) - 2, 1)
        except Exception:
            # non-conforming export (foreign tree layout): serve one
            # positional input rather than crash on an IndexError
            return 1

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_input_handle(self, name: str) -> Tensor:
        assert name in self._input_names, name
        return Tensor(name, self, True)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self, False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute. Either pass a positional list (returns outputs list) or
        pre-fill input handles and read output handles (reference style)."""
        if inputs is None:
            inputs = [self._inputs[n] for n in self._input_names]
        out = self._layer(*inputs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {n: o for n, o in zip(self._output_names, outs)}
        return [np.asarray(o) for o in outs]

    def clear_intermediate_tensor(self):
        self._inputs.clear()
        self._outputs.clear()


def create_predictor(config: Config) -> Predictor:
    """reference: CreatePaddlePredictor (analysis_predictor.cc:1197)."""
    return Predictor(config)


__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "clear_layer_cache", "layer_cache_key", "pin_layer",
           "unpin_layer", "evict_stale_layers"]


class DataType:
    """reference paddle_infer_declare.h PaddleDType — dtype tags carried
    by inference Tensors."""
    FLOAT32 = "float32"
    FLOAT16 = "float16"  # source-compat tag; TPU half type is bfloat16
    BFLOAT16 = "bfloat16"
    INT64 = "int64"
    INT32 = "int32"
    UINT8 = "uint8"
    INT8 = "int8"
    BOOL = "bool"


class PlaceType:
    """reference place tags; XLA owns placement here."""
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    TPU = 3


class PrecisionType:
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


_DTYPE_BYTES = {DataType.FLOAT32: 4, DataType.FLOAT16: 2,
                DataType.BFLOAT16: 2, DataType.INT64: 8,
                DataType.INT32: 4, DataType.UINT8: 1, DataType.INT8: 1,
                DataType.BOOL: 1}


def get_num_bytes_of_data_type(dtype) -> int:
    """reference inference/api/paddle_inference_api.h."""
    try:
        return _DTYPE_BYTES[dtype]
    except KeyError:
        raise ValueError(f"unknown inference DataType {dtype!r}") from None


def get_version() -> str:
    from .. import __version__
    return f"paddle_tpu {__version__}"


class PredictorPool:
    """reference paddle_infer::services::PredictorPool — N predictors over
    one config for concurrent serving threads. Predictors share the
    compiled executable (jit cache); each retains its own IO handles."""

    def __init__(self, config: Config, size: int = 1):
        if size < 1:
            raise ValueError("pool size must be >= 1")
        self._preds = [create_predictor(config) for _ in range(size)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]

    def __len__(self):
        return len(self._preds)


__all__ += ["DataType", "PlaceType", "PrecisionType", "PredictorPool",
            "get_num_bytes_of_data_type", "get_version"]


def __getattr__(name):
    # lazy submodules: the serving runtime / weight quantizer are only
    # imported when asked for, keeping the base handle API import-light
    if name in ("serving", "quant", "kv_cache", "decode_model",
                "fleet", "executor_cache", "spec_decode"):
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
