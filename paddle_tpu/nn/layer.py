"""Layer: the imperative module system.

TPU-native re-design of the reference's dygraph Layer
(reference: python/paddle/fluid/dygraph/layers.py — parameters, sublayers,
buffers, hooks, state_dict) on top of JAX. A Layer owns mutable
``Parameter`` boxes and buffer entries; eager forward just computes with
jax ops on the current values. For compiled execution, ``functional_call``
(see paddle_tpu/jit/functionalization.py) swaps traced values in, making any
Layer a pure function of its state — the dygraph/static duality of the
reference (dygraph_to_static/) collapses into this single bridge.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.naming import unique_name


class Parameter:
    """A mutable box holding a jax.Array leaf of a Layer.

    Equivalent of the reference's ``framework.Parameter``
    (python/paddle/fluid/framework.py) without the Program machinery.
    ``pspec`` optionally carries a ``jax.sharding.PartitionSpec`` used by the
    distributed engine to shard this parameter over the mesh (the TPU-native
    analogue of the reference's per-parameter ``is_distributed`` /
    ``split``-ed vars in fleet/meta_parallel/parallel_layers/mp_layers.py).
    """

    __slots__ = ("value", "name", "trainable", "grad", "pspec", "optimize_attr")

    def __init__(self, value, name: Optional[str] = None, trainable: bool = True):
        self.value = value
        self.name = name or unique_name("param")
        self.trainable = trainable
        self.grad = None
        self.pspec = None  # PartitionSpec for distributed sharding
        self.optimize_attr = {"learning_rate": 1.0}

    # -- array-ish conveniences -------------------------------------------
    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return self.value.ndim

    @property
    def stop_gradient(self):
        return not self.trainable

    @stop_gradient.setter
    def stop_gradient(self, v):
        self.trainable = not v

    def numpy(self):
        return np.asarray(self.value)

    def set_value(self, v):
        self.value = jnp.asarray(v, dtype=self.value.dtype)

    def clear_grad(self):
        self.grad = None

    def astype(self, dt):
        self.value = self.value.astype(dtype_mod.convert_dtype_to_jax(dt))
        return self

    def __jax_array__(self):
        return self.value

    def __repr__(self):
        return (f"Parameter(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, trainable={self.trainable})")


class HookRemoveHelper:
    def __init__(self, hooks: OrderedDict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    """Base class for all network modules (reference: dygraph/layers.py Layer)."""

    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        self.training = True
        self._dtype = dtype_mod.convert_dtype_to_jax(dtype) or dtype_mod.get_default_dtype()
        self._full_name = unique_name(name_scope or type(self).__name__.lower())
        self._forward_pre_hooks: OrderedDict = OrderedDict()
        self._forward_post_hooks: OrderedDict = OrderedDict()
        self._hook_id = 0

    # -- construction ------------------------------------------------------
    def create_parameter(self, shape, dtype=None, initializer=None,
                         is_bias: bool = False, attr=None, trainable: bool = True,
                         name: Optional[str] = None) -> Parameter:
        from .initializer import (Constant, XavierUniform, _global_default,
                                  _to_initializer)
        dt = dtype_mod.convert_dtype_to_jax(dtype) or self._dtype
        # precedence (reference set_global_initializer semantics): an
        # attr-specified initializer wins; otherwise the global default
        # overrides the layer's own default passed via `initializer`.
        init = _to_initializer(attr, None)
        if init is None:
            init = _global_default(is_bias)
        if init is None:
            init = initializer
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        value = init(shape, dt)
        p = Parameter(value, name=name, trainable=trainable)
        if attr is not None and getattr(attr, "learning_rate", None) is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        self._buffers[name] = tensor if tensor is None else jnp.asarray(tensor)
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return self._buffers.get(name)

    # -- attribute routing -------------------------------------------------
    def __setattr__(self, name: str, value: Any):
        if isinstance(value, Parameter):
            self.__dict__.pop(name, None)
            self._parameters[name] = value
        elif isinstance(value, Layer):
            self.__dict__.pop(name, None)
            self._sub_layers[name] = value
        elif "_buffers" in self.__dict__ and name in self._buffers:
            self._buffers[name] = value if value is None else jnp.asarray(value)
        elif "_parameters" in self.__dict__ and name in self._parameters and value is None:
            self._parameters[name] = None
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        d = self.__dict__
        if "_parameters" in d and name in d["_parameters"]:
            return d["_parameters"][name]
        if "_buffers" in d and name in d["_buffers"]:
            return d["_buffers"][name]
        if "_sub_layers" in d and name in d["_sub_layers"]:
            return d["_sub_layers"][name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in (self._parameters, self._buffers, self._sub_layers):
            if name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    # -- iteration ---------------------------------------------------------
    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True,
                                           layers_set=layers_set)

    def sublayers(self, include_self: bool = False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (lp + ("." if lp else "") + name, p)

    def parameters(self, include_sublayers: bool = True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None:
                    continue
                yield (lp + ("." if lp else "") + name, b)

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    # -- mode / dtype ------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable[["Layer"], None]):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self.astype(dtype)
        if device is not None:
            dev = device if not isinstance(device, str) else _resolve_device(device)
            for l in self.sublayers(include_self=True):
                for p in l._parameters.values():
                    if p is not None:
                        p.value = jax.device_put(p.value, dev)
                for k, b in l._buffers.items():
                    if b is not None:
                        l._buffers[k] = jax.device_put(b, dev)
        return self

    def astype(self, dt):
        dt = dtype_mod.convert_dtype_to_jax(dt)
        for l in self.sublayers(include_self=True):
            l._dtype = dt
            for p in l._parameters.values():
                if p is not None and dtype_mod.is_floating(p.dtype):
                    p.value = p.value.astype(dt)
            for k, b in l._buffers.items():
                if b is not None and dtype_mod.is_floating(b.dtype):
                    l._buffers[k] = b.astype(dt)
        return self

    def float(self):
        return self.astype(jnp.float32)

    def bfloat16(self):
        return self.astype(jnp.bfloat16)

    # -- state dict --------------------------------------------------------
    def state_dict(self, include_sublayers: bool = True, structured_name_prefix: str = ""):
        out = OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix,
                                             include_sublayers=include_sublayers):
            out[name] = p.value
        layers = self.named_sublayers(prefix=structured_name_prefix, include_self=True) \
            if include_sublayers else [(structured_name_prefix, self)]
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or name in layer._non_persistable_buffer_names:
                    continue
                out[lp + ("." if lp else "") + name] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        missing, unexpected = [], list(state_dict.keys())
        own = self.state_dict()
        param_map = {n: p for n, p in self.named_parameters()}
        buf_owners = {}
        for lp, layer in self.named_sublayers(include_self=True):
            for name in layer._buffers:
                buf_owners[lp + ("." if lp else "") + name] = (layer, name)
        for name in own:
            if name not in state_dict:
                missing.append(name)
                continue
            unexpected.remove(name)
            v = jnp.asarray(state_dict[name])
            if name in param_map:
                p = param_map[name]
                if tuple(v.shape) != tuple(p.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: got {tuple(v.shape)}, "
                        f"expected {tuple(p.shape)}")
                p.value = v.astype(p.dtype)
            else:
                layer, bname = buf_owners[name]
                layer._buffers[bname] = v.astype(layer._buffers[bname].dtype)
        return missing, unexpected

    load_dict = set_state_dict

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call --------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            body = repr(sub).split("\n")
            body = [body[0]] + ["  " + l for l in body[1:]]
            lines.append(f"  ({name}): " + "\n".join(body))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def full_name(self):
        return self._full_name

    def clear_gradients(self):
        for p in self.parameters():
            p.grad = None


def _resolve_device(name: str):
    import jax
    if name in ("cpu",):
        return jax.devices("cpu")[0]
    if name.startswith(("gpu", "tpu", "cuda")):
        plat = "tpu" if name.startswith("tpu") else "gpu"
        idx = int(name.split(":")[1]) if ":" in name else 0
        return jax.devices(plat)[idx]
    return jax.devices()[0]
