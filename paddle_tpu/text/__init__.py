"""paddle_tpu.text — NLP models & datasets (reference: python/paddle/text/)."""
from . import models  # noqa: F401
from . import datasets  # noqa: F401
from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16)
