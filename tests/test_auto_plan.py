"""Auto-parallel planner tests (beyond-reference capability)."""
import pytest

from paddle_tpu.distributed import auto


def test_small_model_prefers_pure_dp():
    # GPT-base 124M on 8 x 16GB chips: fits with plain DP
    p = auto.plan(124e6, 8, layers=12, hidden=768, seq_len=1024,
                  batch_per_device=8)
    assert p.fits
    assert p.degrees["model"] == 1 and p.degrees["pipe"] == 1
    assert p.degrees["data"] * p.degrees["sharding"] == 8

def test_large_model_engages_tp_or_pp():
    # 30B params on 64 chips cannot be DP-only (opt state alone ~240GB)
    p = auto.plan(30e9, 64, layers=48, hidden=7168, seq_len=2048,
                  batch_per_device=4, zero_stage=1)
    assert p.fits
    assert p.degrees["model"] * p.degrees["pipe"] * \
        p.degrees["sharding"] > 1
    prod = 1
    for v in p.degrees.values():
        prod *= v
    assert prod == 64

def test_infeasible_raises_with_guidance():
    with pytest.raises(ValueError, match="no layout fits"):
        auto.plan(175e9, 2, layers=96, hidden=12288)

def test_zero3_avoids_pipeline_that_zero0_needs():
    kw = dict(layers=32, hidden=4096, seq_len=2048, batch_per_device=4,
              max_model=1)
    p3 = auto.plan(13e9, 16, zero_stage=3, **kw)
    assert p3.fits
    assert p3.degrees["pipe"] == 1       # sharded states fit without PP
    p0 = auto.plan(13e9, 16, zero_stage=0, **kw)
    assert p0.fits
    assert p0.degrees["pipe"] > 1        # unsharded states force PP

def test_plan_builds_a_mesh():
    p = auto.plan(10e6, 8, layers=4, hidden=256, seq_len=256,
                  batch_per_device=2)
    mesh = p.build_mesh()
    total = 1
    for s in mesh.shape.values():
        total *= s
    assert total == 8
    assert p.rationale  # human-readable why

def test_estimate_monotone_in_sharding():
    e1 = auto._estimate(1e9, {"data": 8, "sharding": 1, "model": 1,
                              "pipe": 1}, layers=24, hidden=2048,
                        seq_len=2048, batch_per_device=8, param_bytes=2,
                        zero_stage=1, remat=False)
    e8 = auto._estimate(1e9, {"data": 1, "sharding": 8, "model": 1,
                              "pipe": 1}, layers=24, hidden=2048,
                        seq_len=2048, batch_per_device=8, param_bytes=2,
                        zero_stage=1, remat=False)
    assert e8.opt_state < e1.opt_state
    assert e8.total < e1.total
