"""Detection op suite (reference paddle/fluid/operators/detection/*):
priors/anchors, box transforms, IoU/matching, NMS family, RoI pooling."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.vision import ops as V


class TestPriorsAndAnchors:
    def test_prior_box_shapes_and_centers(self):
        feat = jnp.zeros((1, 8, 4, 4))
        img = jnp.zeros((1, 3, 32, 32))
        boxes, var = V.prior_box(feat, img, min_sizes=[8.0],
                                 aspect_ratios=[1.0, 2.0], flip=True,
                                 max_sizes=[16.0])
        # priors: ar {1, 2, 0.5} * 1 min + 1 max-sq = 4
        assert boxes.shape == (4, 4, 4, 4) and var.shape == boxes.shape
        # cell (0,0): center (0.5*8, 0.5*8) = (4, 4); min box half=4
        np.testing.assert_allclose(np.asarray(boxes[0, 0, 0]),
                                   [0.0, 0.0, 0.25, 0.25], atol=1e-6)
        # max-size square prior: sqrt(8*16)/2
        m = np.sqrt(8 * 16) / 2
        got = np.asarray(boxes[0, 0])
        expect = [(4 - m) / 32, (4 - m) / 32, (4 + m) / 32, (4 + m) / 32]
        assert any(np.allclose(got[p], expect, atol=1e-6) for p in range(4))

    def test_prior_box_clip(self):
        feat = jnp.zeros((1, 8, 2, 2))
        img = jnp.zeros((1, 3, 8, 8))
        boxes, _ = V.prior_box(feat, img, min_sizes=[16.0], clip=True)
        b = np.asarray(boxes)
        assert b.min() >= 0.0 and b.max() <= 1.0

    def test_density_prior_box_counts(self):
        feat = jnp.zeros((1, 8, 2, 2))
        img = jnp.zeros((1, 3, 16, 16))
        boxes, var = V.density_prior_box(
            feat, img, densities=[2], fixed_sizes=[4.0],
            fixed_ratios=[1.0])
        assert boxes.shape == (2, 2, 4, 4)  # density^2 = 4 priors
        flat, _ = V.density_prior_box(
            feat, img, densities=[2], fixed_sizes=[4.0],
            fixed_ratios=[1.0], flatten_to_2d=True)
        assert flat.shape == (16, 4)

    def test_anchor_generator(self):
        feat = jnp.zeros((1, 8, 3, 3))
        anchors, var = V.anchor_generator(
            feat, anchor_sizes=[32.0, 64.0], aspect_ratios=[0.5, 1.0],
            stride=[16.0, 16.0])
        assert anchors.shape == (3, 3, 4, 4)
        a = np.asarray(anchors)
        assert (a[..., 2] > a[..., 0]).all() and (a[..., 3] > a[..., 1]).all()


class TestBoxTransforms:
    def test_box_coder_roundtrip(self):
        rs = np.random.RandomState(0)
        priors = np.abs(rs.rand(5, 4)).astype("f4")
        priors[:, 2:] = priors[:, :2] + 0.2 + priors[:, 2:]
        targets = priors + 0.05 * rs.randn(5, 4).astype("f4")
        var = np.full((5, 4), 0.1, dtype="f4")
        enc = V.box_coder(priors, var, targets,
                          code_type="encode_center_size")
        assert enc.shape == (5, 5, 4)
        # decode the diagonal (each target against its own prior)
        dec = V.box_coder(priors, var, enc, code_type="decode_center_size")
        diag = np.asarray(dec)[np.arange(5), np.arange(5)]
        np.testing.assert_allclose(diag, targets, rtol=1e-4, atol=1e-5)

    def test_box_coder_differentiable(self):
        priors = jnp.asarray([[0.0, 0.0, 1.0, 1.0]], jnp.float32)

        def f(t):
            return jnp.sum(V.box_coder(priors, None, t) ** 2)

        g = jax.grad(f)(jnp.asarray([[0.1, 0.1, 0.8, 0.9]], jnp.float32))
        assert np.isfinite(np.asarray(g)).all() and np.any(g != 0)

    def test_box_clip(self):
        boxes = jnp.asarray([[-5.0, -5.0, 30.0, 40.0]])
        out = V.box_clip(boxes, [20.0, 25.0, 1.0])
        np.testing.assert_allclose(np.asarray(out[0]),
                                   [0.0, 0.0, 24.0, 19.0])

    def test_polygon_box_transform(self):
        x = jnp.zeros((1, 2, 2, 3))
        out = np.asarray(V.polygon_box_transform(x))
        # even channel: 4*j; odd channel: 4*i
        np.testing.assert_allclose(out[0, 0, 0], [0.0, 4.0, 8.0])
        np.testing.assert_allclose(out[0, 1, :, 0], [0.0, 4.0])


class TestIoUAndMatching:
    def test_iou_similarity_values(self):
        a = jnp.asarray([[0.0, 0.0, 1.0, 1.0]])
        b = jnp.asarray([[0.0, 0.0, 1.0, 1.0],
                         [2.0, 2.0, 3.0, 3.0],
                         [0.5, 0.0, 1.5, 1.0]])
        iou = np.asarray(V.iou_similarity(a, b))
        np.testing.assert_allclose(iou[0], [1.0, 0.0, 1.0 / 3.0],
                                   rtol=1e-6)

    def test_bipartite_match_greedy(self):
        d = np.asarray([[0.9, 0.1, 0.3],
                        [0.8, 0.7, 0.2]])
        idx, dist = V.bipartite_match(d)
        # global max 0.9 -> row0/col0; next best row1 -> col1 (0.7)
        np.testing.assert_array_equal(np.asarray(idx), [0, 1, -1])
        np.testing.assert_allclose(np.asarray(dist), [0.9, 0.7, 0.0])

    def test_bipartite_match_per_prediction(self):
        d = np.asarray([[0.9, 0.6], [0.1, 0.2]])
        idx, dist = V.bipartite_match(d, match_type="per_prediction",
                                      dist_threshold=0.5)
        # col1's bipartite match would be row1 (0.2) — but greedy takes
        # (0,0) first, then (1,1)=0.2; per_prediction does not rematch
        # matched cols; unmatched cols above threshold get argmax row
        assert int(idx[0]) == 0


class TestNmsFamily:
    def test_nms_suppresses_overlaps(self):
        boxes = np.asarray([[0, 0, 10, 10], [1, 1, 10, 10],
                            [20, 20, 30, 30]], dtype="f4")
        scores = np.asarray([0.9, 0.8, 0.7], dtype="f4")
        keep = np.asarray(V.nms(boxes, 0.5, scores=scores))
        np.testing.assert_array_equal(keep, [0, 2])

    def test_nms_categories_do_not_suppress(self):
        boxes = np.asarray([[0, 0, 10, 10], [1, 1, 10, 10]], dtype="f4")
        scores = np.asarray([0.9, 0.8], dtype="f4")
        keep = np.asarray(V.nms(boxes, 0.5, scores=scores,
                                category_idxs=np.asarray([0, 1]),
                                categories=[0, 1]))
        assert set(keep.tolist()) == {0, 1}

    def test_multiclass_nms(self):
        bboxes = np.zeros((1, 3, 4), dtype="f4")
        bboxes[0] = [[0, 0, 10, 10], [1, 1, 10, 10], [20, 20, 30, 30]]
        scores = np.zeros((1, 2, 3), dtype="f4")
        scores[0, 1] = [0.9, 0.85, 0.6]
        outs = V.multiclass_nms(bboxes, scores, score_threshold=0.1,
                                nms_threshold=0.5, background_label=0)
        dets = np.asarray(outs[0])
        assert dets.shape[1] == 6
        assert dets.shape[0] == 2            # one suppressed
        assert (dets[:, 0] == 1).all()       # class label
        assert dets[0, 1] >= dets[1, 1]      # sorted by score

    def test_matrix_nms_decays_overlaps(self):
        bboxes = np.zeros((1, 2, 4), dtype="f4")
        bboxes[0] = [[0, 0, 10, 10], [0.5, 0.5, 10, 10]]  # IoU ~0.9
        scores = np.zeros((1, 2, 2), dtype="f4")
        scores[0, 1] = [0.9, 0.8]
        outs = V.matrix_nms(bboxes, scores, score_threshold=0.1,
                            post_threshold=0.0)
        dets = np.asarray(outs[0])
        assert dets.shape[0] == 2
        np.testing.assert_allclose(dets[0, 1], 0.9, rtol=1e-5)  # top kept
        assert dets[1, 1] < 0.2              # near-duplicate decayed hard


class TestRoiOps:
    def test_roi_align_constant_map(self):
        x = jnp.full((1, 2, 8, 8), 3.0)
        boxes = jnp.asarray([[0.0, 0.0, 8.0, 8.0]])
        out = V.roi_align(x, boxes, [1], output_size=4)
        assert out.shape == (1, 2, 4, 4)
        np.testing.assert_allclose(np.asarray(out), 3.0, rtol=1e-6)

    def test_roi_align_gradient_flows(self):
        x = jnp.asarray(np.random.RandomState(0).rand(1, 1, 8, 8), jnp.float32)
        boxes = jnp.asarray([[1.0, 1.0, 6.0, 6.0]])

        def f(xx):
            return jnp.sum(V.roi_align(xx, boxes, [1], 2))

        g = np.asarray(jax.grad(f)(x))
        assert np.isfinite(g).all() and (g != 0).any()
        # gradient localized inside the box region
        assert g[0, 0, 0, 7] == 0.0

    def test_roi_pool_exact_max(self):
        x = jnp.asarray(np.arange(16, dtype="f4").reshape(1, 1, 4, 4))
        boxes = jnp.asarray([[0.0, 0.0, 3.0, 3.0]])
        out = V.roi_pool(x, boxes, [1], output_size=2)
        np.testing.assert_allclose(np.asarray(out[0, 0]),
                                   [[5.0, 7.0], [13.0, 15.0]])

    def test_psroi_pool_uniform(self):
        x = jnp.full((1, 8, 6, 6), 2.0)   # out_c=2, ph=pw=2
        boxes = jnp.asarray([[0.0, 0.0, 5.0, 5.0]])
        out = V.psroi_pool(x, boxes, [1], output_size=2)
        assert out.shape == (1, 2, 2, 2)
        np.testing.assert_allclose(np.asarray(out), 2.0, rtol=1e-6)


class TestReviewRegressions:
    def test_box_clip_batched_im_info(self):
        boxes = jnp.asarray([[[0.0, 0.0, 400.0, 400.0]],
                             [[0.0, 0.0, 400.0, 400.0]]])
        info = np.asarray([[100.0, 100.0, 1.0], [500.0, 500.0, 1.0]])
        out = np.asarray(V.box_clip(boxes, info))
        np.testing.assert_allclose(out[0, 0], [0, 0, 99, 99])
        np.testing.assert_allclose(out[1, 0], [0, 0, 400, 400])

    def test_nms_unnormalized_pixel_iou(self):
        # two identical 1x1 pixel boxes: normalized IoU degenerate (0),
        # pixel IoU 1.0 -> second must be suppressed
        boxes = np.asarray([[5, 5, 5, 5], [5, 5, 5, 5]], dtype="f4")
        keep = np.asarray(V.nms(boxes, 0.5, scores=np.asarray([0.9, 0.8]),
                                normalized=False))
        np.testing.assert_array_equal(keep, [0])

    def test_generate_proposals_returns_scores(self):
        rs2 = np.random.RandomState(1)
        feat = jnp.zeros((1, 8, 3, 3))
        anchors, var = V.anchor_generator(
            feat, anchor_sizes=[16.0], aspect_ratios=[1.0],
            stride=[8.0, 8.0])
        scores = rs2.rand(1, 1, 3, 3).astype("f4")
        deltas = (0.05 * rs2.randn(1, 4, 3, 3)).astype("f4")
        rois, probs, num = V.generate_proposals(
            scores, deltas, np.asarray([[24.0, 24.0]]), anchors, var,
            post_nms_top_n=5, return_rois_num=True)
        assert probs is not None and probs.shape[0] == rois.shape[0]
        p = np.asarray(probs)
        assert (np.diff(p) <= 1e-6).all()  # sorted by score


class TestGenerateProposals:
    def test_shapes_and_clipping(self):
        rs = np.random.RandomState(0)
        H = W = 4
        A = 3
        scores = rs.rand(1, A, H, W).astype("f4")
        deltas = (0.1 * rs.randn(1, A * 4, H, W)).astype("f4")
        feat = jnp.zeros((1, 8, H, W))
        anchors, var = V.anchor_generator(
            feat, anchor_sizes=[16.0, 32.0, 64.0][:A] if A <= 3 else None,
            aspect_ratios=[1.0], stride=[8.0, 8.0])
        rois, _, num = V.generate_proposals(
            scores, deltas, np.asarray([[32.0, 32.0]]), anchors, var,
            pre_nms_top_n=20, post_nms_top_n=8, nms_thresh=0.7,
            return_rois_num=True)
        r = np.asarray(rois)
        assert r.shape[1] == 4 and r.shape[0] == int(num[0]) <= 8
        assert (r[:, 0] >= 0).all() and (r[:, 2] <= 31).all()
        assert (r[:, 1] >= 0).all() and (r[:, 3] <= 31).all()
