"""Per-equation FLOPs / bytes / peak-live-bytes estimation over jaxprs.

The estimates feed two consumers: the "top-k most expensive equations"
table in analysis reports (where per-site numbers matter) and total-cost
regressions like tools/pipeline_flops.py (where traversal semantics
matter: scan bodies bill per trip — XLA's cost_analysis prices a While
body once, hiding exactly the per-tick redundancy pipeline schedules can
hide — and cond branches bill at their MAX, the busiest device's bill).

Conventions:
- dot_general: 2*B*M*N*K multiply-adds; conv_general_dilated the same
  over the implied patch matmul.
- elementwise / everything unpriced: one FLOP per output element.
- bytes: operands + results (HBM traffic lower bound, ignores fusion).
- peak-live-bytes: linear-scan liveness over each jaxpr, inner jaxprs
  billed as their own peak at their call point; an estimate of the
  unfused working set, not an XLA allocator prediction.
"""
from __future__ import annotations

from typing import Callable, List

from .report import CostRow, CostSummary
from .walker import (linear_schedule, source_summary, subjaxprs, unwrap,
                     walk)

__all__ = [
    "aval_bytes", "eqn_flops", "eqn_bytes", "dot_general_flops",
    "total_flops", "matmul_flops", "peak_live_bytes", "top_equations",
    "summarize", "collective_wire_bytes", "overlap_summary",
    "overlap_plan", "replay_overlap",
]


def aval_bytes(aval) -> float:
    """Concrete byte size of an abstract value (0 for tokens etc.)."""
    dtype = getattr(aval, "dtype", None)
    size = getattr(aval, "size", None)
    if dtype is None or size is None:
        return 0.0
    return float(size) * getattr(dtype, "itemsize", 4)


def _var_bytes(v) -> float:
    return aval_bytes(getattr(v, "aval", None))


def dot_general_flops(eqn) -> float:
    """2*B*M*N*K for a dot_general from its dimension numbers."""
    (lc, _rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = 1
    for i in lb:
        batch *= lhs[i]
    k = 1
    for i in lc:
        k *= lhs[i]
    m = 1
    for i, d in enumerate(lhs):
        if i not in lc and i not in lb:
            m *= d
    n = 1
    rc, rb = set(_rc), set(_rb)
    for i, d in enumerate(rhs):
        if i not in rc and i not in rb:
            n *= d
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    """2 * out_elements * (kernel_spatial * in_channels / groups)."""
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval.shape  # OIHW-ordered by dimension_numbers
    dn = eqn.params["dimension_numbers"]
    groups = int(eqn.params.get("feature_group_count", 1))
    in_ch = rhs[dn.rhs_spec[1]]
    spatial = 1
    for i in dn.rhs_spec[2:]:
        spatial *= rhs[i]
    return 2.0 * float(out.size) * spatial * in_ch / max(groups, 1)


def _out_elems(eqn) -> float:
    return float(sum(getattr(v.aval, "size", 0) for v in eqn.outvars))


def _reduce_flops(eqn) -> float:
    return float(sum(getattr(v.aval, "size", 0) for v in eqn.invars
                     if hasattr(v, "aval")))


_FLOPS_FNS = {
    "dot_general": dot_general_flops,
    "conv_general_dilated": _conv_flops,
    "reduce_sum": _reduce_flops,
    "reduce_max": _reduce_flops,
    "reduce_min": _reduce_flops,
    "reduce_prod": _reduce_flops,
    "reduce_and": _reduce_flops,
    "reduce_or": _reduce_flops,
    "argmax": _reduce_flops,
    "argmin": _reduce_flops,
}

# pure data movement / bookkeeping: zero FLOPs
_FREE = frozenset({
    "reshape", "squeeze", "expand_dims", "broadcast_in_dim", "transpose",
    "convert_element_type", "bitcast_convert_type", "stop_gradient",
    "copy", "device_put", "slice", "dynamic_slice", "dynamic_update_slice",
    "concatenate", "pad", "gather", "scatter", "rev", "iota",
    "split", "select_n",
})


def eqn_flops(eqn) -> float:
    """FLOPs of one equation body (inner jaxprs NOT included)."""
    name = eqn.primitive.name
    fn = _FLOPS_FNS.get(name)
    if fn is not None:
        try:
            return fn(eqn)
        except Exception:
            return _out_elems(eqn)
    if name in _FREE:
        return 0.0
    return _out_elems(eqn)


def eqn_bytes(eqn) -> float:
    """Operand + result bytes of one equation (traffic lower bound)."""
    total = 0.0
    for v in eqn.invars:
        total += _var_bytes(v)
    for v in eqn.outvars:
        total += _var_bytes(v)
    return total


# -- totals with traversal semantics ----------------------------------------

def _total(jaxpr, cost_fn: Callable, while_trips: float = 1.0) -> float:
    raw, _ = unwrap(jaxpr)
    tot = 0.0
    for eqn in raw.eqns:
        subs = list(subjaxprs(eqn))
        if not subs:
            tot += cost_fn(eqn)
            continue
        kind = subs[0].kind
        if kind == "scan":
            tot += subs[0].trips * _total(subs[0].jaxpr, cost_fn,
                                          while_trips)
        elif kind == "cond":
            tot += max(_total(s.jaxpr, cost_fn, while_trips)
                       for s in subs)
        elif kind == "while":
            tot += while_trips * sum(_total(s.jaxpr, cost_fn, while_trips)
                                     for s in subs)
        else:  # transparent call / shard_map / unknown higher-order
            tot += sum(_total(s.jaxpr, cost_fn, while_trips) for s in subs)
    return tot


def total_flops(jaxpr, while_trips: float = 1.0) -> float:
    """All-primitive FLOPs estimate (scan x length, cond max)."""
    return _total(jaxpr, eqn_flops, while_trips)


def matmul_flops(jaxpr, while_trips: float = 1.0) -> float:
    """dot_general-only FLOPs — the pipeline_flops regression metric."""
    return _total(
        jaxpr,
        lambda e: dot_general_flops(e)
        if e.primitive.name == "dot_general" else 0.0,
        while_trips)


def total_bytes(jaxpr, while_trips: float = 1.0) -> float:
    return _total(jaxpr, eqn_bytes, while_trips)


# -- peak live bytes ---------------------------------------------------------

def peak_live_bytes(jaxpr) -> float:
    """Liveness-scan peak working set for one jaxpr (recursive: a call
    equation contributes its body's peak at its program point)."""
    raw, _ = unwrap(jaxpr)
    is_var = lambda a: hasattr(a, "aval") and not hasattr(a, "val")  # noqa: E731
    last_use = {}
    for i, eqn in enumerate(raw.eqns):
        for a in eqn.invars:
            if is_var(a):
                last_use[id(a)] = i
    n = len(raw.eqns)
    for v in raw.outvars:
        if is_var(v):
            last_use[id(v)] = n  # outputs stay live to the end
    sizes = {}
    cur = 0.0
    for v in list(raw.invars) + list(raw.constvars):
        sizes[id(v)] = _var_bytes(v)
        cur += sizes[id(v)]
    peak = cur
    for i, eqn in enumerate(raw.eqns):
        inner = 0.0
        for s in subjaxprs(eqn):
            inner = max(inner, peak_live_bytes(s.jaxpr))
        out_b = 0.0
        for v in eqn.outvars:
            sizes[id(v)] = _var_bytes(v)
            out_b += sizes[id(v)]
        cur += out_b
        peak = max(peak, cur + inner)
        # free everything whose last use is this equation (including
        # outputs never consumed downstream)
        for a in list(eqn.invars) + list(eqn.outvars):
            if is_var(a) and last_use.get(id(a), -1) <= i:
                cur -= sizes.pop(id(a), 0.0)
                last_use.pop(id(a), None)
    return peak


# -- compute/collective overlap model ----------------------------------------

# ring-algorithm wire cost per participating rank, as a multiple of the
# payload: all-reduce moves 2(n-1)/n payloads (reduce-scatter + all-gather
# halves), one-shot scatter/gather/all_to_all moves (n-1)/n, ppermute/
# pbroadcast one full hop. "out" = the payload is the RESULT (all_gather:
# input is the shard, the gathered output is what crosses the wire).
_COLL_RING = {
    "psum": ("in", 2.0),
    "pmax": ("in", 2.0),
    "pmin": ("in", 2.0),
    "all_gather": ("out", 1.0),
    "psum_scatter": ("in", 1.0),
    "reduce_scatter": ("in", 1.0),
    "all_to_all": ("in", 1.0),
    "ppermute": ("in", None),
    "pbroadcast": ("in", None),
}


def collective_wire_bytes(eqn, n: int) -> float:
    """Ring-model bytes one rank moves for a collective over an
    ``n``-rank group, from the traced operand/result dtypes (so an int8
    payload is visibly 4x cheaper than fp32)."""
    side, factor = _COLL_RING[eqn.primitive.name]
    vs = eqn.outvars if side == "out" else eqn.invars
    payload = float(sum(_var_bytes(v) for v in vs))
    if factor is None:
        return payload
    return factor * (n - 1) / max(n, 1) * payload


def _group_size(axes, mesh) -> int:
    n = 1
    for ax in axes:
        n *= int(mesh.shape.get(ax, 1))
    return n


def _atomic_flops(eqn, while_trips: float) -> float:
    """FLOPs of an opaque control-flow node (scan/while/cond) billed as
    one compute block — same traversal semantics as :func:`total_flops`."""
    subs = list(subjaxprs(eqn))
    if not subs:
        return eqn_flops(eqn)
    if subs[0].kind == "cond":
        return max(total_flops(s.jaxpr, while_trips) for s in subs)
    tot = 0.0
    for s in subs:
        trips = s.trips if s.trips else while_trips
        tot += trips * total_flops(s.jaxpr, while_trips)
    return tot


def overlap_plan(jaxpr, mesh, while_trips: float = 1.0,
                 reshard_sites=None) -> dict:
    """Stage-once classification behind :func:`overlap_summary`: walk the
    linearized program, classify every node as compute (FLOPs) or
    collective (ring wire bytes + link class), attach predicted
    implicit-resharding sites, and build the dataflow edges — everything
    that requires the jaxpr, and nothing that requires price constants.

    The returned plan dict is pure data (no jaxpr references beyond
    labels), so a candidate staged once can be re-priced many times via
    :func:`replay_overlap` under different calibrated constants — the
    auto-parallel planner's two-tier search re-scores its staged top-k
    this way instead of re-tracing per pricing change.

    Plan keys: ``nodes`` (per-node dicts: ``coll``, ``link``, ``wire``,
    ``trips``, ``axes``, ``flops``, ``primitive``, ``path``, ``index``),
    ``consumers`` / ``indeg`` (dataflow edges), ``reshard`` (node index
    -> list of site dicts with ``time_s``/``wire_bytes``/``trips``/
    ``link``/``axes``/``kind``).
    """
    from ..distributed.mesh import axis_links
    from .rules import collective_axes
    links = axis_links(mesh) if mesh is not None else {}
    nodes = list(linear_schedule(jaxpr))

    entries = []
    for node in nodes:
        eqn = node.eqn
        axes = ()
        if not node.atomic and mesh is not None \
                and node.primitive in _COLL_RING:
            axes = tuple(ax for ax in collective_axes(eqn)
                         if ax in node.bound_axes and ax in mesh.shape)
        n_g = _group_size(axes, mesh) if axes else 1
        entry = {"primitive": node.primitive,
                 "path": "/".join(node.path) or "<top>",
                 "index": node.index}
        if axes and n_g > 1:
            entry["coll"] = True
            entry["link"] = ("dcn" if any(links.get(ax) == "dcn"
                                          for ax in axes) else "ici")
            entry["wire"] = collective_wire_bytes(eqn, n_g) * node.trips
            entry["trips"] = float(node.trips)
            entry["axes"] = list(axes)
        else:
            entry["coll"] = False
            entry["flops"] = (_atomic_flops(eqn, while_trips) if node.atomic
                              else eqn_flops(eqn)) * node.trips
        entries.append(entry)

    # Attach predicted implicit-resharding sites (analysis/sharding) to
    # the node they fire at: innermost anchor first, falling back to the
    # enclosing atomic control-flow equation's node.
    pending = {}
    if reshard_sites:
        node_pos = {}
        for j, node in enumerate(nodes):
            node_pos.setdefault((node.path, node.index), j)
        for s in reshard_sites:
            anchors = list(getattr(s, "anchors", ()) or ())
            anchors.reverse()
            anchors.append((getattr(s, "path", ()),
                            getattr(s, "eqn_index", -1)))
            for key in anchors:
                j = node_pos.get(tuple(key))
                if j is not None:
                    pending.setdefault(j, []).append({
                        "time_s": float(getattr(s, "time_s", 0.0)),
                        "wire_bytes": float(getattr(s, "wire_bytes", 0.0)),
                        "trips": max(float(getattr(s, "trips", 1.0)), 1.0),
                        "link": getattr(s, "link", "ici"),
                        "axes": list(getattr(s, "axes", ())),
                        "kind": getattr(s, "kind", "")})
                    break

    # Dataflow edges over canonical var ids (linear_schedule already
    # resolved call-boundary aliases).
    producer = {}
    for j, node in enumerate(nodes):
        for o in node.out_ids:
            producer[o] = j
    consumers = [[] for _ in nodes]
    indeg = [0] * len(nodes)
    for j, node in enumerate(nodes):
        deps = {producer[i] for i in node.in_ids
                if i in producer and producer[i] != j}
        indeg[j] = len(deps)
        for d in deps:
            consumers[d].append(j)
    return {"nodes": entries, "consumers": consumers, "indeg": indeg,
            "reshard": pending}


def replay_overlap(plan: dict, peak_flops=None, bandwidths=None,
                   latencies=None, include_timeline: bool = False) -> dict:
    """Run the two-stream list-scheduling simulation over a staged
    :func:`overlap_plan` under a given set of price constants. With all
    constants defaulted this reproduces :func:`overlap_summary` exactly;
    passing ``peak_flops`` / ``bandwidths`` / ``latencies`` (dicts keyed
    by link class) re-prices the SAME staged program under different
    calibrated constants without re-tracing — candidate re-pricing for
    the planner, what-if pricing for tools. Resharding sites are
    re-priced from their wire bytes when ``bandwidths`` overrides their
    link; otherwise their sharding-pass ``time_s`` is used as-is.
    """
    import heapq
    from ..distributed.mesh import link_bandwidth, link_latency
    if peak_flops is None:
        from .. import telemetry as _telemetry
        peak_flops = _telemetry.peak_flops_per_sec()
    peak_flops = max(float(peak_flops), 1.0)

    def _bw(link):
        if bandwidths and link in bandwidths:
            return float(bandwidths[link])
        return link_bandwidth(link)

    def _lat(link):
        if latencies and link in latencies:
            return float(latencies[link])
        return link_latency(link)

    entries = plan["nodes"]
    consumers = plan["consumers"]
    indeg = list(plan["indeg"])
    pending = plan["reshard"]
    node_ready = [0.0] * len(entries)
    heap = [(0.0, j) for j in range(len(entries)) if indeg[j] == 0]
    heapq.heapify(heap)
    wire_free = {}                # link class -> busy-until
    t = 0.0                       # compute-stream cursor
    coll_total = compute_total = 0.0
    n_coll = n_reshard = 0
    reshard_total = 0.0
    timeline = [] if include_timeline else None
    while heap:
        rt, j = heapq.heappop(heap)
        e = entries[j]
        # implicit resharding this node forces: charged on the wire
        # stream, and the node itself waits for the result to land
        for s in pending.get(j, ()):
            r_link = s["link"]
            if bandwidths and r_link in bandwidths:
                r_dur = (s["wire_bytes"] / _bw(r_link)
                         + _lat(r_link)) * s["trips"]
            else:
                r_dur = s["time_s"] * s["trips"]
            r_start = max(rt, wire_free.get(r_link, 0.0))
            r_done = r_start + r_dur
            wire_free[r_link] = r_done
            coll_total += r_dur
            reshard_total += r_dur
            n_coll += 1
            n_reshard += 1
            rt = max(rt, r_done)
            if timeline is not None:
                timeline.append({
                    "kind": "reshard", "primitive": e["primitive"],
                    "path": e["path"], "eqn_index": e["index"],
                    "axes": s["axes"], "link": r_link,
                    "bytes": s["wire_bytes"],
                    "start": r_start, "end": r_done,
                    "reshard_kind": s["kind"]})
        if e["coll"]:
            link = e["link"]
            dur = e["wire"] / _bw(link) + _lat(link) * e["trips"]
            start = max(rt, wire_free.get(link, 0.0))
            done = start + dur
            wire_free[link] = done
            coll_total += dur
            n_coll += 1
            if timeline is not None:
                timeline.append({
                    "kind": "collective", "primitive": e["primitive"],
                    "path": e["path"], "eqn_index": e["index"],
                    "axes": e["axes"], "link": link, "bytes": e["wire"],
                    "start": start, "end": done})
        else:
            dur = e["flops"] / peak_flops
            start = max(t, rt)
            idle = start - t
            done = start + dur
            t = done
            compute_total += dur
            if timeline is not None and (e["flops"] > 0 or idle > 0):
                timeline.append({
                    "kind": "compute", "primitive": e["primitive"],
                    "path": e["path"], "eqn_index": e["index"],
                    "flops": e["flops"], "start": start, "end": done,
                    "stall": idle})
        for c in consumers[j]:
            if done > node_ready[c]:
                node_ready[c] = done
            indeg[c] -= 1
            if indeg[c] == 0:
                heapq.heappush(heap, (node_ready[c], c))
    end = max([t] + list(wire_free.values()))
    stall = max(0.0, end - compute_total)
    shadowed = max(0.0, coll_total - stall)
    eff = (None if coll_total <= 0.0
           else max(0.0, min(1.0, shadowed / coll_total)))
    if timeline is not None:
        timeline.sort(key=lambda e: (e["start"], e["eqn_index"]))
    out = {
        "compute_time": compute_total,
        "collective_time": coll_total,
        "stalled_time": stall,
        "overlap_efficiency": eff,
        "n_collectives": n_coll,
        "n_reshard": n_reshard,
        "reshard_time": reshard_total,
        "makespan": end,
        "peak_flops": peak_flops,
    }
    if timeline is not None:
        out["timeline"] = timeline
    return out


def overlap_summary(jaxpr, mesh, peak_flops=None, while_trips: float = 1.0,
                    include_timeline: bool = False,
                    reshard_sites=None) -> dict:
    """Two-stream schedule simulation of the staged program: a single
    compute stream runs equations at ``peak_flops`` while each collective
    runs asynchronously on its link's wire stream (one in flight per link
    class, ring wire bytes / ``mesh.link_bandwidth``) as soon as its
    operands exist. Scheduling is dependency-driven list scheduling over
    the linearized program (:func:`linear_schedule`): the compute stream
    always picks the earliest-ready equation, so work that does NOT
    depend on an in-flight collective executes under it — the same
    reordering freedom XLA's latency-hiding scheduler has. The stream
    only goes idle (stalls) when every remaining equation is waiting on
    an un-landed collective result, which is exactly what the backward-
    overlapped bucketed exchange removes: a bucket's collective issued
    mid-backward lands under the remaining buckets' backward compute
    instead of serializing after it.

    ``reshard_sites`` — predicted IMPLICIT collectives from the sharding
    pass (analysis/sharding.propagate): each site is charged on its
    link's wire stream right before the equation that forces it, and
    that equation's compute waits for it to land — hidden resharding is
    priced exactly like an explicit collective. Sites inside atomic
    control flow attach to the enclosing scan/while/cond node via their
    anchor chain.

    Returns a dict: ``compute_time``, ``collective_time``,
    ``stalled_time`` (compute idle waiting on collectives, incl. the
    tail wait after the last compute), ``overlap_efficiency`` =
    (collective time - stalls) / collective time clamped to [0, 1]
    (None when the program has no collectives), ``n_collectives``,
    ``n_reshard`` / ``reshard_time`` (the implicit-resharding share),
    ``makespan``; with ``include_timeline`` also ``timeline``: per-node
    start/end entries sorted by start time (zero-cost bookkeeping nodes
    omitted). Estimates rank schedules — they are a model, not a
    profiler.

    Internally a thin wrapper: :func:`overlap_plan` (jaxpr-dependent
    classification, staged once) + :func:`replay_overlap` (pricing +
    scheduling, re-runnable under different constants).
    """
    return replay_overlap(
        overlap_plan(jaxpr, mesh, while_trips=while_trips,
                     reshard_sites=reshard_sites),
        peak_flops=peak_flops, include_timeline=include_timeline)


# -- top-k table -------------------------------------------------------------

def _out_sig(eqn) -> str:
    sigs = []
    for v in eqn.outvars:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            continue
        dt = getattr(aval, "dtype", None)
        sigs.append(f"{getattr(dt, 'name', dt)}"
                    f"[{','.join(str(d) for d in aval.shape)}]")
    return " ".join(sigs)


def top_equations(jaxpr, k: int = 10) -> List[CostRow]:
    """The k most expensive equations by trip-multiplied FLOPs (bytes
    break ties, so huge data movers surface even at 0 FLOPs)."""
    rows = []
    for site in walk(jaxpr):
        if has_inner_cheap(site.eqn):
            continue  # call shells: their cost is their inner equations'
        f = eqn_flops(site.eqn) * site.trips
        b = eqn_bytes(site.eqn) * site.trips
        if f <= 0 and b <= 0:
            continue
        rows.append(CostRow(
            primitive=site.primitive, path="/".join(site.path) or "<top>",
            eqn_index=site.index, flops=f, bytes=b,
            out=_out_sig(site.eqn), trips=site.trips,
            source=source_summary(site.eqn)))
    rows.sort(key=lambda r: (-r.flops, -r.bytes))
    return rows[:k]


def has_inner_cheap(eqn) -> bool:
    for _ in subjaxprs(eqn):
        return True
    return False


def summarize(jaxpr, k: int = 10, while_trips: float = 1.0) -> CostSummary:
    raw, _ = unwrap(jaxpr)
    arg_bytes = float(sum(_var_bytes(v) for v in raw.invars))
    return CostSummary(
        total_flops=total_flops(jaxpr, while_trips),
        matmul_flops=matmul_flops(jaxpr, while_trips),
        total_bytes=total_bytes(jaxpr, while_trips),
        peak_live_bytes=peak_live_bytes(jaxpr),
        arg_bytes=arg_bytes,
        top=top_equations(jaxpr, k))
