"""paddle_tpu.vision.transforms (reference:
python/paddle/vision/transforms/__init__.py — class transforms +
functional ops)."""
from . import functional  # noqa: F401
from .functional import (  # noqa: F401
    adjust_brightness, adjust_contrast, adjust_hue, adjust_saturation,
    center_crop, crop, hflip, normalize, pad, resize, rotate, to_grayscale,
    to_tensor, vflip)
from .transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, ColorJitter, Compose,
    ContrastTransform, Grayscale, HueTransform, Normalize, Pad, RandomCrop,
    RandomHorizontalFlip, RandomResizedCrop, RandomRotation,
    RandomVerticalFlip, Resize, SaturationTransform, ToTensor, Transpose)
