"""Normalization functionals (reference: python/paddle/nn/functional/norm.py;
operators/batch_norm_op.*, layer_norm_op.*, group_norm_op.*, instance_norm_op.*).

XLA fuses these fully on TPU; a Pallas fused layer_norm for the residual+LN
pattern lives in paddle_tpu/ops/pallas/layer_norm.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _unwrap(p):
    return p.value if hasattr(p, "value") else p


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    """Returns (out, new_running_mean, new_running_var) when training else out.

    Note: unlike the reference's in-place stat mutation
    (operators/batch_norm_op.cu), the functional form returns updated stats;
    the BatchNorm layer handles the buffer write-back so that jit-staging sees
    a pure function.
    """
    weight, bias = _unwrap(weight), _unwrap(bias)
    running_mean, running_var = _unwrap(running_mean), _unwrap(running_var)
    channel_axis = x.ndim - 1 if data_format[-1] == "C" else 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    use_batch_stats = training and not use_global_stats
    if use_batch_stats:
        mean = jnp.mean(x, axis=reduce_axes)
        var = jnp.var(x, axis=reduce_axes)
        new_rm = momentum * running_mean + (1.0 - momentum) * mean
        new_rv = momentum * running_var + (1.0 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + epsilon).astype(x.dtype)
    out = (x - jnp.reshape(mean, shape)) * jnp.reshape(inv, shape)
    if weight is not None:
        out = out * jnp.reshape(weight, shape)
    if bias is not None:
        out = out + jnp.reshape(bias, shape)
    if training:
        return out, new_rm, new_rv
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    weight, bias = _unwrap(weight), _unwrap(bias)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    var = jnp.var(x32, axis=axes, keepdims=True)
    out = ((x32 - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    weight, bias = _unwrap(weight), _unwrap(bias)
    channel_axis = x.ndim - 1 if data_format[-1] == "C" else 1
    spatial = tuple(i for i in range(2, x.ndim)) if channel_axis == 1 \
        else tuple(i for i in range(1, x.ndim - 1))
    mean = jnp.mean(x, axis=spatial, keepdims=True)
    var = jnp.var(x, axis=spatial, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    if weight is not None:
        out = out * jnp.reshape(weight, shape)
    if bias is not None:
        out = out + jnp.reshape(bias, shape)
    return out


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    weight, bias = _unwrap(weight), _unwrap(bias)
    channel_last = data_format[-1] == "C"
    if channel_last:
        x_cf = jnp.moveaxis(x, -1, 1)
    else:
        x_cf = x
    n, c = x_cf.shape[0], x_cf.shape[1]
    g = num_groups
    grouped = jnp.reshape(x_cf, (n, g, c // g) + x_cf.shape[2:])
    axes = tuple(range(2, grouped.ndim))
    mean = jnp.mean(grouped, axis=axes, keepdims=True)
    var = jnp.var(grouped, axis=axes, keepdims=True)
    out = jnp.reshape((grouped - mean) * jax.lax.rsqrt(var + epsilon), x_cf.shape)
    shape = [1, c] + [1] * (x_cf.ndim - 2)
    if weight is not None:
        out = out * jnp.reshape(weight, shape)
    if bias is not None:
        out = out + jnp.reshape(bias, shape)
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    if p == 2:
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    else:
        n = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(n, epsilon)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    channel_axis = x.ndim - 1 if data_format[-1] == "C" else 1
    sq = jnp.square(x)
    half = size // 2
    pad_cfg = [(0, 0, 0)] * x.ndim
    pad_cfg[channel_axis] = (half, size - 1 - half, 0)
    padded = jax.lax.pad(sq, jnp.array(0.0, sq.dtype), pad_cfg)
    window = [1] * x.ndim
    window[channel_axis] = size
    acc = jax.lax.reduce_window(padded, 0.0, jax.lax.add, tuple(window),
                                (1,) * x.ndim, [(0, 0)] * x.ndim)
    return x / jnp.power(k + alpha * acc, beta)
