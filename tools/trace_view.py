"""Text summariser for kept traces and flight-recorder dumps.

Renders the JSON artifacts the tracing spine writes — without leaving
the terminal, no Perfetto needed:

- ``runs/<x>/traces_kept.json``   (telemetry.tracing.write_kept)
- ``flight_<reason>_<step>.json`` (telemetry.flight dumps)
- merged multi-host dumps         (telemetry.flight.merge_dumps output)

For every trace: a one-line header (name, outcome, keep reason,
duration) and a waterfall — each span a bar positioned/scaled on the
trace's own timeline, indented by tree depth, with status, thread and
event count. Then one cross-trace table of the slowest span names.

Usage::

    python tools/trace_view.py runs/gpt/traces_kept.json
    python tools/trace_view.py /tmp/run/flight_drain_0.json --top 10
    python tools/trace_view.py --smoke        # self-test, prints JSON
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_BAR_W = 36


def _load_traces(path: str):
    """Normalise any of the three artifact shapes to a list of
    {name, outcome, keep_reason, duration_s, spans} trace dicts."""
    with open(path, encoding="utf-8") as f:
        d = json.load(f)
    if "traces" in d:
        return list(d["traces"]), {"kind": "kept"}
    spans = d.get("spans", [])
    by_trace = defaultdict(list)
    for sp in spans:
        by_trace[sp.get("trace_id", "?")].append(sp)
    traces = []
    for tid, sps in by_trace.items():
        root = min(sps, key=lambda s: s.get("span_id", 1 << 30))
        t0 = min(s["t0_ns"] for s in sps)
        t1 = max(s["t1_ns"] for s in sps)
        traces.append({
            "trace_id": tid, "name": root.get("name", "?"),
            "outcome": root.get("status", "?"), "keep_reason": None,
            "duration_s": (t1 - t0) / 1e9, "spans": sps,
        })
    traces.sort(key=lambda t: min(s["t0_ns"] for s in t["spans"]))
    meta = {"kind": "flight", "reason": d.get("reason"),
            "step": d.get("step")} if "reason" in d else {"kind": "merged"}
    return traces, meta


def _depth(sp, by_id):
    d, pid = 0, sp.get("parent_id")
    while pid is not None and d < 16:
        d += 1
        parent = by_id.get(pid)
        pid = parent.get("parent_id") if parent else None
    return d


def _fmt_ms(ns: float) -> str:
    return f"{ns / 1e6:8.2f}ms"


def render_trace(tr: dict, out) -> None:
    dur = tr.get("duration_s") or 0.0
    head = f"trace {tr.get('name')}  outcome={tr.get('outcome')}"
    if tr.get("keep_reason"):
        head += f"  keep={tr['keep_reason']}"
    head += f"  dur={dur * 1e3:.2f}ms  spans={len(tr.get('spans', []))}"
    tid = tr.get("trace_id")
    if tid:
        head += f"  id={tid}"
    print(head, file=out)
    spans = sorted(tr.get("spans", []), key=lambda s: s["t0_ns"])
    if not spans:
        return
    base = min(s["t0_ns"] for s in spans)
    span_ns = max(max(s["t1_ns"] for s in spans) - base, 1)
    by_id = {s.get("span_id"): s for s in spans}
    for s in spans:
        off = int(_BAR_W * (s["t0_ns"] - base) / span_ns)
        w = max(1, int(_BAR_W * (s["t1_ns"] - s["t0_ns"]) / span_ns))
        w = min(w, _BAR_W - off)
        bar = " " * off + "#" * w + " " * (_BAR_W - off - w)
        label = "  " * _depth(s, by_id) + s.get("name", "?")
        status = s.get("status", "?")
        thread = s.get("thread") or ""
        pi = s.get("process_index")
        if pi is not None:
            thread = f"h{pi}:{thread}"
        nev = len(s.get("events") or [])
        ev = f" ev={nev}" if nev else ""
        print(f"  |{bar}| {_fmt_ms(s['t1_ns'] - s['t0_ns'])} "
              f"{label:<32.32} {status:<10.10} {thread}{ev}", file=out)
        for e in (s.get("events") or [])[:8]:
            name = e.get("name") if isinstance(e, dict) else str(e)
            print(" " * (_BAR_W + 4) + f". {name}", file=out)


def slowest_table(traces, top: int, out) -> None:
    agg = defaultdict(lambda: [0, 0.0, 0.0])   # count, total_ms, max_ms
    for tr in traces:
        for s in tr.get("spans", []):
            ms = (s["t1_ns"] - s["t0_ns"]) / 1e6
            a = agg[s.get("name", "?")]
            a[0] += 1
            a[1] += ms
            a[2] = max(a[2], ms)
    rows = sorted(agg.items(), key=lambda kv: -kv[1][1])[:top]
    if not rows:
        return
    print(f"\nslowest spans (by total time, top {len(rows)}):", file=out)
    print(f"  {'name':<32} {'count':>6} {'total_ms':>10} {'max_ms':>10}",
          file=out)
    for name, (n, tot, mx) in rows:
        print(f"  {name:<32.32} {n:>6} {tot:>10.2f} {mx:>10.2f}", file=out)


def render(path: str, top: int = 15, limit: int = 0, out=None) -> int:
    out = out or sys.stdout
    traces, meta = _load_traces(path)
    if meta.get("kind") == "flight":
        print(f"flight dump reason={meta.get('reason')} "
              f"step={meta.get('step')} traces={len(traces)}", file=out)
    shown = traces[:limit] if limit else traces
    for tr in shown:
        render_trace(tr, out)
    if limit and len(traces) > limit:
        print(f"  ... {len(traces) - limit} more traces "
              f"(raise --limit)", file=out)
    slowest_table(traces, top, out)
    return 0 if traces else 1


def _smoke() -> int:
    """Self-test: synthesize a kept trace, render it, check the output."""
    import io
    import tempfile
    import time
    from paddle_tpu.telemetry import tracing
    tracing.reset(policy=tracing.KeepPolicy(keep_all=True))
    tracing.enable()
    tr = tracing.start_trace("smoke_request", rows=1)
    with tr.span("admission_wait"):
        time.sleep(0.002)
    with tr.span("execute", attempt=0) as sp:
        sp.event("kv_prefix_hit", tokens=4)
        time.sleep(0.005)
    tr.close("completed")
    tracing.disable()
    path = os.path.join(tempfile.mkdtemp(), "traces_kept.json")
    assert tracing.write_kept(path)
    buf = io.StringIO()
    rc = render(path, out=buf)
    text = buf.getvalue()
    checks = {
        "rendered": rc == 0,
        "waterfall_has_spans": "admission_wait" in text
                               and "execute" in text,
        "keep_reason_shown": "keep=" in text,
        "event_listed": "kv_prefix_hit" in text,
        "slowest_table": "slowest spans" in text,
    }
    print(json.dumps({"tool": "trace_view", "checks": checks,
                      "exit_code": 0 if all(checks.values()) else 1}))
    return 0 if all(checks.values()) else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", nargs="?",
                   help="traces_kept.json / flight dump / merged dump")
    p.add_argument("--top", type=int, default=15,
                   help="rows in the slowest-span table")
    p.add_argument("--limit", type=int, default=0,
                   help="waterfalls to print (0 = all)")
    p.add_argument("--smoke", action="store_true",
                   help="self-test on a synthetic trace; prints JSON")
    args = p.parse_args(argv)
    if args.smoke:
        return _smoke()
    if not args.path:
        p.error("path required (or --smoke)")
    return render(args.path, top=args.top, limit=args.limit)


if __name__ == "__main__":
    sys.exit(main())
