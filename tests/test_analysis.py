"""Tests for paddle_tpu.analysis: the canonical jaxpr walker, the cost
model, the rule registry, and the three integration layers
(static.Program, ParallelTrainer.compile, tools/lint_program.py).

The rule tests are seeded-violation fixtures: each constructs the
smallest program that contains EXACTLY ONE instance of its violation and
asserts the rule fires exactly once (and that a clean variant stays
silent), so a rule that over- or under-matches fails loudly here before
it pollutes CI lint reports.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import analysis, nn
from paddle_tpu.analysis import AnalysisConfig, analyze, analyze_jaxpr
from paddle_tpu.analysis import cost as acost
from paddle_tpu.analysis import walker
from paddle_tpu.distributed.mesh import build_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rule_hits(report, rule):
    return [f for f in report.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# walker
# ---------------------------------------------------------------------------

class TestWalker:
    def test_count_eqns_recursive(self):
        def f(x):
            def body(c, _):
                return jnp.sin(c) + 1.0, None
            out, _ = lax.scan(body, x, None, length=3)
            return jax.jit(jnp.tanh)(out)

        cj = jax.make_jaxpr(f)(jnp.zeros(4))
        top = len(cj.jaxpr.eqns)
        total = walker.count_eqns(cj)
        assert total > top  # scan body + jitted tanh counted through

    def test_walk_scan_trips_and_loop_flag(self):
        def f(x):
            def body(c, _):
                return jnp.dot(c, c), None
            out, _ = lax.scan(body, x, None, length=5)
            return out

        cj = jax.make_jaxpr(f)(jnp.zeros((4, 4)))
        dots = [s for s in walker.walk(cj) if s.primitive == "dot_general"]
        assert len(dots) == 1
        assert dots[0].trips == 5.0
        assert dots[0].in_loop

    def test_walk_bound_axes_through_shard_map(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        f = jax.shard_map(lambda v: lax.psum(v, "data"), mesh=mesh,
                          in_specs=P("data"), out_specs=P(),
                          check_vma=False)
        cj = jax.make_jaxpr(f)(jnp.zeros(4))
        psums = [s for s in walker.walk(cj) if s.primitive == "psum"]
        assert len(psums) == 1
        assert "data" in psums[0].bound_axes

    def test_inline_target_knows_remat2(self):
        """jax.checkpoint traces to the 'remat2' primitive on this jax;
        the walker must classify it as transparently inlineable (the old
        hand-rolled ONNX dispatch only knew 'remat'/'checkpoint')."""
        cj = jax.make_jaxpr(jax.checkpoint(jnp.sin))(jnp.zeros(3))
        (eqn,) = cj.jaxpr.eqns
        assert eqn.primitive.name == "remat2"
        assert walker.inline_target(eqn) is not None
        assert walker.has_inner(eqn)

    def test_iter_jaxprs_yields_every_scope(self):
        def f(x):
            return lax.cond(x.sum() > 0, jnp.sin, jnp.cos, x)

        cj = jax.make_jaxpr(f)(jnp.zeros(3))
        paths = [p for p, _ in walker.iter_jaxprs(cj)]
        assert () in paths
        assert sum(1 for p in paths if p and p[-1].startswith("cond[")) >= 2


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

class TestCost:
    def test_dot_flops_exact(self):
        cj = jax.make_jaxpr(jnp.dot)(jnp.zeros((8, 16)), jnp.zeros((16, 4)))
        assert acost.matmul_flops(cj) == 2.0 * 8 * 16 * 4

    def test_scan_multiplies_by_length(self):
        def f(x):
            def body(c, _):
                return jnp.dot(c, c), None
            out, _ = lax.scan(body, x, None, length=7)
            return out

        cj = jax.make_jaxpr(f)(jnp.zeros((4, 4)))
        assert acost.matmul_flops(cj) == 7 * 2.0 * 4 * 4 * 4

    def test_cond_bills_max_branch(self):
        def f(x):
            return lax.cond(x[0, 0] > 0,
                            lambda v: jnp.dot(v, v) + jnp.dot(v, v),
                            lambda v: jnp.dot(v, v), x)

        cj = jax.make_jaxpr(f)(jnp.zeros((4, 4)))
        one_dot = 2.0 * 4 * 4 * 4
        assert acost.matmul_flops(cj) == 2 * one_dot  # max, not sum

    def test_peak_live_bytes_bounds(self):
        def f(x):
            y = x + 1.0
            return (y * 2.0).sum()

        cj = jax.make_jaxpr(f)(jnp.zeros((256,), jnp.float32))
        peak = acost.peak_live_bytes(cj)
        assert peak >= 1024.0       # the input alone
        assert peak <= 4 * 1024.0   # never more than a few temporaries

    def test_top_equations_sorted_and_bounded(self):
        def f(x, w):
            return jnp.dot(jnp.dot(x, w), w)

        cj = jax.make_jaxpr(f)(jnp.zeros((8, 8)), jnp.zeros((8, 8)))
        top = acost.top_equations(cj, k=1)
        assert len(top) == 1
        assert top[0].primitive == "dot_general"

    def test_summarize_report_renders(self):
        rep = analyze(lambda x: jnp.dot(x, x), jnp.zeros((4, 4)))
        text = rep.to_text()
        assert "dot_general" in text
        parsed = json.loads(rep.to_json())
        assert parsed["cost"]["matmul_flops"] == 2.0 * 4 * 4 * 4
        assert parsed["ok"] is True


# ---------------------------------------------------------------------------
# seeded-violation fixtures: each rule fires exactly once
# ---------------------------------------------------------------------------

class TestRules:
    def test_fp64_leak_fires_once(self):
        from jax.experimental import enable_x64
        with enable_x64():
            cj = jax.make_jaxpr(
                lambda x: x.astype(jnp.float64))(jnp.zeros(4, jnp.float32))
        rep = analyze_jaxpr(cj)
        assert len(rule_hits(rep, "fp64-leak")) == 1
        assert not rep.ok

    def test_fp64_silent_on_fp32(self):
        rep = analyze(lambda x: x * 2.0, jnp.zeros(4, jnp.float32))
        assert not rule_hits(rep, "fp64-leak")

    def test_unbound_axis_psum_fires_once(self):
        """psum over an axis no shard_map binds — the vmap-without-
        axis_name / stale-axis-env shape of collective misuse."""
        cj = jax.make_jaxpr(lambda x: lax.psum(x, "rogue"),
                            axis_env=[("rogue", 4)])(jnp.zeros(4))
        rep = analyze_jaxpr(cj)
        hits = rule_hits(rep, "collective-unbound-axis")
        assert len(hits) == 1
        assert "rogue" in hits[0].message
        assert not rep.ok

    def test_bound_axis_psum_clean(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        f = jax.shard_map(lambda v: lax.psum(v, "data"), mesh=mesh,
                          in_specs=P("data"), out_specs=P(),
                          check_vma=False)
        rep = analyze_jaxpr(jax.make_jaxpr(f)(jnp.zeros(4)), mesh=mesh)
        assert not rule_hits(rep, "collective-unbound-axis")
        assert not rule_hits(rep, "collective-axis-not-in-mesh")

    def test_axis_not_in_active_mesh_fires_once(self):
        rogue = Mesh(np.array(jax.devices()[:2]), ("rogue",))
        f = jax.shard_map(lambda v: lax.psum(v, "rogue"), mesh=rogue,
                          in_specs=P("rogue"), out_specs=P(),
                          check_vma=False)
        cj = jax.make_jaxpr(f)(jnp.zeros(4))
        active = build_mesh({"data": 2})
        rep = analyze_jaxpr(cj, mesh=active)
        assert len(rule_hits(rep, "collective-axis-not-in-mesh")) == 1

    def test_ppermute_non_permutation_fires_once(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        f = jax.shard_map(
            lambda v: lax.ppermute(v, "data", perm=[(0, 1), (0, 0)]),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False)
        rep = analyze_jaxpr(jax.make_jaxpr(f)(jnp.zeros(4)), mesh=mesh)
        assert len(rule_hits(rep, "ppermute-non-permutation")) == 1

    def test_ppermute_rotation_clean(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        f = jax.shard_map(
            lambda v: lax.ppermute(v, "data", perm=[(0, 1), (1, 0)]),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False)
        rep = analyze_jaxpr(jax.make_jaxpr(f)(jnp.zeros(4)), mesh=mesh)
        assert not rule_hits(rep, "ppermute-non-permutation")

    def test_host_callback_in_step_fires_once(self):
        def step(x):
            jax.debug.callback(lambda v: None, x)
            return x * 2.0

        rep = analyze(step, jnp.zeros(4))
        hits = rule_hits(rep, "host-callback")
        assert len(hits) == 1
        assert "host round-trip" in hits[0].message

    def test_non_donated_large_arg_fires_once(self):
        big = jax.ShapeDtypeStruct((512, 1024), jnp.float32)  # 2 MiB
        cj = jax.make_jaxpr(jax.jit(lambda x: x + 1.0))(big)
        rep = analyze_jaxpr(cj)
        assert len(rule_hits(rep, "non-donated-large-arg")) == 1

    def test_donated_arg_clean(self):
        big = jax.ShapeDtypeStruct((512, 1024), jnp.float32)
        cj = jax.make_jaxpr(
            jax.jit(lambda x: x + 1.0, donate_argnums=0))(big)
        rep = analyze_jaxpr(cj)
        assert not rule_hits(rep, "non-donated-large-arg")

    def test_explicit_donation_info_overrides(self):
        """When the caller supplies the donation mask (the trainer path),
        it is authoritative — no pjit-param double counting."""
        big = jax.ShapeDtypeStruct((512, 1024), jnp.float32)
        cj = jax.make_jaxpr(jax.jit(lambda x: x + 1.0))(big)
        rep = analyze_jaxpr(cj, donated={0})
        assert not rule_hits(rep, "non-donated-large-arg")

    def test_recompile_scalar_const_fires_once(self):
        c = jnp.asarray(2.5)  # 0-d closed-over const -> retrace hazard
        rep = analyze(lambda x: x * c, jnp.zeros(4))
        assert len(rule_hits(rep, "recompile-scalar-const")) == 1

    def test_dead_equation_fires_once(self):
        def f(x):
            _unused = jnp.sin(x)
            return x * 2.0

        rep = analyze(f, jnp.zeros(4))
        hits = rule_hits(rep, "dead-equation")
        assert len(hits) == 1
        assert hits[0].primitive == "sin"

    def test_oversized_allgather_fires_once(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        f = jax.shard_map(lambda v: lax.all_gather(v, "data"), mesh=mesh,
                          in_specs=P("data"), out_specs=P(),
                          check_vma=False)
        cj = jax.make_jaxpr(f)(jnp.zeros((8, 4)))
        cfg = AnalysisConfig(allgather_warn_bytes=64.0)
        rep = analyze_jaxpr(cj, mesh=mesh, config=cfg)
        assert len(rule_hits(rep, "oversized-allgather")) == 1
        # default 64 MiB threshold: same program is clean
        assert not rule_hits(analyze_jaxpr(cj, mesh=mesh),
                             "oversized-allgather")

    def test_amp_fp32_leak_fires_once(self):
        def f(x, w):
            return jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))

        rep = analyze(f, jnp.zeros((4, 8), jnp.bfloat16),
                      jnp.zeros((8, 4), jnp.bfloat16))
        assert len(rule_hits(rep, "amp-fp32-leak")) == 1
        # a matmul kept in bf16 is what AMP wants: silent
        clean = analyze(lambda x, w: jnp.dot(x, w),
                        jnp.zeros((4, 8), jnp.bfloat16),
                        jnp.zeros((8, 4), jnp.bfloat16))
        assert not rule_hits(clean, "amp-fp32-leak")

    def test_int4_overflow_fires_on_narrow_accumulator(self):
        """An int16 sum over 8192 int4-range values can reach 8192*7 >
        int16 max — the hand-rolled-exchange overflow shape."""
        x = jnp.zeros((8192, 4), jnp.int16)
        cj = jax.make_jaxpr(
            lambda v: jnp.sum(v, axis=0, dtype=jnp.int16))(x)
        rep = analyze_jaxpr(cj)
        assert len(rule_hits(rep, "int4-grad-sync-overflow")) == 1
        assert not rep.ok   # severity "error" gates CI

    def test_int4_overflow_silent_when_safe_or_widened(self):
        # 64 * 7 fits int16: silent
        small = jax.make_jaxpr(
            lambda v: jnp.sum(v, axis=0, dtype=jnp.int16))(
                jnp.zeros((64, 4), jnp.int16))
        assert not rule_hits(analyze_jaxpr(small),
                             "int4-grad-sync-overflow")
        # the int4_accum_dtype fix — widen to int32 — is also silent
        wide = jax.make_jaxpr(
            lambda v: jnp.sum(v, axis=0, dtype=jnp.int32))(
                jnp.zeros((8192, 4), jnp.int16))
        assert not rule_hits(analyze_jaxpr(wide),
                             "int4-grad-sync-overflow")

    def _linked_mesh(self, links):
        from paddle_tpu.distributed import mesh as mesh_mod
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("data", "model"))
        mesh_mod.set_axis_links(links, mesh=mesh)
        return mesh, mesh_mod

    def test_link_mismatch_fires_on_int8_over_ici_only(self):
        mesh, mesh_mod = self._linked_mesh({"data": "dcn",
                                            "model": "ici"})
        try:
            f = jax.shard_map(
                lambda v: lax.all_to_all(v.astype(jnp.int8), "model",
                                         split_axis=0, concat_axis=0,
                                         tiled=False),
                mesh=mesh, in_specs=P(("data", "model"), None),
                out_specs=P(("data", "model"), None), check_vma=False)
            cj = jax.make_jaxpr(f)(jnp.zeros((8, 8), jnp.float32))
            rep = analyze_jaxpr(cj, mesh=mesh)
            hits = rule_hits(rep, "compressed-collective-link-mismatch")
            assert len(hits) == 1
            assert "ICI-only" in hits[0].message
        finally:
            mesh_mod._state.links.clear()

    def test_link_mismatch_fires_on_large_fp32_over_dcn(self):
        mesh, mesh_mod = self._linked_mesh({"data": "dcn"})
        try:
            f = jax.shard_map(
                lambda v: lax.psum(v, "data"), mesh=mesh,
                in_specs=P(("data", "model"), None),
                out_specs=P(("data", "model"), None), check_vma=False)
            cj = jax.make_jaxpr(f)(jnp.zeros((4, 64), jnp.float32))
            cfg = AnalysisConfig(dcn_uncompressed_min_bytes=64.0)
            rep = analyze_jaxpr(cj, mesh=mesh, config=cfg)
            hits = rule_hits(rep, "compressed-collective-link-mismatch")
            assert len(hits) == 1
            assert "DCN" in hits[0].message
            # default 1 MiB threshold: this tiny psum is clean
            assert not rule_hits(analyze_jaxpr(cj, mesh=mesh),
                                 "compressed-collective-link-mismatch")
        finally:
            mesh_mod._state.links.clear()

    def test_link_mismatch_silent_without_links(self):
        """Single-slice CPU meshes (no explicit map, inference says all
        ICI) must not spam: the gating question does not arise."""
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        f = jax.shard_map(
            lambda v: lax.psum(v.astype(jnp.int8), "data"), mesh=mesh,
            in_specs=P("data"), out_specs=P(), check_vma=False)
        cj = jax.make_jaxpr(f)(jnp.zeros(256, jnp.float32))
        rep = analyze_jaxpr(cj, mesh=mesh)
        assert not rule_hits(rep, "compressed-collective-link-mismatch")

    def test_link_mismatch_silent_on_int8_over_dcn(self):
        """Compressed traffic on the DCN axis is the WANTED deployment —
        must stay silent."""
        mesh, mesh_mod = self._linked_mesh({"data": "dcn"})
        try:
            f = jax.shard_map(
                lambda v: lax.psum(v.astype(jnp.int8)
                                   .astype(jnp.int8), "data"),
                mesh=mesh, in_specs=P(("data", "model"), None),
                out_specs=P(None, None), check_vma=False)
            cj = jax.make_jaxpr(f)(jnp.zeros((4, 8), jnp.float32))
            rep = analyze_jaxpr(cj, mesh=mesh)
            assert not rule_hits(rep,
                                 "compressed-collective-link-mismatch")
        finally:
            mesh_mod._state.links.clear()

    def test_register_rule_plugs_in_and_rejects_dupes(self):
        from paddle_tpu.analysis import rules as arules
        rid = "test-always-fires"

        @arules.register_rule(rid, "info")
        def _always(ctx):
            yield ctx.finding(None, "hello from plugin")

        try:
            rep = analyze(lambda x: x + 1.0, jnp.zeros(2))
            assert len(rule_hits(rep, rid)) == 1
            with pytest.raises(ValueError):
                arules.register_rule(rid, "info")(lambda ctx: iter(()))
            with pytest.raises(ValueError):
                arules.register_rule("x", "fatal")(lambda ctx: iter(()))
        finally:
            del arules.RULES[rid]


# ---------------------------------------------------------------------------
# integration: Program / ParallelTrainer / lint CLI
# ---------------------------------------------------------------------------

class TestProgramIntegration:
    def _program(self):
        from paddle_tpu import static

        def net(x):
            def body(c, _):
                return jnp.tanh(c), None
            out, _ = lax.scan(body, x, None, length=4)
            return jax.jit(lambda v: v * 2.0)(out)

        return static.Program.trace(
            net, static.data("x", [None, 8], "float32"), static_batch=2)

    def test_num_ops_counts_recursively(self):
        prog = self._program()
        assert prog.num_ops() > len(prog._jaxpr.jaxpr.eqns)

    def test_program_analyze_and_repr(self):
        prog = self._program()
        rep = prog.analyze()
        assert rep.num_eqns == prog.num_ops()
        r = repr(prog)
        assert "ops" in r and "errors" in r

    def test_empty_program(self):
        from paddle_tpu import static
        prog = static.Program()
        assert prog.num_ops() == 0
        assert prog.analyze().num_eqns == 0
        assert repr(prog) == "<Program: empty>"


class TestTrainerCompile:
    def _trainer(self):
        paddle.seed(0)
        build_mesh({"data": 1})
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 4))
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        from paddle_tpu.distributed.engine import ParallelTrainer
        return ParallelTrainer(
            net, opt, lambda out, y: jnp.mean((out - y) ** 2))

    def test_compile_returns_step_without_running(self):
        tr = self._trainer()
        x = jax.ShapeDtypeStruct((8, 16), jnp.float32)
        y = jax.ShapeDtypeStruct((8, 4), jnp.float32)
        step = tr.compile(x, y)
        assert callable(step)
        assert len(tr._step_cache) == 1

    def test_compile_analyze_reports_clean_step(self):
        tr = self._trainer()
        x = np.zeros((8, 16), np.float32)
        y = np.zeros((8, 4), np.float32)
        step, rep = tr.compile(x, y, analyze=True)
        assert callable(step)
        assert rep.ok, rep.to_text()      # shipped step: zero errors
        assert rep.cost.matmul_flops > 0  # fwd+bwd matmuls priced
        # params/opt donated by the step's donate_argnums: no warning
        assert not rule_hits(rep, "non-donated-large-arg")

    def test_compile_shares_step_cache_with_train(self):
        tr = self._trainer()
        x = np.zeros((8, 16), np.float32)
        y = np.zeros((8, 4), np.float32)
        step = tr.compile(x, y)
        tr.train_step(x, y)
        assert len(tr._step_cache) == 1  # train reused the staged step
        assert tr._step_cache[next(iter(tr._step_cache))] is step


@pytest.mark.parametrize("model", ["gpt"])
def test_lint_program_smoke(model):
    """The tier-1 CI wrapper: lint_program --smoke on the bench model
    family must exit 0 (no error findings) and emit a JSON report with
    a populated cost table."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_program.py"),
         "--smoke", "--json", "--model", model],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout.strip().splitlines()[-1])[model]
    assert rep["ok"] is True
    assert rep["counts"]["error"] == 0
    assert 1 <= len(rep["cost"]["top"]) <= 10
    assert rep["cost"]["matmul_flops"] > 0
    assert rep["num_eqns"] > 100  # recursed through the jitted step
