"""ONNX export: real wire-format emission for Sequential models, StableHLO
fallback otherwise (reference: python/paddle/onnx/export.py -> paddle2onnx).

The emitted bytes are validated with the dependency-free protobuf decoder in
paddle_tpu.onnx._pb (the `onnx` package is not in this image); when `onnx`
IS importable the checker test runs too.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.onnx import _pb
from paddle_tpu.static import InputSpec


def _decode_model(path):
    with open(path, "rb") as f:
        buf = f.read()
    model = _pb.decode(buf)
    assert model[1][0] == 8  # ir_version
    graph = _pb.decode(model[7][0])
    nodes = [_pb.decode(n) for n in graph.get(1, [])]
    inits = [_pb.decode(t) for t in graph.get(5, [])]
    return model, graph, nodes, inits


def _op_types(nodes):
    return [n[4][0].decode() for n in nodes]


def test_onnx_export_sequential_mlp(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2),
                      nn.Softmax())
    m.eval()
    out = paddle.onnx.export(m, str(tmp_path / "mlp.onnx"),
                             input_spec=[InputSpec([None, 4], "float32")])
    assert out.endswith(".onnx") and os.path.exists(out)
    _, graph, nodes, inits = _decode_model(out)
    ops = _op_types(nodes)
    assert ops == ["Gemm", "Relu", "Gemm", "Softmax", "Identity"]
    # initializers: 2 weights + 2 biases, with correct dims
    dims = sorted(tuple(t.get(1, [])) for t in inits)
    assert ((4, 8) in dims) and ((8, 2) in dims)
    # weight payload round-trips bit-exact
    w0 = np.asarray(m[0].weight.value, dtype=np.float32)
    blobs = [np.frombuffer(t[9][0], dtype=np.float32) for t in inits]
    assert any(b.size == w0.size and
               np.array_equal(b.reshape(w0.shape), w0) for b in blobs)


def test_onnx_export_lenet(tmp_path):
    from paddle_tpu.vision.models import LeNet
    m = LeNet()
    m.eval()
    out = paddle.onnx.export(m, str(tmp_path / "lenet.onnx"),
                             input_spec=[InputSpec([None, 1, 28, 28],
                                                   "float32")])
    assert out.endswith(".onnx")
    _, graph, nodes, _ = _decode_model(out)
    ops = _op_types(nodes)
    assert ops.count("Conv") == 2 and ops.count("MaxPool") == 2
    assert ops.count("Gemm") == 3 and "Flatten" in ops
    # graph input/output value_info present
    vi_in = _pb.decode(graph[11][0])
    assert vi_in[1][0] == b"input"


def test_onnx_export_residual_via_trace(tmp_path):
    """Skip connections defeat the layer walker; the trace-based
    converter (jaxpr -> ONNX) handles them (round-4 verdict item 7)."""
    class Residual(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            return x + self.fc(x)

    m = Residual()
    m.eval()
    out = paddle.onnx.export(m, str(tmp_path / "res.onnx"),
                             input_spec=[InputSpec([2, 4], "float32")])
    assert out.endswith(".onnx")
    _, graph, nodes, inits = _decode_model(out)
    ops = _op_types(nodes)
    assert "MatMul" in ops and "Add" in ops
    # the weight made it into the initializers bit-exactly
    w = np.asarray(m.fc.weight.value, np.float32)
    blobs = [np.frombuffer(t[9][0], dtype=np.float32) for t in inits
             if t.get(9)]
    assert any(b.size == w.size and np.array_equal(b.reshape(w.shape), w)
               for b in blobs)


def test_onnx_export_checkpointed_layer_via_trace(tmp_path):
    """A jax.checkpoint'd forward traces to the 'remat2' primitive on
    this jax; the converter must inline it like any call (it used to
    know only the 'remat'/'checkpoint' spellings and died with a
    misleading 'no ONNX mapping' error)."""
    import jax

    class Remat(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            return jax.checkpoint(lambda v: self.fc(v) + v)(x)

    m = Remat()
    m.eval()
    out = paddle.onnx.export(m, str(tmp_path / "remat.onnx"),
                             input_spec=[InputSpec([2, 4], "float32")])
    assert out.endswith(".onnx")
    _, _, nodes, _ = _decode_model(out)
    ops = _op_types(nodes)
    assert "MatMul" in ops and "Add" in ops


def _io_elem_types(graph):
    """[(name, elem_type, dims)] for graph inputs (field 11) / outputs
    (12); dims entries are ints or the dim_param string."""
    out = {}
    for field in (11, 12):
        infos = []
        for vi in graph.get(field, []):
            d = _pb.decode(vi)
            name = d[1][0].decode()
            ttype = _pb.decode(_pb.decode(d[2][0])[1][0])
            elem = ttype[1][0]
            dims = []
            for dim in _pb.decode(ttype[2][0]).get(1, []):
                dd = _pb.decode(dim)
                dims.append(dd[1][0] if 1 in dd else dd[2][0].decode())
            infos.append((name, elem, dims))
        out[field] = infos
    return out[11], out[12]


@pytest.mark.slow
def test_onnx_export_resnet50_via_trace(tmp_path):
    """ResNet-50 (the model someone would actually export) round-trips
    through the trace converter with all weights as initializers — with
    a DYNAMIC batch dim (dim_param) and exact dtypes."""
    from paddle_tpu.vision.models import resnet50
    paddle.seed(0)
    m = resnet50()
    m.eval()
    out = paddle.onnx.export(m, str(tmp_path / "r50.onnx"),
                             input_spec=[InputSpec([None, 3, 64, 64],
                                                   "float32")])
    assert out.endswith(".onnx")
    _, graph, nodes, inits = _decode_model(out)
    ops = _op_types(nodes)
    assert ops.count("Conv") == 53      # 53 convs in resnet50
    assert "MaxPool" in ops and "MatMul" in ops
    assert os.path.getsize(out) > 90e6  # ~25.6M params as f32
    ins, outs = _io_elem_types(graph)
    assert ins[0][1] == 1                    # FLOAT input
    assert isinstance(ins[0][2][0], str)     # dynamic batch dim_param
    assert ins[0][2][1:] == [3, 64, 64]
    assert isinstance(outs[0][2][0], str)    # output batch dynamic too
    # the flatten Reshape is batch-polymorphic: leading target dim is 0
    shape_inits = [np.frombuffer(t[9][0], np.int64) for t in inits
                   if t[2][0] == 7 and len(t.get(1, [])) == 1]
    assert any(s.size >= 2 and s[0] == 0 for s in shape_inits)


def test_onnx_mixed_static_dynamic_specs(tmp_path):
    """Only inputs whose spec declared a dynamic leading dim become
    dim_param — a second input with a STATIC leading size (even one that
    collides with the trace sentinel) keeps its literal shape."""
    import jax.numpy as jnp

    class TwoIn(nn.Layer):
        def forward(self, x, y):
            return x + jnp.sum(y, axis=0)

    m = TwoIn()
    m.eval()
    out = paddle.onnx.export(
        m, str(tmp_path / "two.onnx"),
        input_spec=[InputSpec([None, 4], "float32"),
                    InputSpec([13, 4], "float32")])  # 13 == sentinel
    assert out.endswith(".onnx")
    _, graph, _, _ = _decode_model(out)
    ins, outs = _io_elem_types(graph)
    assert isinstance(ins[0][2][0], str)   # dynamic batch
    assert ins[1][2] == [13, 4]            # static spec stays literal
    assert isinstance(outs[0][2][0], str)


def test_onnx_export_dtype_fidelity(tmp_path):
    """Exact-dtype policy (round-4 ADVICE: int32 inputs were silently
    widened to int64): int32 graph inputs stay INT32=6, and int32
    initializers are not widened."""
    class Emb(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(16, 8)
            self.fc = nn.Linear(8, 4)

        def forward(self, ids):
            return self.fc(self.emb(ids))

    paddle.seed(0)
    m = Emb()
    m.eval()
    out = paddle.onnx.export(m, str(tmp_path / "emb.onnx"),
                             input_spec=[InputSpec([2, 4], "int32")])
    assert out.endswith(".onnx")
    _, graph, nodes, inits = _decode_model(out)
    ins, outs = _io_elem_types(graph)
    assert ins[0][1] == 6        # INT32 preserved, not widened to 7
    assert outs[0][1] == 1       # FLOAT out
    # bf16 params export as FLOAT (documented policy), not a new dtype
    m2 = nn.Sequential(nn.Linear(4, 2))
    m2.bfloat16()
    m2.eval()
    out2 = paddle.onnx.export(m2, str(tmp_path / "bf.onnx"),
                              input_spec=[InputSpec([1, 4], "float32")])
    _, g2, _, inits2 = _decode_model(out2)
    assert all(t[2][0] in (1, 7) for t in inits2)  # FLOAT/INT64 only


def test_onnx_export_gpt_block_via_trace(tmp_path):
    """A GPT trunk (embedding + attention block + LN) exports: the
    causal mask/iota subgraphs constant-fold into initializers."""
    from paddle_tpu.text.models import GPTModel
    paddle.seed(0)
    m = GPTModel(tensor_parallel=False, vocab_size=128, hidden_size=32,
                 num_layers=1, num_heads=2, max_position_embeddings=16,
                 attn_dropout=0.0, hidden_dropout=0.0)
    m.eval()
    out = paddle.onnx.export(m, str(tmp_path / "gpt.onnx"),
                             input_spec=[InputSpec([1, 16], "int32")])
    assert out.endswith(".onnx")
    _, graph, nodes, _ = _decode_model(out)
    ops = _op_types(nodes)
    assert "MatMul" in ops and "Gather" in ops and "Where" in ops
    assert "Tanh" in ops  # gelu tanh form inside the block


def test_onnx_export_fallback_warns(tmp_path):
    class Sorty(nn.Layer):
        def forward(self, x):
            import jax.numpy as jnp
            return jnp.sort(x, axis=-1)  # 'sort' has no ONNX mapping

    m = Sorty()
    with pytest.warns(UserWarning, match="ONNX conversion not available"):
        prefix = paddle.onnx.export(
            m, str(tmp_path / "srt.onnx"),
            input_spec=[InputSpec([2, 4], "float32")])
    assert not prefix.endswith(".onnx")
    assert os.path.exists(prefix + ".stablehlo")


def test_onnx_checker_if_available(tmp_path):
    onnx = pytest.importorskip("onnx")
    m = nn.Sequential(nn.Linear(4, 2))
    out = paddle.onnx.export(m, str(tmp_path / "chk.onnx"),
                             input_spec=[InputSpec([1, 4], "float32")])
    model = onnx.load(out)
    onnx.checker.check_model(model)
