"""Tests for static.nn layer functions and static scope/serialization APIs.

Reference surfaces: python/paddle/static/nn/__init__.py (40 names),
fluid/executor.py global_scope/scope_guard, fluid/io.py program state
save/load, details/build_strategy.h shims.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import jax.numpy as jnp

st = paddle.static


def test_scope_guard_and_global_scope():
    s = st.global_scope()
    sub = st.Scope()
    with st.scope_guard(sub):
        assert st.global_scope() is sub
        st.global_scope().var("a", jnp.ones(3))
        assert st.global_scope().find_var("a") is not None
    assert st.global_scope() is s
    # parent chain
    child = sub.new_scope()
    assert child.find_var("a") is not None


def test_fc_param_reuse_and_activation():
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype("float32"))
    a = st.nn.fc(x, 16, name="fc_reuse")
    b = st.nn.fc(x, 16, name="fc_reuse")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    r = st.nn.fc(x, 16, name="fc_relu", activation="relu")
    assert float(np.asarray(r).min()) >= 0.0


def test_embedding_padding_idx():
    ids = np.array([[0, 1], [2, 0]])
    emb = st.nn.embedding(ids, (5, 4), padding_idx=0, name="emb_pad")
    out = np.asarray(emb)
    np.testing.assert_allclose(out[0, 0], np.zeros(4))
    np.testing.assert_allclose(out[1, 1], np.zeros(4))
    assert np.abs(out[0, 1]).sum() > 0


def test_norms_and_prelu():
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4, 8, 8)
                         .astype("float32"))
    bn = st.nn.batch_norm(x, name="bn_t")
    assert bn.shape == x.shape
    gn = st.nn.group_norm(x, groups=2, name="gn_t")
    assert gn.shape == x.shape
    ln = st.nn.layer_norm(x, begin_norm_axis=1, name="ln_t")
    assert ln.shape == x.shape
    inorm = st.nn.instance_norm(x, name="in_t")
    assert inorm.shape == x.shape
    pr = st.nn.prelu(x, mode="channel", name="pr_t")
    assert pr.shape == x.shape
    w = np.random.RandomState(1).randn(6, 3, 3, 3).astype("float32")
    sn = st.nn.spectral_norm(w, power_iters=3)
    assert sn.shape == w.shape
    # spectral norm of the normalized matrix ~ 1
    mat = np.asarray(sn).reshape(6, -1)
    assert abs(np.linalg.svd(mat, compute_uv=False)[0] - 1.0) < 0.2


def test_convs():
    x = paddle.to_tensor(np.random.RandomState(0).randn(1, 3, 8, 8)
                         .astype("float32"))
    c = st.nn.conv2d(x, 6, 3, padding=1, name="c2")
    assert c.shape == (1, 6, 8, 8)
    ct = st.nn.conv2d_transpose(x, 6, filter_size=2, stride=2, name="c2t")
    assert ct.shape == (1, 6, 16, 16)
    x3 = paddle.to_tensor(np.random.RandomState(0).randn(1, 2, 4, 4, 4)
                          .astype("float32"))
    c3 = st.nn.conv3d(x3, 4, 3, padding=1, name="c3")
    assert c3.shape == (1, 4, 4, 4, 4)


def test_control_flow():
    out = st.nn.while_loop(lambda i, s: i < 4, lambda i, s: (i + 1, s + i),
                           (jnp.asarray(0), jnp.asarray(0)))
    assert int(out[1]) == 0 + 1 + 2 + 3
    t = st.nn.cond(jnp.asarray(True), lambda: jnp.ones(2), lambda: jnp.zeros(2))
    np.testing.assert_allclose(np.asarray(t), [1, 1])
    sw = st.nn.switch_case(2, [lambda: jnp.asarray(0), lambda: jnp.asarray(1),
                               lambda: jnp.asarray(2)])
    assert int(sw) == 2
    cs = st.nn.case([(jnp.asarray(False), lambda: jnp.asarray(1)),
                     (jnp.asarray(True), lambda: jnp.asarray(2))],
                    default=lambda: jnp.asarray(3))
    assert int(cs) == 2


def test_py_func():
    def host_fn(a):
        return np.asarray(a) * 2

    x = jnp.ones((3,), jnp.float32)
    out = st.nn.py_func(host_fn, x)
    np.testing.assert_allclose(np.asarray(out), [2, 2, 2])


def test_crf_decoding_prefers_high_scores():
    # 3 tags; emissions strongly prefer tag sequence [0,1,2]
    emis = np.full((1, 3, 3), -5.0, dtype="float32")
    emis[0, 0, 0] = emis[0, 1, 1] = emis[0, 2, 2] = 5.0
    trans = np.zeros((5, 3), dtype="float32")
    path = np.asarray(st.nn.crf_decoding(emis, trans))
    np.testing.assert_array_equal(path[0], [0, 1, 2])


def test_nce_positive_loss():
    x = np.random.RandomState(0).randn(4, 8).astype("float32")
    y = np.array([1, 2, 3, 4])
    loss = st.nn.nce(paddle.to_tensor(x), y, num_total_classes=10,
                     name="nce_t")
    assert loss.shape == (4, 1)
    assert np.all(np.asarray(loss) > 0)


def test_sequence_ops():
    x = paddle.to_tensor(np.arange(24, dtype="float32").reshape(2, 4, 3))
    length = np.array([2, 4])
    pooled = st.nn.sequence_pool(x, "average", length=length)
    np.testing.assert_allclose(np.asarray(pooled)[0],
                               np.arange(24).reshape(2, 4, 3)[0, :2].mean(0))
    last = st.nn.sequence_last_step(x, length=length)
    np.testing.assert_allclose(np.asarray(last)[0],
                               np.arange(24).reshape(2, 4, 3)[0, 1])
    rev = st.nn.sequence_reverse(x, length=length)
    np.testing.assert_allclose(np.asarray(rev)[0, 0],
                               np.arange(24).reshape(2, 4, 3)[0, 1])
    np.testing.assert_allclose(np.asarray(rev)[0, 2],
                               np.arange(24).reshape(2, 4, 3)[0, 2])
    sm = st.nn.sequence_softmax(x, length=length)
    s = np.asarray(sm)
    assert abs(s[0, :, 0].sum() - 1.0) < 1e-5  # masked softmax over 2 steps
    assert s[0, 2:].sum() == 0.0
    padded, lens = st.nn.sequence_pad(
        [np.ones((2, 3)), np.ones((4, 3))], 0.0)
    assert padded.shape == (2, 4, 3)
    np.testing.assert_array_equal(np.asarray(lens), [2, 4])
    unp = st.nn.sequence_unpad(padded, lens)
    assert unp[0].shape == (2, 3) and unp[1].shape == (4, 3)
    eng = st.nn.sequence_enumerate(np.array([[1, 2, 3]]), 2, pad_value=0)
    np.testing.assert_array_equal(np.asarray(eng)[0],
                                  [[1, 2], [2, 3], [3, 0]])
    sc = st.nn.sequence_conv(x, 5, filter_size=3, name="sconv")
    assert sc.shape == (2, 4, 5)


def test_row_conv():
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 5, 3)
                         .astype("float32"))
    out = st.nn.row_conv(x, 2, name="rc_t")
    assert out.shape == (2, 5, 3)


def test_multi_box_head():
    feats = [paddle.to_tensor(np.random.RandomState(i).randn(1, 4, s, s)
                              .astype("float32")) for i, s in enumerate([8, 4])]
    img = paddle.to_tensor(np.zeros((1, 3, 64, 64), dtype="float32"))
    locs, confs, boxes, vars_ = st.nn.multi_box_head(
        feats, img, base_size=64, num_classes=3, aspect_ratios=[[2.0], [2.0]],
        name="mbh")
    assert locs.shape[0] == 1 and locs.shape[2] == 4
    assert confs.shape[2] == 3
    assert boxes.shape[0] == locs.shape[1]
    assert vars_.shape == boxes.shape


def test_program_state_roundtrip(tmp_path):
    scope = st.Scope()
    with st.scope_guard(scope):
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4)
                             .astype("float32"))
        out = st.nn.fc(x, 8, name="fc_rt")
        prog = st.default_main_program()
        path = str(tmp_path / "model")
        st.save(prog, path)
        state = st.load_program_state(path)
        assert "fc_rt.w_0" in state
        # mutate then restore
        scope.var("fc_rt.w_0", jnp.zeros_like(scope.find_var("fc_rt.w_0")))
        st.load(prog, path)
        out2 = st.nn.fc(x, 8, name="fc_rt")
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2))


def test_serialize_bytes_roundtrip(tmp_path):
    data = st.serialize_persistables()
    st.deserialize_persistables(st.default_main_program(), data)
    pb = st.serialize_program()
    prog = st.deserialize_program(pb)
    assert isinstance(prog, st.Program)
    p = str(tmp_path / "blob.bin")
    st.save_to_file(p, b"abc")
    assert st.load_from_file(p) == b"abc"


def test_strategies_and_places():
    bs = st.BuildStrategy()
    bs.fuse_all_reduce_ops = False
    es = st.ExecutionStrategy()
    es.num_threads = 4
    assert st.cuda_places()
    assert st.xpu_places()
    with st.device_guard("cpu"):
        pass
    st.WeightNormParamAttr(dim=0)
    acc = st.accuracy(jnp.asarray(np.eye(4, 5, dtype="float32")),
                      jnp.asarray(np.arange(4)))
    assert float(acc) == 1.0


def test_create_global_var_and_parameter():
    scope = st.Scope()
    with st.scope_guard(scope):
        v = st.create_global_var([2, 3], 1.5, "float32", name="gv")
        assert scope.find_var("gv").shape == (2, 3)
        st.create_parameter([4], "float32", name="pp")
        assert scope.find_var("pp") is not None
        assert repr(v).startswith("Variable")


def test_program_trace_creates_concrete_params():
    # regression: _param under Program.trace must not leak tracers into the
    # scope or the global RNG (ensure_compile_time_eval path)
    scope = st.Scope()
    with st.scope_guard(scope):
        def net(x):
            return st.nn.fc(x, 4, name="traced_fc")

        prog = st.Program.trace(net, st.data("x", [2, 3]))
        out = st.Executor().run(prog,
                                feed={"x": np.ones((2, 3), "float32")})
        assert out[0].shape == (2, 4)
        w = scope.find_var("traced_fc.w_0")
        assert hasattr(w, "dtype") and not hasattr(w, "aval") or \
            not str(type(w)).count("Tracer")
    # global RNG still usable
    paddle.rand([2])


def test_case_no_default_uses_last_fn():
    r = st.nn.case([(jnp.asarray(False), lambda: jnp.asarray(1)),
                    (jnp.asarray(False), lambda: jnp.asarray(2))])
    assert int(r) == 2


def test_nce_fresh_negatives_eager():
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype("float32"))
    y = np.arange(1, 5)
    l1 = np.asarray(st.nn.nce(x, y, 50, name="nce_fresh"))
    l2 = np.asarray(st.nn.nce(x, y, 50, name="nce_fresh"))
    # same weights, different sampled negatives -> different loss values
    assert not np.allclose(l1, l2)


def test_executor_uses_loaded_state(tmp_path):
    # regression: scope params must be jit INPUTS, so load/set_program_state
    # changes the executed weights without retracing
    scope = st.Scope()
    with st.scope_guard(scope):
        def net(x):
            return st.nn.fc(x, 4, name="exec_fc", bias_attr=False)

        prog = st.Program.trace(net, st.data("x", [2, 3]))
        exe = st.Executor()
        feed = {"x": np.ones((2, 3), "float32")}
        out1 = exe.run(prog, feed=feed)[0]
        scope.var("exec_fc.w_0", jnp.zeros((3, 4), jnp.float32))
        out2 = exe.run(prog, feed=feed)[0]
        np.testing.assert_allclose(out2, np.zeros((2, 4)))
        assert not np.allclose(out1, out2)


def test_unnamed_layers_stable_across_retrace(tmp_path):
    # regression: auto-named params must be identical on every retrace
    scope = st.Scope()
    with st.scope_guard(scope):
        def net(x):
            return st.nn.fc(x, 4)  # no explicit name

        prog = st.Program.trace(net, st.data("x", [2, 3]))
        names_after_trace = set(scope.local_var_names())
        exe = st.Executor()
        exe.run(prog, feed={"x": np.ones((2, 3), "float32")})
        assert set(scope.local_var_names()) == names_after_trace


def test_crf_decoding_respects_length():
    rng = np.random.RandomState(3)
    n = 4
    for _ in range(10):
        emis = rng.randn(2, 6, n).astype("float32")
        trans = rng.randn(n + 2, n).astype("float32")
        full = np.asarray(st.nn.crf_decoding(emis[:1, :3], trans))
        masked = np.asarray(st.nn.crf_decoding(
            np.concatenate([emis[:1], emis[1:]], 0), trans,
            length=np.array([3, 6])))
        np.testing.assert_array_equal(masked[0, :3], full[0])


def test_multi_box_head_nonsquare_heights():
    feats = [paddle.to_tensor(np.zeros((1, 4, 4, 4), dtype="float32"))]
    img = paddle.to_tensor(np.zeros((1, 3, 100, 200), dtype="float32"))
    _, _, boxes, _ = st.nn.multi_box_head(
        feats, img, base_size=100, num_classes=2, aspect_ratios=[[1.0]],
        min_sizes=[20.0], max_sizes=[40.0], flip=False, name="mbh_ns")
    b = np.asarray(boxes)
    # first prior of the first cell: square min_size box
    w = b[0, 2] - b[0, 0]
    h = b[0, 3] - b[0, 1]
    assert abs(w - 20.0 / 200) < 1e-6
    assert abs(h - 20.0 / 100) < 1e-6


def test_two_programs_do_not_collide_on_auto_names():
    # regression: first trace must COMMIT its name-counter advance
    scope = st.Scope()
    with st.scope_guard(scope):
        p1 = st.Program.trace(lambda x: st.nn.fc(x, 4), st.data("x", [2, 3]))
        p2 = st.Program.trace(lambda x: st.nn.fc(x, 8), st.data("x", [2, 5]))
        o1 = st.Executor().run(p1, feed={"x": np.ones((2, 3), "float32")})[0]
        o2 = st.Executor().run(p2, feed={"x": np.ones((2, 5), "float32")})[0]
        assert o1.shape == (2, 4) and o2.shape == (2, 8)


def test_executor_rebinds_to_current_scope():
    # regression: compiled cache must be per-scope, not first-scope-wins
    a, b = st.Scope(), st.Scope()
    feed = {"x": np.ones((2, 3), "float32")}
    with st.scope_guard(a):
        prog = st.Program.trace(
            lambda x: st.nn.fc(x, 4, name="sc_fc", bias_attr=False),
            st.data("x", [2, 3]))
        exe = st.Executor()
        out_a = exe.run(prog, feed=feed)[0]
    with st.scope_guard(b):
        b.var("sc_fc.w_0", jnp.zeros((3, 4), jnp.float32))
        out_b = exe.run(prog, feed=feed)[0]
    np.testing.assert_allclose(out_b, np.zeros((2, 4)))
    assert not np.allclose(out_a, out_b)
