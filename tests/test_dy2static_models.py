"""Dy2static FULL-MODEL tier, ported from the reference's
dygraph_to_static suite (round-5 verdict item 7): the conversion
acceptance models — BERT, Transformer, seq2seq, ResNet — plus the model
zoo scenarios around them. Each test names its reference file.

The acceptance contract (reference test_bert.py/test_resnet.py et al.):
training ONE STEP through the converted model produces the same losses
and parameters as eager execution. Conversion must actually happen —
to_static's stage-the-original fallback warning is promoted to an error
inside ``_convert``.
"""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu import autograd
from paddle_tpu.jit.dy2static import convert_function

RS = np.random.RandomState(7)


def _convert(m):
    """to_static with the conversion-failure fallback made fatal; train
    through the underlying layer whose forward is now the converted
    function (TracedLayer snapshots params, which a training loop must
    not use)."""
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        tl = paddle.jit.to_static(m)
    return tl.layer


def _train_parity(build, loss_of, steps=2, lr=0.05, rtol=1e-4,
                  atol=1e-5, opt_of=None):
    """losses + a parameter after `steps` of eager vs converted."""
    def run(convert):
        paddle.seed(0)
        m = build()
        if convert:
            m = _convert(m)
        opt = (opt_of(m) if opt_of is not None
               else paddle.optimizer.SGD(lr, parameters=m.parameters()))
        losses = []
        for _ in range(steps):
            opt.clear_grad()
            # eager forward for the recorded loss (the backward closure
            # re-runs under the grad trace — its value is a tracer)
            losses.append(float(loss_of(m)))
            autograd.backward(m, lambda: loss_of(m))
            opt.step()
        p0 = next(iter(m.parameters()))
        return losses, np.asarray(p0.value, np.float32)

    el, ep = run(False)
    cl, cp = run(True)
    np.testing.assert_allclose(el, cl, rtol=rtol, atol=atol)
    np.testing.assert_allclose(ep, cp, rtol=rtol, atol=atol)
    assert np.isfinite(el).all()


def _ce(logits, labels):
    logits = logits.reshape(-1, logits.shape[-1])
    return jnp.mean(nn.functional.cross_entropy(
        logits, labels.reshape(-1).astype("int64")))


# -- test_resnet.py / test_resnet_v2.py --------------------------------------
class TestResNet:
    @pytest.mark.slow
    def test_resnet18_forward_parity_under_jit(self):
        # ref: test_resnet.py ResNet conversion (full zoo model)
        from paddle_tpu.vision.models import resnet18
        x = jnp.asarray(RS.randn(2, 3, 32, 32), jnp.float32)
        paddle.seed(0)
        e = resnet18()
        e.eval()
        paddle.seed(0)
        c = resnet18()
        c.eval()
        c = _convert(c)
        np.testing.assert_allclose(
            np.asarray(e(x)), np.asarray(jax.jit(lambda z: c(z))(x)),
            rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_resnet18_train_one_step(self):
        # ref: test_resnet.py train_one_step static == dygraph
        from paddle_tpu.vision.models import resnet18
        x = jnp.asarray(RS.randn(2, 3, 32, 32), jnp.float32)
        y = jnp.asarray(RS.randint(0, 10, (2,)), jnp.int32)
        _train_parity(
            lambda: resnet18(num_classes=10),
            lambda m: _ce(m(x), y), steps=2, lr=0.01,
            rtol=5e-4, atol=5e-5)


# -- test_bert.py ------------------------------------------------------------
class TestBert:
    CFG = dict(tensor_parallel=False, vocab_size=128, hidden_size=32,
               num_layers=2, num_heads=2, max_position_embeddings=16,
               attn_dropout=0.0, hidden_dropout=0.0)

    @pytest.mark.slow
    def test_bert_pretraining_train_one_step(self):
        # ref: test_bert.py train_static == train_dygraph (MLM+NSP)
        from paddle_tpu.text.models import BertForPretraining
        ids = jnp.asarray(RS.randint(0, 128, (2, 16)), jnp.int32)
        mlm = np.full((2, 16), -100, "int32")
        mlm[:, ::4] = RS.randint(0, 128, (2, 4))
        mlm = jnp.asarray(mlm)
        nsp = jnp.asarray(RS.randint(0, 2, (2,)), jnp.int32)

        def loss_of(m):
            mlm_logits, nsp_logits = m(ids)
            return m.loss(mlm_logits, nsp_logits, mlm, nsp)

        _train_parity(
            lambda: BertForPretraining(**self.CFG), loss_of,
            steps=2, lr=1e-3, rtol=5e-4, atol=5e-5,
            opt_of=lambda m: paddle.optimizer.AdamW(
                1e-3, parameters=m.parameters()))


# -- test_transformer.py -----------------------------------------------------
class TestTransformer:
    def test_mt_transformer_train_one_step(self):
        # ref: test_transformer.py train_static_vs_dygraph
        from paddle_tpu.text.models import TransformerModel
        src = jnp.asarray(RS.randint(2, 64, (2, 8)), jnp.int32)
        trg = jnp.asarray(RS.randint(2, 64, (2, 8)), jnp.int32)
        lbl = jnp.asarray(RS.randint(2, 64, (2, 8)), jnp.int32)

        def build():
            return TransformerModel(
                src_vocab_size=64, trg_vocab_size=64, max_length=16,
                num_encoder_layers=2, num_decoder_layers=2, n_head=2,
                d_model=32, d_inner_hid=64, dropout=0.0)

        _train_parity(build, lambda m: _ce(m(src, trg), lbl),
                      steps=2, lr=0.01, rtol=5e-4, atol=5e-5)


# -- test_seq2seq.py (seq2seq_dygraph_model.py BaseModel) --------------------
class Seq2Seq(nn.Layer):
    """LSTM encoder-decoder with teacher forcing — the reference
    BaseModel's shape (seq2seq_dygraph_model.py:66), decoder unrolled
    with a Python loop the converter stages."""

    def __init__(self, vocab=64, hidden=32):
        super().__init__()
        self.src_emb = nn.Embedding(vocab, hidden)
        self.trg_emb = nn.Embedding(vocab, hidden)
        self.enc = nn.LSTM(hidden, hidden)
        self.dec_cell = nn.LSTMCell(hidden, hidden)
        self.head = nn.Linear(hidden, vocab)

    def forward(self, src, trg):
        enc_out, (h, c) = self.enc(self.src_emb(src))
        h, c = h[0], c[0]
        logits = []
        for t in range(trg.shape[1]):     # teacher-forced decode loop
            step_in = self.trg_emb(trg[:, t])
            _, (h, c) = self.dec_cell(step_in, (h, c))
            logits.append(self.head(h))
        return jnp.stack(logits, axis=1)


class TestSeq2Seq:
    def test_seq2seq_train_one_step(self):
        # ref: test_seq2seq.py train_dygraph == train_static
        src = jnp.asarray(RS.randint(2, 64, (2, 6)), jnp.int32)
        trg = jnp.asarray(RS.randint(2, 64, (2, 5)), jnp.int32)
        lbl = jnp.asarray(RS.randint(2, 64, (2, 5)), jnp.int32)
        _train_parity(Seq2Seq, lambda m: _ce(m(src, trg), lbl),
                      steps=2, lr=0.05, rtol=5e-4, atol=5e-5)


# -- test_ptb_lm.py / test_ptb_lm_v2.py --------------------------------------
class TestPtbLm:
    def test_lstm_lm_train_one_step(self):
        # ref: test_ptb_lm.py PtbModel train parity
        class PtbLm(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(64, 32)
                self.lstm = nn.LSTM(32, 32, num_layers=2)
                self.head = nn.Linear(32, 64)

            def forward(self, ids):
                h, _ = self.lstm(self.emb(ids))
                return self.head(h)

        ids = jnp.asarray(RS.randint(0, 64, (2, 8)), jnp.int32)
        lbl = jnp.asarray(RS.randint(0, 64, (2, 8)), jnp.int32)
        _train_parity(PtbLm, lambda m: _ce(m(ids), lbl),
                      steps=2, lr=0.05, rtol=5e-4, atol=5e-5)


# -- test_se_resnet.py -------------------------------------------------------
class TestSeResNet:
    def test_se_block_train_one_step(self):
        # ref: test_se_resnet.py SqueezeExcitation conv block
        class SeNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(3, 8, 3, padding=1)
                self.fc1 = nn.Linear(8, 4)
                self.fc2 = nn.Linear(4, 8)
                self.head = nn.Linear(8, 5)

            def forward(self, x):
                f = nn.functional.relu(self.conv(x))
                s = jnp.mean(f, axis=(2, 3))          # squeeze
                e = jax.nn.sigmoid(self.fc2(
                    nn.functional.relu(self.fc1(s))))  # excitation
                f = f * e[:, :, None, None]
                return self.head(jnp.mean(f, axis=(2, 3)))

        x = jnp.asarray(RS.randn(2, 3, 8, 8), jnp.float32)
        y = jnp.asarray(RS.randint(0, 5, (2,)), jnp.int32)
        _train_parity(SeNet, lambda m: _ce(m(x), y), steps=2)


# -- test_mobile_net.py ------------------------------------------------------
class TestMobileNet:
    def test_depthwise_separable_train_one_step(self):
        # ref: test_mobile_net.py depthwise_separable conv stack
        class DwNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.dw = nn.Conv2D(4, 4, 3, padding=1, groups=4)
                self.pw = nn.Conv2D(4, 8, 1)
                self.head = nn.Linear(8, 3)

            def forward(self, x):
                h = nn.functional.relu(self.dw(x))
                h = nn.functional.relu(self.pw(h))
                return self.head(jnp.mean(h, axis=(2, 3)))

        x = jnp.asarray(RS.randn(2, 4, 8, 8), jnp.float32)
        y = jnp.asarray(RS.randint(0, 3, (2,)), jnp.int32)
        _train_parity(DwNet, lambda m: _ce(m(x), y), steps=2)


# -- test_word2vec.py --------------------------------------------------------
class TestWord2Vec:
    def test_skipgram_train_one_step(self):
        # ref: test_word2vec.py SkipGram (center/context embedding dot)
        class SkipGram(nn.Layer):
            def __init__(self):
                super().__init__()
                self.center = nn.Embedding(64, 16)
                self.context = nn.Embedding(64, 16)

            def forward(self, c, o, neg):
                ce = self.center(c)
                pos = jnp.sum(ce * self.context(o), -1)
                negs = jnp.einsum("bd,bkd->bk", ce, self.context(neg))
                return pos, negs

        c = jnp.asarray(RS.randint(0, 64, (8,)), jnp.int32)
        o = jnp.asarray(RS.randint(0, 64, (8,)), jnp.int32)
        neg = jnp.asarray(RS.randint(0, 64, (8, 3)), jnp.int32)

        def nce(m):
            pos, negs = m(c, o, neg)
            return jnp.mean(jax.nn.softplus(-pos)) + \
                jnp.mean(jax.nn.softplus(negs))

        _train_parity(SkipGram, nce, steps=2, lr=0.1)


# -- test_sentiment.py -------------------------------------------------------
class TestSentiment:
    def test_cnn_classifier_train_one_step(self):
        # ref: test_sentiment.py CNN text classifier
        class TextCnn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(64, 16)
                self.conv = nn.Conv2D(1, 8, (3, 16))
                self.head = nn.Linear(8, 2)

            def forward(self, ids):
                e = self.emb(ids)[:, None]            # (b, 1, L, E)
                h = nn.functional.relu(self.conv(e))[..., 0]
                return self.head(jnp.max(h, axis=-1))

        ids = jnp.asarray(RS.randint(0, 64, (4, 12)), jnp.int32)
        y = jnp.asarray(RS.randint(0, 2, (4,)), jnp.int32)
        _train_parity(TextCnn, lambda m: _ce(m(ids), y), steps=2)


# -- test_simnet.py / test_simnet_v2.py --------------------------------------
class TestSimNet:
    def test_two_tower_hinge_train_one_step(self):
        # ref: test_simnet.py BOW towers + hinge loss on cosine sims
        class SimNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(64, 16)   # shared tower
                self.proj = nn.Linear(16, 16)

            def tower(self, ids):
                v = self.proj(jnp.mean(self.emb(ids), axis=1))
                return v / (jnp.linalg.norm(v, axis=-1,
                                            keepdims=True) + 1e-6)

            def forward(self, q, p, n):
                tq = self.tower(q)
                return (jnp.sum(tq * self.tower(p), -1),
                        jnp.sum(tq * self.tower(n), -1))

        q = jnp.asarray(RS.randint(0, 64, (4, 6)), jnp.int32)
        p = jnp.asarray(RS.randint(0, 64, (4, 6)), jnp.int32)
        n = jnp.asarray(RS.randint(0, 64, (4, 6)), jnp.int32)

        def hinge(m):
            sp, sn = m(q, p, n)
            return jnp.mean(jnp.maximum(0.0, 0.5 - sp + sn))

        _train_parity(SimNet, hinge, steps=2, lr=0.1)


# -- test_break_continue.py --------------------------------------------------
class TestBreakContinue:
    def test_python_loop_break_continue(self):
        # ref: test_break_continue.py test_break_in_for_loop
        def fn(x):
            total = jnp.zeros(())
            for i in range(6):
                if i == 4:
                    break
                if i % 2 == 1:
                    continue
                total = total + jnp.sum(x) * i
            return total

        x = jnp.asarray(RS.randn(3).astype("float32"))
        e = fn(x)
        c = jax.jit(convert_function(fn))(x)
        np.testing.assert_allclose(np.asarray(e), np.asarray(c),
                                   rtol=1e-6)


class TestUnrollDiagnostics:
    """The static-trip-count unroll path's failure modes are located
    diagnostics, not raw jax errors (round-5 review findings)."""

    def test_unroll_cap_diagnostic(self):
        from paddle_tpu.jit.dy2static import Dy2StaticError

        def fn(x):
            acc = []
            s = x                     # traced carry -> the peel path
            i = 0
            while i < 600:            # > _UNROLL_CAP with growing carry
                acc.append(s)
                s = s * 1.01
                i = i + 1
            return acc[-1]

        with pytest.raises(Dy2StaticError, match="unroll cap"):
            jax.jit(convert_function(fn))(jnp.ones(2))

    def test_read_before_assignment_located(self):
        from paddle_tpu.jit.dy2static import Dy2StaticError

        def fn(x):
            s = x
            n = 0
            while n < 3:
                acc = acc + [s]       # noqa: F821 — read before assign
                s = s * 2.0
                n = n + 1
            return acc

        with pytest.raises(Dy2StaticError, match="read before"):
            jax.jit(convert_function(fn))(jnp.ones(2))


# -- test_declarative.py -----------------------------------------------------
class TestDeclarative:
    def test_enable_to_static_toggle(self):
        # ref: test_declarative.py + program_translator enable flag
        from paddle_tpu.jit import ProgramTranslator

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, x):
                out = self.fc(x)
                if jnp.sum(x) > 1e9:   # converted tensor-dependent if
                    out = out * 0.0
                return out

        x = jnp.asarray(RS.randn(2, 4), jnp.float32)
        paddle.seed(0)
        m1 = M()
        out_static = paddle.jit.to_static(m1)(x)
        try:
            ProgramTranslator().enable(False)
            paddle.seed(0)
            m2 = M()
            assert paddle.jit.to_static(m2) is m2  # disabled: no wrap
            np.testing.assert_allclose(np.asarray(out_static),
                                       np.asarray(m2(x)), rtol=1e-5)
        finally:
            ProgramTranslator().enable(True)


# -- test_cache_program.py ---------------------------------------------------
class TestCacheProgram:
    def test_traced_layer_tracks_param_updates(self):
        # ref: test_cache_program.py — the cached program must see
        # parameter UPDATES (cache keys on code, not stale weights)
        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, x):
                return self.fc(x)

        paddle.seed(0)
        m = M()
        tl = paddle.jit.to_static(m)
        x = jnp.asarray(RS.randn(2, 4), jnp.float32)
        out1 = np.asarray(tl(x))
        m.fc.weight.value = m.fc.weight.value + 1.0
        tl.refresh_state()
        out2 = np.asarray(tl(x))
        assert not np.allclose(out1, out2)
        np.testing.assert_allclose(out2, np.asarray(m(x)), rtol=1e-5)


# -- test_save_inference_model.py --------------------------------------------
class TestSaveInferenceModel:
    def test_converted_model_saves_and_loads(self, tmp_path):
        # ref: test_save_inference_model.py — to_static -> save -> load
        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, x):
                out = nn.functional.relu(self.fc(x))
                if jnp.mean(x) > 1e9:      # converted control flow
                    out = out * 0.0
                return out

        from paddle_tpu.jit import InputSpec
        paddle.seed(0)
        m = M()
        m.eval()
        cm = _convert(m)
        path = str(tmp_path / "conv_model")
        paddle.jit.save(cm, path, input_spec=[InputSpec([2, 4])])
        loaded = paddle.jit.load(path)
        x = jnp.asarray(RS.randn(2, 4), jnp.float32)
        np.testing.assert_allclose(np.asarray(cm(x)),
                                   np.asarray(loaded(x)), rtol=1e-5)
