"""paddle.text.datasets (reference: python/paddle/text/datasets/ —
Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16, Conll05st).

Same class names, constructor surfaces, archive formats and sample tuples
as the reference; parsing is re-implemented. Zero-egress: pass
``data_file=`` pointing at a local archive, or rely on the download cache
(utils/download.py raises a clear error when the network is unreachable).
"""
from .conll05 import Conll05st  # noqa: F401
from .imdb import Imdb  # noqa: F401
from .imikolov import Imikolov  # noqa: F401
from .movielens import Movielens  # noqa: F401
from .uci_housing import UCIHousing  # noqa: F401
from .wmt14 import WMT14  # noqa: F401
from .wmt16 import WMT16  # noqa: F401

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]
