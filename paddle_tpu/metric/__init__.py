"""Metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing done on-device inside the jitted eval step."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = jnp.argsort(pred, axis=-1)[..., ::-1][..., :self.maxk]
        if label.ndim == 1:
            label = label.reshape(-1, 1)
        elif label.shape[-1] != 1:
            label = jnp.argmax(label, axis=-1, keepdims=True)
        return (pred == label).astype(jnp.float32)

    def update(self, correct, *args):
        correct = np.asarray(correct)
        accs = []
        for k in self.topk:
            num_corrects = correct[..., :k].sum()
            num_samples = int(np.prod(correct.shape[:-1]))
            accs.append(float(num_corrects) / num_samples if num_samples else 0.0)
            self.total[self.topk.index(k)] += float(num_corrects)
            self.count[self.topk.index(k)] += num_samples
        return accs[0] if len(self.topk) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels)
        preds = np.rint(preds).astype(np.int32).reshape(-1)
        labels = labels.astype(np.int32).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int32).reshape(-1)
        labels = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args, **kwargs):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
            else preds.reshape(-1)
        bins = np.minimum((pos_prob * self.num_thresholds).astype(np.int64),
                          self.num_thresholds - 1)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, dtype=np.int64)

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds - 1, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return auc / denom if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference: fluid/layers/metric_op.py)."""
    import jax
    _, idx = jax.lax.top_k(input, k)
    if label.ndim == 1:
        label = label[:, None]
    correct_ = jnp.any(idx == label, axis=-1)
    return jnp.mean(correct_.astype(jnp.float32))
