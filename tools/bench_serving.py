"""Serving bench: synthetic Poisson open-loop traffic against the
inference serving runtime (paddle_tpu/inference/serving.py).

One server (two Predictor replicas over a jit.save'd MLP, per-prefix
load cache shared) is driven through four phases and ONE JSON line is
printed:

- **warmup** — sequential requests at every batch-bucket size, so the
  compiled-program set is established (``serving_recompiles_total``
  recorded here must NOT grow afterwards — shape buckets closed).
- **baseline** — Poisson arrivals at 0.5x nominal capacity: the
  no-overload goodput / latency reference.
- **overload** — Poisson at 2x capacity: admission control must shed
  (``requests_shed_total > 0``) while the p99 latency of requests that
  completed stays within the deadline, and in-deadline goodput stays
  within a bounded band of the baseline run.
- **failover** — a ``replica_stall`` fault wedges one replica mid-run:
  the per-call deadline fires, the batch requeues to the survivor
  (``replica_failover_total >= 1``), and ZERO admitted-and-feasible
  requests are silently lost — every submitted request terminates as
  completed / shed / expired (``accounted``).

Capacity is made deterministic on any machine by padding each batch
execute with a fixed service time (the model itself is tiny), so
"2x capacity" means the same thing in CI and on a workstation.

Usage::

    python tools/bench_serving.py            # full (longer phases)
    python tools/bench_serving.py --smoke    # CI contract (~20 s)

    {"metric": "serving_overload_goodput_rps", "value": ...,
     "extra": {"requests_shed_total": ..., "replica_failover_total": ...,
               "serving_recompiles_total": {"closed": true, ...}, ...}}
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time

import numpy as np

from _mesh_setup import ensure_repo_on_path, force_host_devices

ensure_repo_on_path()
force_host_devices(1)

IN_DIM = 32


def build_model(tmp: str):
    """jit.save a tiny MLP with a shape-polymorphic batch dim (the
    serving batcher pads rows to a small bucket set)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit import InputSpec

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(IN_DIM, 64)
            self.fc2 = nn.Linear(64, 8)

        def forward(self, x):
            return self.fc2(nn.functional.relu(self.fc1(x)))

    paddle.seed(0)
    net = MLP()
    net.eval()
    prefix = tmp + "/model"
    paddle.jit.save(net, prefix, input_spec=[InputSpec([None, IN_DIM],
                                                       "float32")])
    return prefix


def make_executor(pred, pad_s: float):
    """Predictor executor with a fixed per-batch service pad, so nominal
    capacity (= replicas * max_batch / pad) is machine-independent."""

    def fn(arrays):
        out = pred.run(list(arrays))
        time.sleep(pad_s)
        return out

    return fn


def _diff(before: dict, after: dict) -> dict:
    out = {}
    for k, v in after.items():
        if isinstance(v, (int, float)) and isinstance(before.get(k), (int, float)):
            out[k] = v - before[k]
    for cause in set(after["shed_causes"]) | set(before["shed_causes"]):
        out.setdefault("shed_causes", {})[cause] = (
            after["shed_causes"].get(cause, 0)
            - before["shed_causes"].get(cause, 0))
    return out


def run_phase(server, rate_rps: float, duration_s: float,
              deadline_s: float, rng) -> dict:
    """Open-loop Poisson traffic: arrivals are scheduled independently
    of completions (the defining property of an overload test — a
    closed loop would self-throttle)."""
    before = server.stats()
    reqs = []
    t0 = time.monotonic()
    next_t = t0
    while True:
        next_t += rng.exponential(1.0 / rate_rps)
        if next_t - t0 > duration_s:
            break
        lag = next_t - time.monotonic()
        if lag > 0:
            time.sleep(lag)
        x = rng.rand(1, IN_DIM).astype("float32")
        reqs.append(server.submit([x], deadline_s=deadline_s))
    elapsed = time.monotonic() - t0
    settle = time.monotonic() + deadline_s + 10.0
    for r in reqs:
        r._done.wait(max(0.0, settle - time.monotonic()))
    delta = _diff(before, server.stats())
    lat = sorted(r.latency for r in reqs
                 if r.state == "completed" and r.latency is not None)
    in_deadline = sum(1 for r in reqs if r.state == "completed"
                      and r.latency is not None and r.latency <= deadline_s)

    def pct(p):
        return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else None

    return {
        "offered_rps": round(len(reqs) / elapsed, 1),
        "duration_s": round(elapsed, 3),
        "submitted": len(reqs),
        "completed": delta.get("completed", 0),
        "shed": delta.get("shed", 0),
        "expired": delta.get("expired", 0),
        "failed": delta.get("failed", 0),
        "shed_causes": delta.get("shed_causes", {}),
        "failovers": delta.get("failovers", 0),
        "deadline_s": deadline_s,
        "p50_s": pct(0.50),
        "p99_s": pct(0.99),
        "goodput_rps": round(in_deadline / elapsed, 1),
    }


def run_bench(smoke: bool, seed: int = 0) -> dict:
    from paddle_tpu import inference, telemetry
    from paddle_tpu.inference import serving
    from paddle_tpu.resilience import faults

    telemetry.enable()
    replicas, max_batch = 2, 4
    pad_s = 0.04
    capacity = replicas * max_batch / pad_s          # nominal rows/sec
    deadline_s = 0.4
    duration = 1.5 if smoke else 6.0

    tmp = tempfile.mkdtemp(prefix="bench_serving_")
    prefix = build_model(tmp)
    cfg = inference.Config(prefix)
    pool = inference.PredictorPool(cfg, replicas)
    scfg = serving.ServingConfig(
        max_queue=512, max_batch=max_batch, batch_wait_s=0.004,
        call_timeout_s=0.5, admission_safety=1.3, probation_base_s=0.05,
        probation_max_s=0.5, seed=seed)
    server = serving.InferenceServer(
        [make_executor(pool.retrieve(i), pad_s) for i in range(replicas)],
        config=scfg)
    rng = np.random.RandomState(seed)

    with server:
        # -- warmup: touch every batch bucket so the compiled set closes
        for rows in range(1, max_batch + 1):
            server.submit([rng.rand(rows, IN_DIM).astype("float32")],
                          deadline_s=30.0).result(timeout=60)
        recompiles_warm = server.stats()["recompiles"]

        baseline = run_phase(server, 0.5 * capacity, duration,
                             deadline_s, rng)
        overload = run_phase(server, 2.0 * capacity, duration,
                             deadline_s, rng)

        # -- failover: wedge one replica a few batches into the phase
        stall_at = server.stats()["batches"] + 4
        with faults.inject("replica_stall", at_step=stall_at) as spec:
            failover = run_phase(server, 0.6 * capacity,
                                 max(duration, 2.0), 2.0, rng)
        failover["stall_fired"] = spec.fired
        stats = server.stats()
        recompiles_final = stats["recompiles"]
        accounted = server.accounted()
        server.shutdown(drain=True)

    shed_total = (overload["shed"] + overload["expired"])
    goodput_band_ok = (
        baseline["goodput_rps"] > 0
        and overload["goodput_rps"] >= 0.5 * baseline["goodput_rps"])
    checks = {
        "overload_sheds": shed_total > 0,
        "overload_p99_within_deadline": (
            overload["p99_s"] is not None
            and overload["p99_s"] <= deadline_s),
        "goodput_band": goodput_band_ok,
        "failover_happened": failover["failovers"] >= 1
        and failover["stall_fired"] == 1,
        "zero_requests_lost": accounted and failover["failed"] == 0,
        "buckets_closed": recompiles_final == recompiles_warm,
    }
    return {
        "metric": "serving_overload_goodput_rps",
        "value": overload["goodput_rps"],
        "unit": "req/s",
        "extra": {
            "smoke": smoke,
            "capacity_rps_nominal": capacity,
            "service_pad_s": pad_s,
            "replicas": replicas,
            "max_batch": max_batch,
            "baseline": baseline,
            "overload": overload,
            "failover": failover,
            "requests_shed_total": shed_total,
            "replica_failover_total": failover["failovers"],
            "serving_recompiles_total": {
                "after_warmup": recompiles_warm,
                "final": recompiles_final,
                "closed": checks["buckets_closed"],
            },
            "accounted": accounted,
            "stats": stats,
            "telemetry": {
                "prometheus_bytes": len(telemetry.prometheus_text()),
            },
            "checks": checks,
            "exit_code": 0 if all(checks.values()) else 1,
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="short phases + the CI self-check contract")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    res = run_bench(args.smoke, seed=args.seed)
    print(json.dumps(res))
    return res["extra"]["exit_code"]


if __name__ == "__main__":
    sys.exit(main())
