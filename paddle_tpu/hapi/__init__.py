"""paddle_tpu.hapi — high-level Model API (reference: python/paddle/hapi/)."""
from . import callbacks, model_summary  # noqa: F401
from .model import Model  # noqa: F401
